"""Model executor: owns params + paged KV cache on a device mesh and exposes
jitted prefill/decode steps with fused sampling.

Engine-tier component (the reference's analog is inside the absent xLLM
submodule; the service-visible contracts it must honor are the 128-token
block size and the KV-handle metadata relayed in InstanceMetaInfo —
SURVEY.md §2.3).

TPU design points:
  * one compiled decode step for a FIXED batch of R slots — batch
    composition changes never recompile (SURVEY.md §7 hard part 3);
  * prefill lengths are bucketed; each bucket compiles once;
  * KV caches are donated through every step (in-place update, no HBM copy);
  * sampling runs on-device inside the same jit — only R int32 tokens +
    R float32 logprobs cross back to the host per step;
  * params/caches carry NamedShardings from parallel/sharding.py; under
    multi-device meshes XLA emits the TP collectives.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from xllm_service_tpu.common.config import EngineConfig
from xllm_service_tpu.models import llama
from xllm_service_tpu.models.configs import ModelConfig, get_model_config
from xllm_service_tpu.ops import sampling as sampling_ops
from xllm_service_tpu.parallel.mesh import build_mesh
from xllm_service_tpu.parallel.sharding import (
    check_tp_divisibility,
    kv_cache_sharding,
    param_shardings,
)


@dataclass
class SamplingBatch:
    """Device-ready per-slot sampling params for the fixed decode batch."""

    temperature: np.ndarray  # [R] float32
    top_k: np.ndarray  # [R] int32
    top_p: np.ndarray  # [R] float32
    seeds: np.ndarray  # [R] uint32
    steps: np.ndarray  # [R] int32 (per-request generated-token count)


class ModelExecutor:
    def __init__(
        self,
        engine_cfg: EngineConfig,
        model_cfg: Optional[ModelConfig] = None,
        mesh: Optional[Mesh] = None,
        init_seed: int = 0,
    ):
        self.engine_cfg = engine_cfg
        self.cfg = model_cfg or get_model_config(engine_cfg.model)
        self.mesh = mesh or build_mesh(engine_cfg.dp_size, engine_cfg.tp_size)
        tp = self.mesh.shape.get("tp", 1)
        if tp > 1:
            check_tp_divisibility(self.cfg, tp)

        self.dtype = jnp.bfloat16 if engine_cfg.dtype == "bfloat16" else jnp.float32
        self.R = engine_cfg.max_running_requests
        self.block_size = engine_cfg.block_size
        self.num_blocks = self._decide_num_blocks()
        self.max_blocks_per_seq = math.ceil(
            engine_cfg.max_seq_len / self.block_size
        )

        p_shardings = param_shardings(self.cfg, self.mesh)
        kv_sharding = kv_cache_sharding(self.mesh)

        with self.mesh:
            if engine_cfg.checkpoint_path:
                from xllm_service_tpu.runtime.weights import load_checkpoint

                self.params = load_checkpoint(
                    engine_cfg.checkpoint_path, self.cfg, self.dtype, p_shardings
                )
            else:
                init_fn = jax.jit(
                    lambda key: llama.init_params(self.cfg, key, self.dtype),
                    out_shardings=p_shardings,
                )
                self.params = init_fn(jax.random.key(init_seed))

            # [L, N, Hkv, BS, D]: KV-head-major within a block so the Pallas
            # decode kernel can DMA one (block, head) tile of shape [BS, D]
            # with TPU-legal last-two-dims tiling.
            cache_shape = (
                self.cfg.num_layers,
                self.num_blocks,
                self.cfg.num_kv_heads,
                self.block_size,
                self.cfg.head_dim,
            )
            alloc = jax.jit(
                lambda: (
                    jnp.zeros(cache_shape, self.dtype),
                    jnp.zeros(cache_shape, self.dtype),
                ),
                out_shardings=(kv_sharding, kv_sharding),
            )
            self.k_cache, self.v_cache = alloc()

        self._decode_jit = jax.jit(
            self._decode_impl, donate_argnums=(0, 1), static_argnames=("use_kernel",)
        )
        self._prefill_jit = jax.jit(
            self._prefill_impl, donate_argnums=(0, 1)
        )
        self.prefill_buckets = sorted(
            b for b in engine_cfg.prefill_buckets if b <= engine_cfg.max_seq_len
        )
        # Buckets must cover max_seq_len so any admissible suffix fits.
        if not self.prefill_buckets or self.prefill_buckets[-1] < engine_cfg.max_seq_len:
            self.prefill_buckets.append(engine_cfg.max_seq_len)

    # ----------------------------------------------------------- sizing

    def _decide_num_blocks(self) -> int:
        if self.engine_cfg.num_blocks > 0:
            return self.engine_cfg.num_blocks
        # Size the KV pool from free HBM after params (bench/real use).
        cfg = self.cfg
        bytes_per_param = 2 if self.engine_cfg.dtype == "bfloat16" else 4
        E, L = cfg.hidden_size, cfg.num_layers
        F = cfg.moe_intermediate_size * cfg.num_experts if cfg.is_moe else cfg.intermediate_size
        n_params = (
            cfg.vocab_size * E * (1 if cfg.tie_word_embeddings else 2)
            + L * E * (cfg.num_heads + 2 * cfg.num_kv_heads) * cfg.head_dim
            + L * cfg.num_heads * cfg.head_dim * E
            + 3 * L * E * F
        )
        try:
            stats = jax.devices()[0].memory_stats() or {}
            total_hbm = stats.get("bytes_limit", 16 * 2**30)
        except Exception:
            total_hbm = 16 * 2**30
        tp = self.mesh.shape.get("tp", 1)
        # XLA's AOT peak-memory estimate counts donated KV caches on both
        # sides of the step, so budget for 2x the pool (params are not
        # donated and count once).
        budget = (
            total_hbm * self.engine_cfg.hbm_utilization
            - n_params * bytes_per_param / tp
        ) / 2
        block_bytes = (
            2
            * self.cfg.num_layers
            * self.block_size
            * (self.cfg.num_kv_heads // tp if self.cfg.num_kv_heads >= tp else self.cfg.num_kv_heads)
            * self.cfg.head_dim
            * bytes_per_param
        )
        n = int(budget // block_bytes)
        if n < 16:
            import warnings

            warnings.warn(
                f"KV pool auto-sizing collapsed to the 16-block floor "
                f"(budget {budget/2**30:.2f} GiB, block {block_bytes/2**20:.2f} "
                f"MiB): params leave almost no HBM headroom; expect thrashing",
                stacklevel=2,
            )
        return max(n, 16)

    # ------------------------------------------------------------ step fns

    def _decode_impl(
        self,
        k_cache,
        v_cache,
        params,
        token_ids,
        positions,
        block_tables,
        active,
        temperature,
        top_k,
        top_p,
        step_keys,
        use_kernel=None,
    ):
        logits, k_cache, v_cache = llama.decode_step(
            params,
            self.cfg,
            k_cache,
            v_cache,
            token_ids,
            positions,
            block_tables,
            active,
            use_kernel=use_kernel,
        )
        tokens, logprob, _ = sampling_ops.sample_tokens(
            logits, temperature, top_k, top_p, step_keys
        )
        return k_cache, v_cache, tokens, logprob

    def _prefill_impl(
        self,
        k_cache,
        v_cache,
        params,
        token_ids,
        start_pos,
        true_len,
        block_table,
        temperature,
        top_k,
        top_p,
        step_key,
    ):
        logits, k_cache, v_cache = llama.prefill_step(
            params, self.cfg, k_cache, v_cache, token_ids, start_pos, true_len,
            block_table,
        )
        tokens, logprob, _ = sampling_ops.sample_tokens(
            logits[None],
            temperature[None],
            top_k[None],
            top_p[None],
            step_key[None],
        )
        return k_cache, v_cache, tokens[0], logprob[0]

    # ---------------------------------------------------------- public API

    def bucket_len(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.prefill_buckets[-1]

    def prefill(
        self,
        token_ids: np.ndarray,  # [n] int32 — uncached suffix of the prompt
        start_pos: int,
        block_table: np.ndarray,  # [max_blocks_per_seq] int32
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        seed: int = 0,
        step: int = 0,
    ) -> Tuple[int, float]:
        n = len(token_ids)
        pad = self.bucket_len(n)
        padded = np.zeros((pad,), np.int32)
        padded[:n] = token_ids
        key = sampling_ops.make_step_keys(
            jnp.asarray([seed], jnp.uint32), jnp.int32(step)
        )[0]
        self.k_cache, self.v_cache, tok, lp = self._prefill_jit(
            self.k_cache,
            self.v_cache,
            self.params,
            jnp.asarray(padded),
            jnp.int32(start_pos),
            jnp.int32(n),
            jnp.asarray(block_table, jnp.int32),
            jnp.float32(temperature),
            jnp.int32(top_k),
            jnp.float32(top_p),
            key,
        )
        return int(tok), float(lp)

    def decode(
        self,
        token_ids: np.ndarray,  # [R]
        positions: np.ndarray,  # [R]
        block_tables: np.ndarray,  # [R, max_blocks_per_seq]
        active: np.ndarray,  # [R] bool
        batch: SamplingBatch,
        use_kernel: Optional[bool] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        keys = jax.vmap(
            lambda s, st: jax.random.key_data(
                jax.random.fold_in(jax.random.key(s), st)
            )
        )(jnp.asarray(batch.seeds, jnp.uint32), jnp.asarray(batch.steps, jnp.int32))
        self.k_cache, self.v_cache, tokens, logprobs = self._decode_jit(
            self.k_cache,
            self.v_cache,
            self.params,
            jnp.asarray(token_ids, jnp.int32),
            jnp.asarray(positions, jnp.int32),
            jnp.asarray(block_tables, jnp.int32),
            jnp.asarray(active),
            jnp.asarray(batch.temperature, jnp.float32),
            jnp.asarray(batch.top_k, jnp.int32),
            jnp.asarray(batch.top_p, jnp.float32),
            keys,
            use_kernel=use_kernel,
        )
        return np.asarray(tokens), np.asarray(logprobs)

    # ------------------------------------------------- KV block migration

    def export_blocks(self, block_ids: np.ndarray) -> jax.Array:
        """Gather KV blocks for migration to a peer instance (PD disagg).
        Returns [2, L, n, bs, Hkv, D] on device; the transfer layer moves it
        over ICI/DCN (jax.device_put to the peer mesh) or via host RPC."""
        ids = jnp.asarray(block_ids, jnp.int32)
        return jnp.stack([self.k_cache[:, ids], self.v_cache[:, ids]])

    def import_blocks(self, blocks: jax.Array, block_ids: np.ndarray) -> None:
        ids = jnp.asarray(block_ids, jnp.int32)
        self.k_cache = self.k_cache.at[:, ids].set(blocks[0].astype(self.dtype))
        self.v_cache = self.v_cache.at[:, ids].set(blocks[1].astype(self.dtype))
