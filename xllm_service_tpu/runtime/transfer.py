"""Cross-process device-to-device KV data plane.

The reference relays per-instance RDMA handles (cluster_ids / addrs /
k_cache_ids / v_cache_ids — xllm_service/common/types.h:174-177, proto
fields 37-40, served by GetInstanceInfo in rpc_service/service.cpp:74-105)
so a decode engine can pull prefilled KV straight out of the prefill
engine's device memory. The TPU-native analog is
`jax.experimental.transfer`: each instance runs one TransferServer bound to
its JAX client; the prefill side OFFERS a device array under a uuid, the
decode side PULLS it directly into its own device memory over the
transfer transport (DCN/ICI on real pods, TCP on CPU tests) — the payload
never stages through host memory on either side.

Wire protocol: the existing /kv/import control message carries a
`kv_pull` header ({addr, uuid, shape, dtype}) INSTEAD of body bytes; the
receiving handler pulls synchronously before acking, so the offer's
lifetime is bounded by the control round-trip and errors surface in the
HTTP response exactly like the bytes path.
"""

from __future__ import annotations

import itertools
import logging
import threading
from typing import Any, Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)


class KVTransferServer:
    """One per process: offers outgoing KV arrays and pulls incoming ones.

    Thread-safe; connections to peer servers are cached per address.
    """

    def __init__(self, listen: str = "127.0.0.1:0"):
        import jax
        from jax.experimental import transfer

        # local_devices, not devices: under jax.distributed the global
        # list starts with process 0's devices — a server/pull target on
        # any other host must address its OWN chips.
        self._client = jax.local_devices()[0].client
        # An explicit socket transport address is REQUIRED: with the
        # default (none), jaxlib routes same-host peers through its
        # "local bulk transport" registry, which only knows transports
        # created in THIS process — a pull from another process on the
        # same host then dies on a CHECK in streaming.cc
        # (LocalBulkTransportFactory::RecvBulkTransport).
        host = listen.rsplit(":", 1)[0] or "127.0.0.1"
        self._srv = transfer.start_transfer_server(
            self._client, listen, [f"{host}:0"]
        )
        self._mu = threading.Lock()
        self._conns: Dict[str, Any] = {}
        self._uuid = itertools.count(1)
        # Keep offered arrays (and their pull futures) alive until the
        # peer's pull completes — retract() drops the reference.
        self._pending: Dict[int, Any] = {}
        # retract_later timers by uuid: a clean ack after an errored
        # control path must CANCEL the timer and drop the offer NOW —
        # otherwise every such offer pins HBM for the full grace window
        # even though the peer's pull already completed.
        self._retract_timers: Dict[int, threading.Timer] = {}

    @property
    def address(self) -> str:
        return self._srv.address()

    def offer(self, arrays: Sequence[Any]) -> int:
        """Register device arrays for a one-shot pull; returns the uuid
        the peer pulls under."""
        with self._mu:
            uuid = next(self._uuid)
            self._pending[uuid] = (self._srv.await_pull(uuid, list(arrays)), arrays)
        return uuid

    def retract(self, uuid: int) -> None:
        """Drop an offer's keepalive (after the peer acked its pull, or on
        control-message failure). Cancels any retract_later timer still
        pending for the uuid."""
        with self._mu:
            self._pending.pop(uuid, None)
            timer = self._retract_timers.pop(uuid, None)
        if timer is not None:
            timer.cancel()

    def pull(self, addr: str, uuid: int, avals: Sequence[Any]) -> List[Any]:
        """Pull arrays offered under `uuid` from the server at `addr` into
        this process's devices. `avals` are jax.ShapeDtypeStruct with
        shardings on local devices. A failed pull evicts the peer's cached
        connection — a restarted peer must not keep receiving pulls over a
        dead cached transport."""
        with self._mu:
            conn = self._conns.get(addr)
        if conn is None:
            # Connect OUTSIDE the lock: establishing a transport to a
            # slow/dead peer must not stall every other thread's offer/
            # retract/pull on this server. A racing pull may connect
            # too; first insert wins, and a losing connector closes its
            # redundant transport and pulls over the cached winner.
            fresh = self._srv.connect(addr)
            with self._mu:
                conn = self._conns.setdefault(addr, fresh)
            if conn is not fresh:
                try:
                    close = getattr(fresh, "close", None)
                    if callable(close):
                        close()
                except Exception:
                    pass
        try:
            return conn.pull(uuid, list(avals))
        except Exception:
            with self._mu:
                if self._conns.get(addr) is conn:
                    del self._conns[addr]
            raise

    def pull_single(self, addr: str, uuid: int, shape, dtype,
                    sharding=None) -> Any:
        """Pull one array straight onto `sharding` — the consumer
        executor's migration_sharding for KV payloads, so a tp-sharded
        consumer lands the pull on its own kv_cache_sharding layout
        instead of bouncing through one device and resharding later
        (1-device consumers pass None and keep the old single-device
        landing). If the transfer transport cannot serve the sharded
        aval (older jaxlib), the pull falls back to the single-device
        landing and a `jax.device_put` onto `sharding` — still one
        device-side hop, never a host bounce."""
        import jax
        from jax.sharding import SingleDeviceSharding

        single = SingleDeviceSharding(jax.local_devices()[0])
        if sharding is not None:
            try:
                aval = jax.ShapeDtypeStruct(
                    tuple(shape), dtype, sharding=sharding
                )
                return self.pull(addr, uuid, [aval])[0]
            except (TypeError, ValueError, NotImplementedError):
                pass
        aval = jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=single)
        out = self.pull(addr, uuid, [aval])[0]
        if sharding is not None:
            out = jax.device_put(out, sharding)
        return out

    def retract_later(self, uuid: int, delay_s: float = 120.0) -> None:
        """Drop an offer's keepalive AFTER the peer's possible pull window
        (used when a control POST errored mid-flight: the peer may still
        be pulling, so an immediate retract could free the buffer under
        it). A later retract() for the same uuid cancels the timer and
        frees immediately."""
        t = threading.Timer(delay_s, self.retract, args=(uuid,))
        t.daemon = True
        with self._mu:
            old = self._retract_timers.pop(uuid, None)
            self._retract_timers[uuid] = t
        if old is not None:
            old.cancel()
        t.start()

    def open_offer_session(self) -> "KVOfferSession":
        """Group several offers (a pipelined PD handoff's chunks) under one
        session for bulk retraction on abort."""
        return KVOfferSession(self)


class KVOfferSession:
    """Multi-offer bookkeeping for one streaming handoff session: each
    chunk's arrays are offered independently (the peer pulls them as its
    /kv/import control messages land, asynchronously w.r.t. later chunks),
    and an abort retracts everything still pending in one sweep."""

    def __init__(self, server: KVTransferServer):
        self._server = server
        self._mu = threading.Lock()
        self._uuids: List[int] = []

    def offer(self, arrays: Sequence[Any]) -> int:
        uuid = self._server.offer(arrays)
        with self._mu:
            self._uuids.append(uuid)
        return uuid

    def retract(self, uuid: int) -> None:
        """One chunk's pull completed (clean control ack): drop its offer
        now, keep the rest of the session alive."""
        self._server.retract(uuid)
        self.forget(uuid)

    def forget(self, uuid: int) -> None:
        """Remove a uuid from the session WITHOUT touching its offer —
        for offers whose lifetime was handed to a server-level grace
        timer (errored control path): a later session-wide retract_all
        must not cancel that timer and free the buffer mid-pull."""
        with self._mu:
            try:
                self._uuids.remove(uuid)
            except ValueError:
                pass

    def retract_all_later(self, delay_s: float = 120.0) -> None:
        """Session abort with chunks possibly still being pulled: give
        every outstanding offer the grace window, then free."""
        with self._mu:
            uuids, self._uuids = self._uuids, []
        for uuid in uuids:
            self._server.retract_later(uuid, delay_s)

    def retract_all(self) -> None:
        with self._mu:
            uuids, self._uuids = self._uuids, []
        for uuid in uuids:
            self._server.retract(uuid)


_PROCESS_SERVER: Optional[KVTransferServer] = None
_PROCESS_MU = threading.Lock()


def get_transfer_server(listen: str = "127.0.0.1:0") -> KVTransferServer:
    """Process-wide singleton (a TransferServer binds per-client transport
    resources; instances in one process share it)."""
    global _PROCESS_SERVER
    with _PROCESS_MU:
        if _PROCESS_SERVER is None:
            _PROCESS_SERVER = KVTransferServer(listen)
        return _PROCESS_SERVER
