"""Instance side of the fleet-wide prefix KV fabric (docs/KV_CACHE.md).

Three duties, mixed into InstanceServer (`self` is the server):

  * **Requester** — a forwarded request carrying the master's `kv_fabric`
    hint starts `_fabric_prefetch`: compute the prompt's chained block
    hashes, count what is already held locally on any tier, and pull the
    missing matched range from the holding peer over `POST /kv/fetch`.
    The fetch runs on a daemon thread WHILE the engine chunk-prefills the
    uncovered tail; landed blocks are adopted at the next chunk boundary
    (engine `_extend_midchunk_match`). Any failure — peer death, timeout,
    shape mismatch, fault injection — only costs recompute, never an
    error. Anti-stampede: concurrent requests missing the same first
    block share ONE fetch (the rest count `dedup_waits` and proceed;
    their chunk boundaries pick the blocks up when they land).
  * **Holder** — `/kv/fetch` serves requested hashes from any local tier
    via `engine.export_cached_blocks` (engine-thread export; a torn
    off-thread read of an evicting block can never ship).
  * **Evictor** — the engine's `on_cold_evict` hook lands here when a
    block leaves the last local tier: the offer worker batches hashes to
    the master's `/rpc/fabric/evict_offer`, and blocks the master marks
    "send" are POSTed to the chosen peer's /kv/import (`fabric_blocks`
    frames) so the last fleet replica of a hot prefix survives local
    pressure. A dropped offer (chaos, full queue, master gone) just lets
    the block die — the index retraction was already queued.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from xllm_service_tpu.api.http_utils import (
    HttpJsonApi,
    post_bytes,
    post_bytes_raw,
    post_json,
)
from xllm_service_tpu.api.protocol import (
    kv_frame_array,
    kv_frame_split,
    kv_frame_to_bytes,
)
from xllm_service_tpu.common import faults
from xllm_service_tpu.common.hashing import prefix_block_hashes
from xllm_service_tpu.cluster.prefix_fabric import fabric_enabled

logger = logging.getLogger("xllm_service_tpu.api.instance")

# Bounds: one fetch round-trip moves at most this many blocks (a huge
# shared prefix fetches its head; the tail recomputes or refetches on the
# next request), and the holder-side export waits at most this long for
# the engine thread (a wedged engine must not pin HTTP workers).
FETCH_MAX_BLOCKS = 2048
FETCH_TIMEOUT_S = 30.0
EXPORT_WAIT_S = 10.0
# Evict-offer queue bound: under a host-tier eviction storm the fabric
# sheds offers (blocks die locally, exactly as without the fabric) rather
# than buffering unbounded host KV copies.
EVICT_QUEUE_CAP = 64
# Concurrent-fetch cap: each in-flight fetch is one daemon thread
# buffering up to FETCH_MAX_BLOCKS of KV for up to FETCH_TIMEOUT_S —
# the cap bounds both. A request arriving past the cap simply recomputes
# (the universal fabric fallback).
FETCH_MAX_CONCURRENT = 8


class FabricMixin:
    def _init_fabric(self) -> None:  # graftlint: init-only
        """Fabric state + observability. Called from InstanceServer
        .__init__ once self.metrics and self.engine exist."""
        from xllm_service_tpu.obs import LATENCY_BUCKETS_MS

        self._fabric_mu = threading.Lock()
        # first-missing-hash -> in-flight marker (anti-stampede dedup).
        self._fabric_inflight: Dict[bytes, bool] = {}
        self._fabric_evict_q: "queue.Queue" = queue.Queue(
            maxsize=EVICT_QUEUE_CAP
        )
        self._fabric_evict_thread = None
        # Offers accepted into the queue but not yet fully processed
        # (batch HTTP round-trips included). fabric_evict_quiesce waits
        # on this instead of sleep/polling the metrics counter — the
        # PR-12-flagged evict-offer e2e race was an offers0 snapshot
        # taken while phase-1 offers were still in flight.
        self._fabric_evict_cond = threading.Condition()
        self._fabric_evict_pending = 0  # guarded by: self._fabric_evict_cond
        self._m_fabric_fetches = self.metrics.counter(
            "xllm_fabric_fetches_total",
            "Peer prefix fetches started (requester side)",
        )
        self._m_fabric_fetch_blocks = self.metrics.counter(
            "xllm_fabric_fetch_blocks_total",
            "KV blocks landed from peer prefix fetches",
        )
        self._m_fabric_fetch_aborts = self.metrics.counter(
            "xllm_fabric_fetch_aborts_total",
            "Peer prefix fetches that failed or timed out (the request "
            "recomputes the gap — never an error)",
        )
        self._m_fabric_evict_offers = self.metrics.counter(
            "xllm_fabric_evict_offers_total",
            "Last-replica blocks re-homed onto a peer's cache by the "
            "coordinated eviction tier",
        )
        self._m_fabric_dedup = self.metrics.counter(
            "xllm_fabric_dedup_waits_total",
            "Requests that piggybacked on an identical in-flight prefix "
            "fetch instead of starting their own (anti-stampede)",
        )
        self._m_fabric_fetch_ms = self.metrics.histogram(
            "xllm_fabric_fetch_ms",
            "Peer prefix fetch: request start to blocks landed",
            buckets=LATENCY_BUCKETS_MS,
        )
        # Coordinated eviction needs a real engine (host tier + block
        # manager) and a master to ask; wire the hook only then.
        if self._master is not None and hasattr(self.engine, "on_cold_evict"):
            self.engine.on_cold_evict = self._fabric_on_cold_evict

    def _fabric_enabled(self) -> bool:
        return fabric_enabled(self.cfg)

    # ------------------------------------------------------- requester side

    def _fabric_prefetch(
        self, token_ids: List[int], hint: Dict[str, Any],
        srid: str = "", trace: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Kick off the peer prefix fetch for one admitted request (HTTP
        serving thread; the network work runs on a daemon thread so
        admission is never delayed). Best-effort throughout — any early
        exit just means recompute."""
        if not hint or not self._fabric_enabled():
            return
        eng = self.engine
        bm = getattr(eng, "block_mgr", None)
        if bm is None or not hasattr(eng, "import_kv_blocks"):
            return
        holder = str(hint.get("holder") or "")
        if not holder or holder == self.name:
            return
        want = min(int(hint.get("blocks") or 0), FETCH_MAX_BLOCKS)
        if want <= 0:
            return
        hashes = prefix_block_hashes(
            token_ids[: max(len(token_ids) - 1, 0)], bm.block_size, bm.seed
        )
        want = min(want, len(hashes))
        host = getattr(eng, "host_pool", None)
        ssd = getattr(eng, "ssd_pool", None)
        local = 0
        for h in hashes[:want]:
            # Racy off-thread reads by design: an over- or under-count
            # only shifts how many blocks ride the fetch; landing is
            # content-addressed and dedups either way.
            if (
                bm.lookup_hash(h) is not None
                or (host is not None and h in host)
                or (ssd is not None and h in ssd)
            ):
                local += 1
            else:
                break
        missing = hashes[local:want]
        if not missing:
            return
        key = missing[0]
        with self._fabric_mu:
            if key in self._fabric_inflight:
                # Anti-stampede: one fetch per distinct missing range; the
                # piggybackers' chunk boundaries adopt the blocks when the
                # winner lands them.
                self._m_fabric_dedup.inc()
                return
            if len(self._fabric_inflight) >= FETCH_MAX_CONCURRENT:
                return  # over the cap: recompute, don't pile up threads
            self._fabric_inflight[key] = True
        addr = str(hint.get("addr") or "")
        threading.Thread(
            target=self._fabric_fetch,
            args=(holder, addr, missing, key, srid, trace),
            name=f"kv-fetch-{self.name}",
            daemon=True,
        ).start()

    def _fabric_fetch(
        self, holder: str, addr: str, missing: List[bytes], key: bytes,
        srid: str = "", trace: Optional[Dict[str, Any]] = None,
    ) -> None:
        t0 = time.monotonic()
        self._m_fabric_fetches.inc()
        self._span(
            srid, "fabric_fetch", holder=holder, blocks=len(missing)
        )
        try:
            if not addr:
                addr = self._resolve_instance_addr(holder)
            if not addr:
                raise ConnectionError(f"holder {holder} unknown")
            faults.point(
                "kv_fetch.send",
                instance=self.name, peer=holder, addr=addr,
                blocks=len(missing),
            )
            fetch_header: Dict[str, Any] = {
                "block_hashes": [h.hex() for h in missing]
            }
            if isinstance(trace, dict):
                # Trace context rides the fetch frame so the holder's
                # serve shows up on the requesting request's timeline.
                fetch_header["trace"] = trace
            code, raw = post_bytes_raw(
                addr, "/kv/fetch",
                kv_frame_to_bytes(fetch_header),
                timeout=FETCH_TIMEOUT_S,
            )
            if code != 200:
                raise ConnectionError(f"holder {holder} returned {code}")
            header, body = kv_frame_split(raw)
            served = [
                bytes.fromhex(x) for x in header.get("block_hashes", [])
            ]
            kv = kv_frame_array(header, body)
            if not served or kv is None:
                raise ConnectionError(f"holder {holder} served no blocks")
            # Shape gate, same rule as the PD stream receiver: a fleet
            # whose engine configs diverge must fall back to recompute,
            # not land garbage KV.
            ex = getattr(self.engine, "executor", None)
            if ex is not None and hasattr(ex, "migration_shape"):
                expect = ex.migration_shape(len(served))
                if tuple(kv.shape) != tuple(expect):
                    raise ValueError(
                        f"fetched KV shape {tuple(kv.shape)} != local "
                        f"cache layout {tuple(expect)}"
                    )
            self.engine.import_kv_blocks(served, kv)
            self._m_fabric_fetch_blocks.inc(len(served))
            self._m_fabric_fetch_ms.observe((time.monotonic() - t0) * 1000)
            self._span(
                srid, "fabric_landed",
                holder=holder, blocks=len(served),
                fetch_ms=round((time.monotonic() - t0) * 1000, 3),
            )
        except Exception as e:  # noqa: BLE001 — fetch must fail soft
            self._m_fabric_fetch_aborts.inc()
            logger.warning(
                "prefix-fabric fetch of %d block(s) from %s aborted (%s); "
                "recompute covers the gap", len(missing), holder, e,
            )
        finally:
            with self._fabric_mu:
                self._fabric_inflight.pop(key, None)

    # --------------------------------------------------------- holder side

    def _handle_kv_fetch(self, h: HttpJsonApi) -> None:
        """Serve one peer's prefix fetch: kv-frame request ({block_hashes})
        in, kv-frame response (served hashes + stacked KV bytes) out."""
        try:
            n = int(h.headers.get("Content-Length", 0))
            header, _ = kv_frame_split(h.rfile.read(n))
            hashes = [
                bytes.fromhex(x) for x in header.get("block_hashes", [])
            ]
        except Exception as e:
            h.send_error_json(400, f"bad fetch request: {e}")
            return
        try:
            faults.point(
                "kv_fetch.recv", instance=self.name, blocks=len(hashes)
            )
        except faults.FaultInjected as fi:
            h.send_error_json(503, str(fi))
            return
        if not self._fabric_enabled() or not hasattr(
            self.engine, "export_cached_blocks"
        ):
            h.send_error_json(
                400, "this instance cannot serve prefix fetches"
            )
            return
        if not hashes:
            h.send_error_json(400, "fetch names no blocks")
            return
        served, kv = self.engine.export_cached_blocks(
            hashes[:FETCH_MAX_BLOCKS], timeout=EXPORT_WAIT_S
        )
        body = kv_frame_to_bytes(
            {"block_hashes": [b.hex() for b in served]},
            kv if served else None,
        )
        h.send_response(200)
        h.send_header("Content-Type", "application/octet-stream")
        h.send_header("Content-Length", str(len(body)))
        h.end_headers()
        h.wfile.write(body)

    def _handle_fabric_import(
        self, h: HttpJsonApi, header: Dict[str, Any], body: bytes
    ) -> None:
        """Receive re-homed eviction blocks (a peer's coordinated-eviction
        send): land them content-addressed into the local prefix cache.
        The next heartbeat's stored delta re-indexes them fleet-wide."""
        if not self._fabric_enabled():
            # The escape hatch must disable the RECEIVE side too: a
            # fabric-off instance refuses foreign KV (same gate as
            # /kv/fetch) — in-flight offers from not-yet-flipped peers
            # just drop their blocks, exactly like any refused offer.
            h.send_error_json(400, "prefix fabric disabled")
            return
        if not hasattr(self.engine, "import_kv_blocks"):
            h.send_error_json(400, "this instance cannot land KV blocks")
            return
        try:
            hashes = [
                bytes.fromhex(x) for x in header.get("block_hashes", [])
            ]
            kv = kv_frame_array(header, body)
        except Exception as e:
            h.send_error_json(400, f"bad fabric frame: {e}")
            return
        if not hashes or kv is None:
            h.send_error_json(400, "fabric frame carries no blocks")
            return
        ex = getattr(self.engine, "executor", None)
        if ex is not None and hasattr(ex, "migration_shape"):
            expect = ex.migration_shape(len(hashes))
            if tuple(kv.shape) != tuple(expect):
                h.send_error_json(
                    400,
                    f"fabric KV shape {tuple(kv.shape)} != local cache "
                    f"layout {tuple(expect)}",
                )
                return
        self.engine.import_kv_blocks(hashes, kv)
        h.send_json({"ok": True, "landed": len(hashes)})

    # -------------------------------------------------------- evictor side

    def _fabric_on_cold_evict(self, block_hash: bytes, kv) -> None:
        """Engine-thread hook: a committed block is leaving the last local
        tier. Enqueue the offer and return — NEVER block the engine; a
        full queue sheds the offer (the block dies locally, exactly as
        without the fabric)."""
        if not self._fabric_enabled() or self._master is None:
            return
        with self._fabric_evict_cond:
            try:
                self._fabric_evict_q.put_nowait(
                    (bytes(block_hash), np.ascontiguousarray(kv))
                )
            except queue.Full:
                return
            self._fabric_evict_pending += 1
        self._fabric_evict_start()

    def _fabric_evict_start(self) -> None:
        t = self._fabric_evict_thread
        if t is not None and t.is_alive():
            return
        with self._fabric_mu:
            t = self._fabric_evict_thread
            if t is not None and t.is_alive():
                return
            t = threading.Thread(
                target=self._fabric_evict_loop,
                name=f"fabric-evict-{self.name}",
                daemon=True,
            )
            self._fabric_evict_thread = t
        t.start()

    def _fabric_evict_loop(self) -> None:
        while True:
            item = self._fabric_evict_q.get()
            if item is None:
                return
            batch = [item]
            while len(batch) < 16:
                try:
                    nxt = self._fabric_evict_q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._fabric_evict_q.put(None)
                    break
                batch.append(nxt)
            try:
                self._fabric_offer_batch(batch)
            except Exception:  # noqa: BLE001 — offers are best-effort
                logger.debug("fabric evict offer failed", exc_info=True)
            finally:
                with self._fabric_evict_cond:
                    self._fabric_evict_pending -= len(batch)
                    self._fabric_evict_cond.notify_all()

    def fabric_evict_quiesce(self, timeout: float = 10.0) -> bool:
        """Deadline-bounded wait until every evict offer accepted so far
        has been FULLY processed (batch shipped or dropped, metrics
        settled) — the race-free barrier the e2e suite uses before
        snapshotting offer counters or installing fault plans, replacing
        sleep/poll. Returns False on timeout."""
        with self._fabric_evict_cond:
            return self._fabric_evict_cond.wait_for(
                lambda: self._fabric_evict_pending == 0, timeout=timeout
            )

    def _fabric_offer_batch(self, batch) -> None:
        """Ask the master where (whether) this batch of last-tier victims
        should live, then ship the 'send' verdicts to their peers. Any
        failure — chaos at the fault point, master unreachable, peer
        rejection — drops the blocks exactly as an uncoordinated eviction
        would: the index retraction is already queued on the heartbeat."""
        hashes = [h for h, _ in batch]
        faults.point(
            "fabric.evict_offer", instance=self.name, blocks=len(hashes)
        )
        code, resp = post_json(
            self._master._addr, "/rpc/fabric/evict_offer",
            {
                "name": self.name,
                "block_hashes": [h.hex() for h in hashes],
            },
            timeout=5.0,
        )
        if code != 200 or not isinstance(resp, dict):
            return
        decisions = resp.get("decisions") or []
        sends: Dict[str, List] = {}
        for (h_bytes, kv), d in zip(batch, decisions):
            if (
                isinstance(d, dict)
                and d.get("action") == "send"
                and d.get("addr")
            ):
                sends.setdefault(str(d["addr"]), []).append((h_bytes, kv))
        for addr, items in sends.items():
            frame = kv_frame_to_bytes(
                {
                    "fabric_blocks": True,
                    "block_hashes": [h.hex() for h, _ in items],
                },
                np.stack([kv for _, kv in items], axis=2),
            )
            try:
                code, _ = post_bytes(addr, "/kv/import", frame, timeout=30.0)
            except Exception:
                continue
            if code == 200:
                self._m_fabric_evict_offers.inc(len(items))
