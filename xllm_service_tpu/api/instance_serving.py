"""OpenAI serving paths of the instance server.

Split from api/instance.py (round-3 de-monolith): forwarded-traffic
fan-out (n/best_of), direct client serving (stream + accumulate),
best_of selection/response shaping, prompt tokenization, and the
generations push callback. Mixed into InstanceServer; `self` is the
server.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any, Dict, List, Optional, Tuple

from xllm_service_tpu.api.http_utils import HttpJsonApi, SseWriter
from xllm_service_tpu.api.protocol import parse_prompt_field, sampling_from_body
from xllm_service_tpu.common.shortuuid import generate_uuid
from xllm_service_tpu.common.types import RequestOutput, StatusCode
from xllm_service_tpu.ops.sampling import SamplingParams
from xllm_service_tpu.service.request import ServiceRequest
from xllm_service_tpu.service.response_handler import accumulate_sequences
from xllm_service_tpu.tokenizer import parse_messages
from xllm_service_tpu.tokenizer.tokenizer import IncrementalDetokenizer

logger = logging.getLogger("xllm_service_tpu.api.instance")


class ServingMixin:
    def _make_push_callback(
        self,
        srid: str,
        detoks: Optional[Dict[int, IncrementalDetokenizer]] = None,
    ):
        if detoks is None:
            detoks = {}

        def callback(out: RequestOutput) -> bool:
            out.service_request_id = srid
            self._detokenize(out, detoks)
            self._srid_note_delivered(
                srid, sum(len(s.token_ids) for s in out.outputs)
            )
            if out.finished:
                with self._srid_mu:
                    self._srid_map.pop(srid, None)
                    self._srid_forget_locked(srid)
                # A prefill_only request that finishes on its first token
                # (EOS / max_tokens=1 / reject / cancel) never runs its
                # handoff — reap the ack event here or it leaks forever.
                with self._push_acked_mu:
                    self._push_acked.pop(srid, None)
                # Same for the streamed-media handle: without this, a
                # finished request's embedding arrays stay pinned in
                # _mm_streams until the NEXT mm request triggers the TTL
                # reap — indefinitely on an instance gone text-only.
                self._mm_stream_discard(srid)
            self._push_q.put(out)
            return True

        return callback

    def _serve_fanout_forwarded(
        self,
        srid: str,
        token_ids: List[int],
        sampling: SamplingParams,
        n: int,
        best_of: int,
        guided: Optional[str] = None,
        schema: Optional[dict] = None,
        adapter_idx: int = 0,
        offline: bool = False,
    ) -> None:
        """Run n (or best_of) sequences as independent engine requests and
        push INDEXED deltas under one service_request_id. The prompt's KV
        blocks are shared through the prefix cache. best_of buffers all
        children and pushes only the top-n (by mean logprob) at the end."""
        from xllm_service_tpu.common.types import Usage
        from xllm_service_tpu.runtime.engine import EngineRequest

        total = best_of or n
        detoks: Dict[int, IncrementalDetokenizer] = {}
        agg_mu = threading.Lock()
        state = {
            "remaining": total,
            "generated": [0] * total,
            "logprob_sum": [0.0] * total,
            "buffered": {} if best_of else None,  # index -> merged SequenceOutput
            "aborted": False,
        }
        want_logprobs = sampling.logprobs

        def make_cb(i: int):
            def cb(out: RequestOutput) -> bool:
                out.service_request_id = srid
                for s in out.outputs:
                    s.index = i
                    for lp in s.logprobs:
                        state["logprob_sum"][i] += lp.data.logprob
                with agg_mu:
                    if state["aborted"]:
                        return False
                    if out.usage:
                        state["generated"][i] = out.usage.num_generated_tokens
                    last = False
                    if out.finished:
                        state["remaining"] -= 1
                        last = state["remaining"] == 0
                self._srid_note_delivered(
                    srid, sum(len(s.token_ids) for s in out.outputs)
                )
                if not out.status.ok() and not out.cancelled:
                    # Child error (reject/engine failure): surface it ONCE,
                    # cancel the siblings, drop the request.
                    with agg_mu:
                        state["aborted"] = True
                    with self._srid_mu:
                        others = self._srid_map.pop(srid, None) or []
                        self._srid_forget_locked(srid)
                    for other in others:
                        self.engine.cancel(other)
                    out.finished = True
                    self._push_q.put(out)
                    return False
                if state["buffered"] is not None:
                    # best_of: hold everything until all children finish.
                    with agg_mu:
                        accumulate_sequences(state["buffered"], out)
                    if last:
                        self._finish_best_of(
                            srid, state, token_ids, n, want_logprobs, detoks
                        )
                    return True
                # n>1 streaming/accumulating path: push indexed deltas; only
                # the LAST child's finish carries finished + merged usage
                # (per-seq finish_reason still reaches the client).
                self._detokenize(out, detoks)
                if out.finished and not last:
                    out.finished = False
                    out.usage = None
                elif out.finished and last:
                    out.usage = Usage(
                        num_prompt_tokens=len(token_ids),
                        num_generated_tokens=sum(state["generated"]),
                    )
                    with self._srid_mu:
                        self._srid_map.pop(srid, None)
                        self._srid_forget_locked(srid)
                self._push_q.put(out)
                return True

            return cb

        # Register the rids BEFORE submitting: a fast-finishing child pops
        # the srid entry, and a late registration would resurrect it (leak)
        # or let a /cancel in the window find nothing to cancel.
        rids = [generate_uuid(16) for _ in range(total)]
        with self._srid_mu:
            self._srid_map.setdefault(srid, []).extend(rids)
        for i, rid in enumerate(rids):
            self.engine.add_request(
                EngineRequest(
                    request_id=rid,
                    prompt_token_ids=list(token_ids),
                    sampling=self._child_sampling(
                        sampling, i, need_logprobs=bool(best_of)
                    ),
                    callback=make_cb(i),
                    guided=guided,
                    schema=schema,
                    offline=offline,
                    adapter_idx=adapter_idx,
                )
            )

    def _finish_best_of(
        self,
        srid: str,
        state: Dict[str, Any],
        token_ids: List[int],
        n: int,
        want_logprobs: bool,
        detoks: Dict[int, IncrementalDetokenizer],
    ) -> None:
        """All best_of children done: rank by mean logprob, re-index the
        top n as choices 0..n-1, push ONE final output."""
        from xllm_service_tpu.common.types import Usage

        merged = state["buffered"]
        order = sorted(
            merged,
            key=lambda i: (
                state["logprob_sum"][i] / max(len(merged[i].token_ids), 1)
            ),
            reverse=True,
        )
        winners = []
        for new_idx, old_idx in enumerate(order[:n]):
            s = merged[old_idx]
            s.index = new_idx
            if not want_logprobs:
                s.logprobs = []
            winners.append(s)
        final = RequestOutput(
            request_id=srid,
            service_request_id=srid,
            outputs=winners,
            usage=Usage(
                num_prompt_tokens=len(token_ids),
                num_generated_tokens=sum(state["generated"]),
            ),
            finished=True,
        )
        self._detokenize(final, detoks)
        with self._srid_mu:
            self._srid_map.pop(srid, None)
            self._srid_forget_locked(srid)
        self._push_q.put(final)

    def _prompt_tokens(self, body: Dict[str, Any], chat: bool) -> List[int]:
        # Forwarded traffic arrives pre-tokenized (the injection contract,
        # service.cpp:334-341) — never re-tokenize.
        if body.get("token_ids"):
            return [int(t) for t in body["token_ids"]]
        if chat:
            prompt = self.chat_template.apply(
                parse_messages(body.get("messages", [])), body.get("tools")
            )
        else:
            prompt, token_ids, err = parse_prompt_field(body.get("prompt", ""))
            if err:
                raise ValueError(err)
            if token_ids:
                return token_ids
        return self.tokenizer.encode(prompt)

    @staticmethod
    def _n_sequences(body: Dict[str, Any], chat: bool) -> Tuple[int, int, str]:
        """Parse (n, best_of, error). best_of is the completions-only
        over-generation count (>= n, select top-n by logprob); chat has no
        best_of. Errors mirror OpenAI validation."""
        try:
            n = max(int(body.get("n") or 1), 1)
        except (TypeError, ValueError):
            return 1, 0, "invalid n"
        best_of = 0
        if not chat and body.get("best_of") is not None:
            try:
                best_of = int(body["best_of"])
            except (TypeError, ValueError):
                return n, 0, "invalid best_of"
            if best_of < n:
                return n, best_of, "best_of must be >= n"
            if body.get("stream"):
                return n, best_of, "best_of is not supported with streaming"
        return n, best_of, ""

    def _vocab_size(self):
        ex = getattr(self.engine, "executor", None)
        return getattr(getattr(ex, "cfg", None), "vocab_size", None)

    def _parse_guided(
        self, body: Dict[str, Any]
    ) -> Tuple[Optional[str], Optional[dict], str]:
        """OpenAI response_format -> (guided mode, schema, error).
        {"type": "json_object"} constrains to any JSON object;
        {"type": "json_schema", "json_schema": {"schema": ...}} to the
        given schema (strict subset — guided/schema_fsm); "text"/absent
        pass through."""
        rf = body.get("response_format")
        if not rf:
            return None, None, ""
        if not isinstance(rf, dict) or "type" not in rf:
            return None, None, "response_format must be an object with a type"
        if rf["type"] in ("text", None):
            return None, None, ""
        if rf["type"] == "json_schema":
            js = rf.get("json_schema")
            schema = js.get("schema") if isinstance(js, dict) else None
            if not isinstance(schema, dict):
                return None, None, (
                    "response_format json_schema requires "
                    "json_schema.schema (an object)"
                )
            from xllm_service_tpu.guided import schema_fsm

            try:
                schema_fsm.compile_schema(schema)
            except schema_fsm.SchemaError as e:
                return None, None, f"unsupported json_schema: {e}"
            err = self._ensure_guided_context()
            if not err:
                # HTTP-thread prewarm: compute the canonical-path token
                # bitmaps NOW so the engine step loop (all running
                # decodes) never stalls behind the first-visit vocab
                # byte walk (advisor finding, round 4).
                try:
                    self.engine.prewarm_schema(schema)
                except Exception:
                    pass  # prewarm is an optimization, never a gate
            return (("json_schema", schema, "") if not err
                    else (None, None, err))
        if rf["type"] != "json_object":
            return None, None, (
                f"response_format type {rf['type']!r} is not supported "
                f"(json_schema, json_object or text)"
            )
        err = self._ensure_guided_context()
        return ("json", None, "") if not err else (None, None, err)

    def _ensure_guided_context(self) -> str:
        """Build + install the JSON-mode mask table once (persistent-
        cached next to the XLA jit cache when configured — the first
        build walks every vocab token through the automaton from every
        abstract state, ~a minute for 128K vocabs)."""
        if getattr(self, "_guided_ready", False):
            return ""
        if not hasattr(self, "_guided_build_lock"):
            self._guided_build_lock = threading.Lock()
        with self._guided_build_lock:
            if getattr(self, "_guided_ready", False):
                return ""
            return self._build_guided_context()

    def _build_guided_context(self) -> str:
        if not hasattr(self.engine, "set_guided_context"):
            return "guided decoding requires a real engine"
        vocab = self._vocab_size()
        if not vocab:
            return "guided decoding requires a real engine"
        tb = self.tokenizer.token_bytes_table(vocab)
        if tb is None:
            return "guided json is not supported for this tokenizer"
        from xllm_service_tpu.guided import json_fsm

        eos = sorted(
            set(self.engine.eos_token_ids)
            | ({self.tokenizer.eos_token_id}
               if self.tokenizer.eos_token_id is not None else set())
        )
        table = self._load_guided_cache(tb, eos)
        if table is None:
            table = json_fsm.token_mask_table(tb, eos)
            self._store_guided_cache(tb, eos, table)
        # eos travels with the table: schema bitmaps must allow the SAME
        # eos set the json_object table was built with (the engine's own
        # set is empty in service deployments).
        self.engine.set_guided_context(table, tb, eos_ids=eos)
        self._guided_ready = True
        return ""

    def _guided_cache_path(self, tb, eos):
        import hashlib
        import os
        import tempfile

        from xllm_service_tpu.guided import json_fsm

        h = hashlib.sha256()
        for t in tb:
            h.update(t + b"\x00")
        h.update(repr(eos).encode())
        h.update(
            f"v{json_fsm.FSM_VERSION}:{json_fsm.NUM_MASK_STATES}".encode()
        )
        base = self.cfg.compilation_cache_dir or tempfile.gettempdir()
        return os.path.join(base, f"xllm-json-mask-{h.hexdigest()[:16]}.npy")

    def _load_guided_cache(self, tb, eos):
        import os

        import numpy as np

        from xllm_service_tpu.guided import json_fsm

        path = self._guided_cache_path(tb, eos)
        if os.path.exists(path):
            try:
                table = np.load(path)
            except Exception:
                return None
            if table.shape == (json_fsm.NUM_MASK_STATES, len(tb)):
                return table
        return None

    def _store_guided_cache(self, tb, eos, table) -> None:
        import os
        import tempfile

        import numpy as np

        path = self._guided_cache_path(tb, eos)
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            fd, tmp = tempfile.mkstemp(
                dir=os.path.dirname(path), suffix=".npy"
            )
            os.close(fd)
            np.save(tmp, table)  # np.save keeps the .npy name as-is
            os.replace(tmp, path)
        except Exception:
            pass  # cache is best-effort

    @staticmethod
    def _child_sampling(sampling: SamplingParams, i: int, need_logprobs: bool):
        """Per-sequence sampling params: distinct RNG stream per choice
        (i=0 keeps the request seed so n=1 behavior is unchanged)."""
        seed = (sampling.seed + 0x9E3779B9 * i) & 0xFFFFFFFF
        return dataclasses.replace(
            sampling,
            seed=seed,
            logprobs=sampling.logprobs or need_logprobs,
        )

    def _serve(self, h: HttpJsonApi, body: Dict[str, Any], chat: bool) -> None:
        from xllm_service_tpu.runtime.engine import EngineRequest

        srid = body.get("service_request_id", "")
        try:
            token_ids = self._prompt_tokens(body, chat)
        except (ValueError, TypeError) as e:
            h.send_error_json(400, str(e))
            return
        if not token_ids:
            h.send_error_json(400, "empty prompt")
            return
        n, best_of, n_err = self._n_sequences(body, chat)
        if n_err:
            h.send_error_json(400, n_err)
            return
        try:
            sampling = sampling_from_body(
                body, self.cfg, vocab_size=self._vocab_size()
            )
        except ValueError as e:
            h.send_error_json(400, str(e))
            return
        guided, guided_schema, gerr = self._parse_guided(body)
        if gerr:
            h.send_error_json(400, gerr)
            return
        # Multi-LoRA: an OpenAI `model` naming a registered adapter routes
        # to its row; anything else runs the base model.
        adapter_idx = getattr(self, "lora_names", {}).get(
            body.get("model"), 0
        )
        # Hybrid scheduling: offline work admits behind online work and
        # its running decodes preempt under online bursts (engine-level;
        # the master additionally parks offline admissions).
        offline = bool(body.get("offline", False))

        if srid and self._master is not None:
            # Prefix-fabric peer fetch (docs/KV_CACHE.md): the master's
            # dispatch hint says a peer holds more of this prompt's
            # prefix than we do — pull the gap while the engine
            # chunk-prefills the tail. Best-effort, never a gate.
            fab = body.get("kv_fabric")
            if fab and not body.get("mm_positions") and not adapter_idx:
                try:
                    self._fabric_prefetch(
                        token_ids, fab, srid=srid, trace=body.get("trace")
                    )
                except Exception:
                    logger.exception("fabric prefetch failed; recomputing")

        if srid and self._master is not None and (n > 1 or best_of > 1):
            # Reconcile-manifest entry (docs/FAULT_TOLERANCE.md) — after
            # every validation reject, so a refused request can't leak a
            # tracking entry that only a takeover scan would collect.
            self._srid_track(
                srid, len(token_ids), body.get("master_epoch")
            )
            self._span(
                srid, "admit", prompt_tokens=len(token_ids), fanout=True
            )
            # Fan-out mode: PD split is skipped for multi-sequence requests
            # (a per-child handoff would need sub-request ids on the wire);
            # this instance serves all sequences and pushes indexed deltas.
            self._serve_fanout_forwarded(
                srid, token_ids, sampling, n, best_of, guided=guided,
                schema=guided_schema, adapter_idx=adapter_idx,
                offline=offline,
            )
            h.send_json({"ok": True, "service_request_id": srid})
            return
        rid = generate_uuid(16)
        # Mid-stream failover resume (docs/FAULT_TOLERANCE.md): the last
        # `resume_from` token_ids are replayed output from a dead
        # instance. The generation budget shrinks by the replayed count
        # (the client already holds those tokens), and the engine-side
        # marker keeps deterministic engines' continuations aligned.
        resume_from = int(body.get("resume_from") or 0)
        if resume_from:
            if (
                resume_from < 0  # would INFLATE the budget below
                or resume_from >= len(token_ids)
                or n > 1
                or best_of > 1
            ):
                h.send_error_json(400, "invalid resume_from")
                return
            sampling = dataclasses.replace(
                sampling,
                max_new_tokens=max(sampling.max_new_tokens - resume_from, 1),
            )

        if srid and self._master is not None:
            # Forwarded mode: ack now, stream back over /rpc/generations.
            mm_embeds = mm_positions = mm_stream = None
            if body.get("mm_positions"):
                from xllm_service_tpu.api.instance_mm import (
                    _encoder_fabric_enabled,
                )

                if _encoder_fabric_enabled(self.cfg):
                    # Encoder fabric (docs/EPD.md): admit NOW with a
                    # stream handle — the engine prefills text chunks
                    # while the encoder's per-item session lands
                    # embeddings, adopting them at chunk boundaries.
                    mm_positions = [int(p) for p in body["mm_positions"]]
                    mm_stream = self._mm_stream_attach(srid, mm_positions)
                    mm_stream.note_admitted()
                else:
                    # Legacy synchronous EPD: the encoder pushed this
                    # request's media embeddings to /mm/import before the
                    # master forwarded the text (usually already landed).
                    mm = self._pop_mm_import(srid, timeout=60.0)
                    if mm is None:
                        h.send_error_json(
                            503, "media embeddings never arrived"
                        )
                        return
                    mm_embeds, mm_positions = mm
                    if len(mm_positions) != len(body["mm_positions"]):
                        # Encoder and service disagree on media-token
                        # count — reject rather than pair mismatched
                        # arrays (an embeds/positions desync would crash
                        # the engine step).
                        h.send_error_json(
                            502,
                            f"encoder produced {len(mm_positions)} media "
                            f"tokens but the request has "
                            f"{len(body['mm_positions'])} placeholders",
                        )
                        return
            with self._srid_mu:
                self._srid_map.setdefault(srid, []).append(rid)
            # Manifest entry rides the same admission (after the mm/
            # resume rejects above — see the fan-out branch's comment).
            self._srid_track(
                srid, len(token_ids), body.get("master_epoch")
            )
            # Instance-side span: one admission record per forwarded
            # request, clocked on THIS process (the trace collector
            # aligns it with the master's dispatch span).
            self._span(srid, "admit", prompt_tokens=len(token_ids))
            detoks: Dict[int, IncrementalDetokenizer] = {}
            callback = self._make_push_callback(srid, detoks)
            routing = body.get("routing") or {}
            decode_name = routing.get("decode_name", "")
            if mm_embeds is not None or mm_stream is not None:
                # Media requests serve colocated: the recomputed tail on a
                # decode peer would need the embeddings too.
                decode_name = ""
            if adapter_idx:
                # LoRA requests serve colocated too: adapter KV never
                # commits (adapter-blind hashes), so a PD split would ship
                # a zero-block handoff and the decode peer would silently
                # recompute the whole prompt.
                decode_name = ""
            if resume_from:
                # Resumed requests serve colocated: the replay already
                # paid one re-prefill; a PD handoff would bolt a second
                # migration onto a recovery path that must stay simple.
                decode_name = ""
            if decode_name and decode_name != self.name:
                # PD disaggregation: this instance is the prefill side —
                # emit the first token, then migrate KV to the decode peer
                # (reference topology: rpc_service/service.h:61-71). The
                # streaming session (pipelined per-chunk KV export,
                # docs/PD_DISAGGREGATION.md) opens here, at ADMIT time:
                # the master already routed the decode peer, so chunk 0
                # can leave before prefill-done.
                with self._push_acked_mu:
                    self._push_acked[srid] = threading.Event()
                kv_stream = self._open_kv_stream(
                    srid, decode_name, epoch=body.get("master_epoch"),
                    trace=body.get("trace"),
                )
                self.engine.add_request(
                    EngineRequest(
                        request_id=rid,
                        prompt_token_ids=token_ids,
                        sampling=sampling,
                        callback=callback,
                        guided=guided,
                        schema=guided_schema,
                        offline=offline,
                        adapter_idx=adapter_idx,
                        prefill_only=True,
                        kv_stream=kv_stream,
                        handoff=self._make_handoff_sender(
                            srid, decode_name, body, detoks,
                            seed=sampling.seed,
                            respond_via_self=(
                                routing.get("decode_response_to_service", True)
                                is False
                            ),
                            kv_stream=kv_stream,
                        ),
                    )
                )
            else:
                self.engine.add_request(
                    EngineRequest(
                        request_id=rid,
                        prompt_token_ids=token_ids,
                        sampling=sampling,
                        callback=callback,
                        guided=guided,
                        schema=guided_schema,
                        offline=offline,
                        adapter_idx=adapter_idx,
                        mm_embeds=mm_embeds,
                        mm_positions=mm_positions,
                        mm_grids=body.get("mm_grids"),
                        mm_stream=mm_stream,
                        resume_from=resume_from,
                    )
                )
            h.send_json({"ok": True, "service_request_id": srid, "request_id": rid})
            return

        # Direct mode: this instance is the whole stack for one request.
        self._serve_direct(
            h, body, chat, token_ids, sampling, rid, n, best_of,
            guided=guided, schema=guided_schema, adapter_idx=adapter_idx,
            offline=offline,
        )

    def _serve_direct(
        self,
        h: HttpJsonApi,
        body: Dict[str, Any],
        chat: bool,
        token_ids: List[int],
        sampling: SamplingParams,
        rid: str,
        n: int = 1,
        best_of: int = 0,
        guided: Optional[str] = None,
        schema: Optional[dict] = None,
        adapter_idx: int = 0,
        offline: bool = False,
    ) -> None:
        from xllm_service_tpu.runtime.engine import EngineRequest

        total = best_of or n

        req = ServiceRequest(
            service_request_id=("chatcmpl-" if chat else "cmpl-") + rid,
            model=body.get("model", self.cfg.model),
            stream=bool(body.get("stream", False)),
            include_usage=bool(
                (body.get("stream_options") or {}).get("include_usage", False)
            ),
            token_ids=token_ids,
        )
        if chat:
            req.messages = parse_messages(body.get("messages", []))
            req.tools = body.get("tools")  # tool-call extraction
        else:
            p = body.get("prompt", "")
            req.prompt = p if isinstance(p, str) else "".join(p)

        done = threading.Event()
        acc: List[RequestOutput] = []
        sse: Optional[SseWriter] = None
        # Per-choice: each choice's first chat chunk must carry the
        # assistant role (OpenAI stream semantics), not just the globally
        # first chunk.
        first_sent: Dict[int, bool] = {}
        agg_mu = threading.Lock()
        remaining = [total]
        lp_sums = [0.0] * total
        gen_counts = [0] * total

        detoks: Dict[int, IncrementalDetokenizer] = {}
        if req.stream:
            sse = SseWriter(h)

            class _Stream:
                def write(_, payload):
                    return sse.send(payload)

                def write_done(_):
                    ok = sse.send_done()
                    done.set()
                    return ok

            stream = _Stream()

            def make_callback(i: int):
                def callback(out: RequestOutput) -> bool:
                    if not out.status.ok() and not out.cancelled:
                        # Engine-side failure: surface it, don't end as a
                        # clean empty stream.
                        sse.send(
                            {"error": {"message": out.status.message,
                                       "code": int(out.status.code)}}
                        )
                        sse.close()
                        done.set()
                        return False
                    for s in out.outputs:
                        s.index = i
                        gen_counts[i] += len(s.token_ids)
                    with agg_mu:
                        last = True
                        if out.finished:
                            remaining[0] -= 1
                            last = remaining[0] == 0
                        if out.finished and not last:
                            # Suppress the per-child [DONE]; keep the
                            # choice's finish_reason chunk.
                            out.finished = False
                            out.usage = None
                        elif out.finished and out.usage and total > 1:
                            from xllm_service_tpu.common.types import Usage

                            out.usage = Usage(
                                num_prompt_tokens=len(token_ids),
                                num_generated_tokens=sum(gen_counts),
                            )
                    self._detokenize(out, detoks)
                    ok = self._responses.send_delta_to_client(
                        stream, req, out, first_sent.get(i, False)
                    )
                    first_sent[i] = True
                    if out.finished or not ok:
                        # All sequences finished, or the client
                        # disconnected — the exchange is over.
                        done.set()
                    return ok

                return callback
        else:

            def make_callback(i: int):
                def callback(out: RequestOutput) -> bool:
                    for s in out.outputs:
                        s.index = i
                        for lp in s.logprobs:
                            lp_sums[i] += lp.data.logprob
                    if not best_of:
                        self._detokenize(out, detoks)
                    with agg_mu:
                        acc.append(out)
                        if out.finished:
                            remaining[0] -= 1
                            if remaining[0] == 0:
                                done.set()
                    return True

                return callback

        rids = []
        for i in range(total):
            child_rid = rid if i == 0 else generate_uuid(16)
            rids.append(child_rid)
            self.engine.add_request(
                EngineRequest(
                    request_id=child_rid,
                    prompt_token_ids=list(token_ids),
                    sampling=self._child_sampling(
                        sampling, i, need_logprobs=bool(best_of)
                    ),
                    callback=make_callback(i),
                    guided=guided,
                    schema=schema,
                    offline=offline,
                    adapter_idx=adapter_idx,
                )
            )
        if not done.wait(600.0):
            for child_rid in rids:
                self.engine.cancel(child_rid)
            if sse is None:
                # Only a never-started exchange can still carry an error
                # response; an open SSE stream must not get a second head.
                h.send_error_json(504, "generation timeout")
            else:
                sse.close()
                h.close_connection = True
            return
        if not req.stream:
            if best_of:
                self._respond_best_of(
                    h, req, acc, lp_sums, n, sampling.logprobs, detoks
                )
            else:
                self._respond_accumulated(h, req, acc)

    def _respond_best_of(
        self,
        h: HttpJsonApi,
        req: ServiceRequest,
        acc: List[RequestOutput],
        lp_sums: List[float],
        n: int,
        want_logprobs: bool,
        detoks: Dict[int, IncrementalDetokenizer],
    ) -> None:
        """Rank best_of children by mean logprob, return the top n as
        choices 0..n-1 (completions API best_of semantics)."""
        from xllm_service_tpu.common.types import Usage

        if any(not o.status.ok() and not o.cancelled for o in acc):
            self._respond_accumulated(h, req, acc)  # error path
            return
        merged: Dict[int, Any] = {}
        for out in acc:
            accumulate_sequences(merged, out)
        order = sorted(
            merged,
            key=lambda i: lp_sums[i] / max(len(merged[i].token_ids), 1),
            reverse=True,
        )
        winners = []
        total_generated = sum(len(s.token_ids) for s in merged.values())
        for new_idx, old_idx in enumerate(order[:n]):
            s = merged[old_idx]
            s.index = new_idx
            if not want_logprobs:
                s.logprobs = []
            winners.append(s)
        final = RequestOutput(
            request_id=req.service_request_id,
            service_request_id=req.service_request_id,
            outputs=winners,
            usage=Usage(
                num_prompt_tokens=len(req.token_ids),
                num_generated_tokens=total_generated,
            ),
            finished=True,
        )
        self._detokenize(final, detoks)

        class _Once:
            def finish(_, payload):
                h.send_json(payload)
                return True

            def finish_with_error(_, code, msg):
                h.send_error_json(500, msg)
                return True

        self._responses.send_result_to_client(_Once(), req, final)

    def _respond_accumulated(
        self, h: HttpJsonApi, req: ServiceRequest, acc: List[RequestOutput]
    ) -> None:
        # With n>1 children interleaving, an errored child's output can sit
        # anywhere in acc — scan, don't just check the tail.
        err = next(
            (o for o in acc if not o.status.ok() and not o.cancelled), None
        )
        if err is not None:
            h.send_error_json(
                429 if err.status.code == StatusCode.RESOURCE_EXHAUSTED else 500,
                err.status.message,
            )
            return
        merged: Dict[int, Any] = {}
        usage = None
        for out in acc:
            accumulate_sequences(merged, out)
            if out.usage:
                usage = out.usage
        if usage is not None and len(merged) > 1:
            # n>1: per-child usage only counts its own tokens — report the
            # request-level total.
            from xllm_service_tpu.common.types import Usage

            usage = Usage(
                num_prompt_tokens=usage.num_prompt_tokens,
                num_generated_tokens=sum(
                    len(s.token_ids) for s in merged.values()
                ),
            )
        final = RequestOutput(
            request_id=req.service_request_id,
            service_request_id=req.service_request_id,
            outputs=sorted(merged.values(), key=lambda s: s.index),
            usage=usage,
            finished=True,
        )

        class _Once:
            def finish(_, payload):
                h.send_json(payload)
                return True

            def finish_with_error(_, code, msg):
                h.send_error_json(500, msg)
                return True

        self._responses.send_result_to_client(_Once(), req, final)
