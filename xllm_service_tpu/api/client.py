"""Instance-side control-plane client.

The embeddable client an engine instance uses to join the cluster —
register, 3 s heartbeat loop, decode->service token push — mirroring the
reference's rpc client library (reference: rpc_service/client.{h,cpp}:
heartbeat loop :59-77, register_instance :85-115) over the JSON protocol in
api/protocol.py. Heartbeats carry load/latency metrics + KV cache events;
a `reregister` response (lease lost) triggers automatic re-registration.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from xllm_service_tpu.api.http_utils import get_json, post_json
from xllm_service_tpu.api.protocol import output_to_json
from xllm_service_tpu.common import faults
from xllm_service_tpu.common.types import (
    InstanceMetaInfo,
    KvCacheEvent,
    LatencyMetrics,
    LoadMetrics,
    RequestOutput,
)

logger = logging.getLogger(__name__)


class MasterClient:
    def __init__(self, master_rpc_addr: str):
        self._addr = master_rpc_addr
        # Clock-alignment echo state (docs/OBSERVABILITY.md, Distributed
        # tracing): the master's reply stamp from the LAST heartbeat
        # response plus this process's monotonic clock at receipt — echoed
        # on the next beat so the master derives a LOWER bound on
        # (master_mono - instance_mono); the request's send stamp gives
        # the upper bound. Reset on master takeover (old stamps are from
        # a different process clock).
        self._clock_echo: Optional[Dict] = None

    def hello(self, name: str) -> bool:
        code, resp = post_json(self._addr, "/rpc/hello", {"name": name})
        return code == 200 and resp.get("ok", False)

    def register(self, meta: InstanceMetaInfo) -> Dict:
        code, resp = post_json(
            self._addr, "/rpc/register", {"meta": meta.to_json()}
        )
        if code != 200 or not resp.get("ok"):
            raise RuntimeError(f"register failed: {code} {resp}")
        return resp

    def deregister(self, name: str) -> bool:
        """Graceful-shutdown removal from the registry (lease revoked
        immediately; ungraceful death still falls to TTL expiry)."""
        try:
            code, resp = post_json(
                self._addr, "/rpc/deregister", {"name": name}, timeout=5.0
            )
        except Exception:
            return False
        return code == 200 and resp.get("ok", False)

    def heartbeat(
        self,
        name: str,
        load_metrics: Optional[LoadMetrics] = None,
        latency_metrics: Optional[LatencyMetrics] = None,
        cache_event: Optional[KvCacheEvent] = None,
        serving_role: str = "",
    ) -> Dict:
        body: Dict = {"name": name}
        if serving_role:
            body["serving_role"] = serving_role
        if load_metrics is not None:
            body["load_metrics"] = load_metrics.to_json()
        if latency_metrics is not None:
            body["latency_metrics"] = latency_metrics.to_json()
        if cache_event is not None and not cache_event.empty():
            body["cache_event"] = cache_event.to_json()
        # Monotonic-offset sample for cross-process trace alignment: the
        # send stamp bounds the master-instance clock offset from above,
        # the echoed reply stamp (previous response) bounds it from below.
        clock: Dict = {"send_mono_ms": round(time.monotonic() * 1000.0, 3)}
        if self._clock_echo is not None:
            clock["echo_master_mono_ms"] = self._clock_echo["master_mono_ms"]
            clock["echo_recv_mono_ms"] = self._clock_echo["recv_mono_ms"]
        body["clock"] = clock
        # Chaos hook: a dropped beat simulates the instance->master side of
        # a partition (staleness suspicion / pruning paths).
        faults.point("heartbeat.send", name=name, addr=self._addr)
        code, resp = post_json(self._addr, "/rpc/heartbeat", body, timeout=10.0)
        if code == 200 and isinstance(resp, dict):
            reply = resp.get("clock")
            if isinstance(reply, dict) and reply.get("master_mono_ms") is not None:
                self._clock_echo = {
                    "master_mono_ms": float(reply["master_mono_ms"]),
                    "recv_mono_ms": round(time.monotonic() * 1000.0, 3),
                }
        return resp if code == 200 else {"ok": False}

    def push_generations(
        self, outputs: List[RequestOutput], epoch: int = 0
    ) -> Dict[str, bool]:
        """Batched decode->service stream (proto analog:
        DisaggStreamGenerations, Generations RPC). Returns the per-request
        continue map; False means the service dropped the request.

        `epoch` is the instance's fence high-water: a master that sees a
        HIGHER epoch than its own was deposed and just doesn't know yet —
        it 503s instead of judging the batch (its cont=False would cancel
        work the real master dispatched in the pre-demotion window). A
        non-200 RAISES so the caller retries; by then the heartbeat has
        re-pointed `_addr` at the successor."""
        if not outputs:
            return {}
        body: Dict = {"gens": [output_to_json(o) for o in outputs]}
        if epoch:
            body["master_epoch"] = int(epoch)
        code, resp = post_json(
            self._addr, "/rpc/generations", body, timeout=30.0
        )
        if code != 200:
            raise RuntimeError(f"generations push rejected: HTTP {code}")
        return resp.get("cont", {})

    def instance_info(self, name: str) -> Optional[InstanceMetaInfo]:
        code, resp = get_json(self._addr, f"/rpc/instance_info?name={name}")
        return InstanceMetaInfo.from_json(resp) if code == 200 else None


class HeartbeatLoop:
    """Background register+heartbeat driver (reference: client.cpp:59-77).

    Collect callbacks sample the engine's current load/latency/cache-delta
    at each beat; re-registers when the master reports a lost lease."""

    def __init__(
        self,
        client: MasterClient,
        meta: InstanceMetaInfo,
        interval_s: float = 3.0,
        collect_load: Optional[Callable[[], LoadMetrics]] = None,
        collect_latency: Optional[Callable[[], LatencyMetrics]] = None,
        collect_cache_event: Optional[Callable[[], KvCacheEvent]] = None,
        collect_cache_snapshot: Optional[Callable[[], KvCacheEvent]] = None,
    ):
        self._client = client
        self._meta = meta
        self._interval = interval_s
        self._collect_load = collect_load
        self._collect_latency = collect_latency
        self._collect_cache_event = collect_cache_event
        # Full-tier snapshot provider (engine.cache_snapshot_event —
        # stored = HBM commits, offload = host/SSD holdings): sent when
        # the master asks (`resync_cache` on a heartbeat response — it
        # pruned this instance's index locations on breaker ejection and
        # deltas alone cannot rebuild them).
        self._collect_cache_snapshot = collect_cache_snapshot
        self._resync_cache = False
        self._stop = threading.Event()
        # Cache delta drained from the engine but not yet delivered: merged
        # into the next beat so a failed POST never loses transitions (the
        # global KV index would silently diverge otherwise).
        self._pending_event: Optional[KvCacheEvent] = None
        self._thread = threading.Thread(
            target=self._loop, name=f"heartbeat-{meta.name}", daemon=True
        )

    def start(self) -> None:
        resp = self._client.register(self._meta)
        self._interval = float(resp.get("heartbeat_interval_s", self._interval))
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)

    @property
    def stopped(self) -> bool:
        return self._stop.is_set()

    def beat_now(self) -> Dict:
        """One synchronous beat (tests / forced flush)."""
        return self._beat()

    def _beat(self) -> Dict:
        event = self._collect_cache_event() if self._collect_cache_event else None
        if self._pending_event is not None:
            event = (
                self._pending_event.merge(event)
                if event is not None
                else self._pending_event
            )
            self._pending_event = None
        if self._resync_cache and self._collect_cache_snapshot is not None:
            # Master-requested index rebuild: fold the FULL tier snapshot
            # (stored = HBM, offload = host/SSD) under this beat's delta —
            # merge() gives the newer delta precedence, and the index-side
            # application is idempotent (set inserts / tier moves).
            self._resync_cache = False
            try:
                snap = self._collect_cache_snapshot()
            except Exception:
                snap = None
            if snap is not None and not snap.empty():
                event = snap.merge(event) if event is not None else snap
        try:
            resp = self._client.heartbeat(
                self._meta.name,
                load_metrics=self._collect_load() if self._collect_load else None,
                latency_metrics=(
                    self._collect_latency() if self._collect_latency else None
                ),
                cache_event=event,
                # Role reconciliation: the master compares against its
                # registry and re-sends /flip on mismatch (a dropped or
                # restart-lost notification self-heals within one beat).
                serving_role=self._meta.current_type.name,
            )
        except Exception:
            self._pending_event = event
            raise
        if not resp.get("ok", False) and event is not None and not event.empty():
            # Master rejected/unreachable: keep the delta for the next beat.
            self._pending_event = event
        if isinstance(resp, dict) and resp.get("resync_cache"):
            # The master pruned this instance's KV-index locations (breaker
            # ejection) and needs the full snapshot on the next beat —
            # deltas alone cannot rebuild what was dropped.
            self._resync_cache = True
        new_rpc = resp.get("master_rpc") if isinstance(resp, dict) else ""
        if new_rpc and new_rpc != self._client._addr:
            # A deposed master answered with the successor's address
            # (docs/FAULT_TOLERANCE.md): follow it — the next beat gets
            # `reregister` from the new master and a fresh lease.
            logger.info(
                "heartbeat re-pointing %s -> %s (master takeover)",
                self._client._addr, new_rpc,
            )
            self._client._addr = new_rpc
            # The successor runs a different process clock: stale echo
            # stamps would poison its offset lower bounds.
            self._client._clock_echo = None
        if resp.get("reregister") and not self._stop.is_set():
            # The stop guard matters: a slow in-flight beat straddling
            # shutdown would otherwise re-insert the instance AFTER the
            # graceful deregister revoked its lease — routing requests to
            # a closed endpoint until the fresh TTL lapsed.
            try:
                self._client.register(self._meta)
            except Exception:
                logger.warning("re-registration failed for %s", self._meta.name)
        return resp

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self._beat()
            except Exception:
                logger.exception("heartbeat failed for %s", self._meta.name)
