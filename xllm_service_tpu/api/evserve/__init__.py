"""Event-driven (selectors/epoll) HTTP+SSE front end for the control plane.

Selected via ServiceConfig.http_backend = "event" (the default); the
threaded stdlib backend remains available as "threaded". See
docs/FRONTEND.md for the design.
"""

from xllm_service_tpu.api.evserve.handler import EvHandler
from xllm_service_tpu.api.evserve.parser import (
    Headers,
    HttpRequest,
    ParseError,
    RequestParser,
)
from xllm_service_tpu.api.evserve.server import EventLoopHttpServer

__all__ = [
    "EvHandler",
    "EventLoopHttpServer",
    "Headers",
    "HttpRequest",
    "ParseError",
    "RequestParser",
]
