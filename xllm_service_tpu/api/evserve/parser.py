"""Incremental HTTP/1.1 request parser for the event-loop front end.

Zero-copy-ish push parser: the event loop feeds whatever bytes epoll
delivered, the parser emits complete requests (possibly several — clients
may pipeline). No request body streaming: the control plane's bodies are
small JSON documents (the KV data plane rides the instance tier's servers,
not this one), so bodies buffer fully before dispatch.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

MAX_HEAD_BYTES = 64 * 1024
MAX_BODY_BYTES = 64 * 1024 * 1024


class ParseError(Exception):
    """Malformed/oversized request. `status` is the HTTP status the
    connection should answer with before closing."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


class Headers:
    """Case-insensitive header map (the email.message.Message.get subset
    the handlers use)."""

    def __init__(self):
        self._d = {}

    def add(self, key: str, value: str) -> None:
        k = key.lower()
        if k in self._d:
            # Repeated headers join per RFC 9110 §5.2 (none of ours repeat,
            # but a client's duplicated Connection: must not be dropped).
            self._d[k] = self._d[k] + ", " + value
        else:
            self._d[k] = value

    def get(self, key: str, default=None):
        return self._d.get(key.lower(), default)

    def __contains__(self, key: str) -> bool:
        return key.lower() in self._d

    def items(self):
        return self._d.items()


class HttpRequest:
    __slots__ = ("method", "target", "version", "headers", "body", "keep_alive")

    def __init__(self, method: str, target: str, version: str,
                 headers: Headers, body: bytes):
        self.method = method
        self.target = target
        self.version = version
        self.headers = headers
        self.body = body
        conn_tokens = (headers.get("connection", "") or "").lower()
        if version == "HTTP/1.0":
            self.keep_alive = "keep-alive" in conn_tokens
        else:
            self.keep_alive = "close" not in conn_tokens


class RequestParser:
    """feed(data) -> list of complete HttpRequests; raises ParseError once
    the stream is unrecoverable (caller answers + closes)."""

    def __init__(self, max_head_bytes: int = MAX_HEAD_BYTES,
                 max_body_bytes: int = MAX_BODY_BYTES):
        self._buf = bytearray()
        self._head: Optional[Tuple[str, str, str, Headers]] = None
        self._body_len = 0
        self._max_head = max_head_bytes
        self._max_body = max_body_bytes

    def feed(self, data: bytes) -> List[HttpRequest]:
        self._buf += data
        out: List[HttpRequest] = []
        while True:
            req = self._try_parse_one()
            if req is None:
                return out
            out.append(req)

    def _try_parse_one(self) -> Optional[HttpRequest]:
        if self._head is None:
            end = self._buf.find(b"\r\n\r\n")
            if end < 0:
                if len(self._buf) > self._max_head:
                    raise ParseError(431, "request head too large")
                return None
            head = bytes(self._buf[:end])
            del self._buf[: end + 4]
            self._head = self._parse_head(head)
            headers = self._head[3]
            if "chunked" in (headers.get("transfer-encoding", "") or "").lower():
                raise ParseError(501, "chunked request bodies unsupported")
            try:
                self._body_len = int(headers.get("content-length", 0) or 0)
            except ValueError:
                raise ParseError(400, "bad Content-Length") from None
            if self._body_len < 0:
                raise ParseError(400, "bad Content-Length")
            if self._body_len > self._max_body:
                raise ParseError(413, "request body too large")
        if len(self._buf) < self._body_len:
            return None
        body = bytes(self._buf[: self._body_len])
        del self._buf[: self._body_len]
        method, target, version, headers = self._head
        self._head = None
        self._body_len = 0
        return HttpRequest(method, target, version, headers, body)

    @staticmethod
    def _parse_head(head: bytes) -> Tuple[str, str, str, Headers]:
        try:
            text = head.decode("iso-8859-1")
        except Exception:  # pragma: no cover — iso-8859-1 decodes anything
            raise ParseError(400, "undecodable request head") from None
        lines = text.split("\r\n")
        parts = lines[0].split()
        if len(parts) != 3 or not parts[2].startswith("HTTP/1."):
            raise ParseError(400, f"malformed request line: {lines[0]!r}")
        method, target, version = parts
        headers = Headers()
        for line in lines[1:]:
            if not line:
                continue
            name, sep, value = line.partition(":")
            if not sep or not name or name != name.strip():
                raise ParseError(400, f"malformed header line: {line!r}")
            headers.add(name, value.strip())
        return method, target, version, headers
