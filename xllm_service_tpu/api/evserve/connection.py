"""Per-connection state for the event-loop front end.

One Connection owns one accepted socket. The loop thread does all socket
I/O and selector bookkeeping; scheduler lanes and pool workers only ever
touch the thread-safe outbox (`enqueue`), which wakes the loop to drain.

Exchange lifecycle: the parser may buffer pipelined requests, but at most
one is in flight — the next starts only after the current response is
fully framed (Content-Length met or chunked terminator written). An SSE
exchange can outlive its pool worker by deferring (EvHandler.hold), so a
generation holds a connection, never a thread.
"""

from __future__ import annotations

import socket
import threading
import time
from collections import deque
from typing import Deque, List, Optional

from xllm_service_tpu.api.evserve.parser import HttpRequest, ParseError, RequestParser

# Coalesce outbox chunks up to this size per send() call: one syscall per
# readiness for the common SSE burst instead of one per token.
_SEND_COALESCE = 64 * 1024

# A client may pipeline, but a control-plane peer queueing this deep is
# abuse (each buffered request holds up to MAX_BODY_BYTES) — drop it.
_MAX_PIPELINED = 64


class Connection:
    def __init__(self, server, sock: socket.socket, addr):
        self.server = server
        self.sock = sock
        self.addr = addr
        self.parser = RequestParser(max_body_bytes=server.max_body_bytes)
        self._out: Deque[memoryview] = deque()
        self._out_bytes = 0
        self._mu = threading.Lock()
        self.closed = False
        self._close_after_flush = False
        # Loop-thread view of the selector registration (read may be paused
        # for backpressure; write tracks a non-empty outbox).
        self.events_mask = 0
        self.in_flight = None  # current EvHandler, loop-thread owned
        self.pending: Deque[HttpRequest] = deque()
        self.last_activity = time.monotonic()
        # Set (from the worker thread) when the current exchange switched to
        # chunked SSE — arms the slow-client buffer cap.
        self.streaming = False
        self.overflowed = False
        # Protocol error answered; later bytes are drained and DISCARDED —
        # the parser sits in a half-consumed state after a ParseError, so
        # feeding it again could buffer a rejected oversized body in full
        # and then dispatch the very request the client was told was bad.
        self.rejected = False

    # ------------------------------------------------------------------ #
    # any-thread side
    # ------------------------------------------------------------------ #

    def enqueue(self, data: bytes) -> bool:
        """Queue response bytes; returns False when the connection is gone
        (closed, or evicted as a slow client). Wakes the loop to flush."""
        if not data:
            return not self.closed
        with self._mu:
            if self.closed or self._close_after_flush:
                return False
            if (
                self.streaming
                and self._out_bytes + len(data) > self.server.max_stream_buffer
            ):
                # Slow client: the SSE producer outran the socket by a full
                # buffer. Drop the connection instead of buffering without
                # bound — the False return propagates up through SseWriter
                # to the scheduler, which cancels generation upstream.
                self.overflowed = True
                self.server.note_slow_client()
                self.server.post(self.close)
                return False
            self._out.append(memoryview(bytes(data)))
            self._out_bytes += len(data)
        self.server.request_flush(self)
        return True

    @property
    def buffered_bytes(self) -> int:
        return self._out_bytes

    # ------------------------------------------------------------------ #
    # loop-thread side
    # ------------------------------------------------------------------ #

    def on_readable(self) -> None:
        try:
            data = self.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self.close()
            return
        if not data:
            self.close()
            return
        self.last_activity = time.monotonic()
        if self.rejected:
            return  # drained and discarded; closing once the error flushes
        try:
            reqs = self.parser.feed(data)
        except ParseError as e:
            self.rejected = True
            body = (
                '{"error": {"message": %s, "type": "protocol_error"}}'
                % _json_str(e.message)
            ).encode()
            head = (
                f"HTTP/1.1 {e.status} {e.message[:40]}\r\n"
                f"Content-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
            ).encode()
            with self._mu:
                self._out.append(memoryview(head + body))
                self._out_bytes += len(head) + len(body)
                self._close_after_flush = True
            self._flush_ready()
            return
        if reqs:
            self.pending.extend(reqs)
            if len(self.pending) > _MAX_PIPELINED:
                self.close()
                return
            self.maybe_start_next()

    def maybe_start_next(self) -> None:
        if self.in_flight is None and self.pending and not self.closed:
            req = self.pending.popleft()
            self.server.start_exchange(self, req)

    def exchange_complete(self, handler, close: bool) -> None:
        """Loop thread: the in-flight response is fully framed."""
        if handler is not self.in_flight:
            return  # stale completion after a hard close
        self.in_flight = None
        self.streaming = False
        self.last_activity = time.monotonic()
        if close or getattr(handler, "close_connection", False):
            with self._mu:
                self._close_after_flush = True
            self._flush_ready()
        else:
            self.maybe_start_next()

    def on_writable(self) -> None:
        self._flush_ready()

    def _flush_ready(self) -> None:
        """Send as much buffered output as the socket accepts; manage the
        EVENT_WRITE registration and deferred close."""
        if self.closed:
            return
        while True:
            with self._mu:
                if not self._out:
                    break
                chunk = self._out[0]
                # Coalesce small chunks (SSE events are ~100 bytes each).
                if len(chunk) < _SEND_COALESCE and len(self._out) > 1:
                    parts: List[memoryview] = []
                    size = 0
                    while self._out and size < _SEND_COALESCE:
                        parts.append(self._out.popleft())
                        size += len(parts[-1])
                    chunk = memoryview(b"".join(parts))
                    self._out.appendleft(chunk)
            try:
                n = self.sock.send(chunk)
            except (BlockingIOError, InterruptedError):
                n = 0
            except OSError:
                self.close()
                return
            if n:
                self.last_activity = time.monotonic()
                with self._mu:
                    self._out_bytes -= n
                    if n == len(chunk):
                        self._out.popleft()
                    else:
                        self._out[0] = chunk[n:]
                if n < len(chunk):
                    break  # socket full
            else:
                break
        with self._mu:
            empty = not self._out
            close_now = empty and self._close_after_flush
        if close_now:
            self.close()
            return
        self.server.update_interest(self, want_write=not empty)

    def close(self) -> None:
        """Loop thread: tear the connection down now. Any later enqueue from
        a lane returns False, which cancels its stream upstream."""
        if self.closed:
            return
        with self._mu:
            self.closed = True
            self._out.clear()
            self._out_bytes = 0
        self.server.forget_connection(self)
        try:
            self.sock.close()
        except OSError:
            pass
        # Finalize any held exchange NOW: without this, a client that dies
        # mid-stream leaks the active_streams gauge and pins the handler
        # (plus its deadline timer closure) for the full request timeout.
        h, self.in_flight = self.in_flight, None
        if h is not None:
            h._complete(close=True)


def _json_str(s: str) -> str:
    import json

    return json.dumps(s)
