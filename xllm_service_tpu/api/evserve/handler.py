"""QuietHandler-compatible request adapter for the event-loop server.

Route code (master/instance handlers, SseWriter, HttpClientStream) is
written against the BaseHTTPRequestHandler surface: `headers`, `path`,
`send_response/send_header/end_headers`, `wfile.write`, plus the JSON
helpers. EvHandler provides that surface over a Connection outbox, so the
same handler functions run on either backend.

The one capability the threaded handler cannot offer: `hold()` without a
blocked thread. A deferred exchange parks the HTTP exchange on the
connection; scheduler lanes stream into it and a loop timer enforces the
request deadline — 1k concurrent SSE streams cost 1k sockets, not 1k
threads.
"""

from __future__ import annotations

import io
import threading
from http.client import responses as _REASONS
from typing import Callable, Optional

from xllm_service_tpu.api.evserve.parser import HttpRequest
from xllm_service_tpu.api.http_utils import HttpJsonApi


class _BodyWriter:
    """wfile shim: write() enqueues on the connection, raising
    BrokenPipeError when the client is gone so SseWriter/HttpClientStream
    error paths fire exactly as they do on a real socket."""

    def __init__(self, handler: "EvHandler"):
        self._h = handler

    def write(self, data: bytes) -> int:
        self._h._write_body(data)
        return len(data)

    def flush(self) -> None:  # enqueue already woke the loop
        pass


class EvHandler(HttpJsonApi):
    protocol_version = "HTTP/1.1"
    # Grace between the deadline fail() and abandoning the exchange
    # (class attr so tests can compress it).
    grace_s = 5.0

    def __init__(self, server, conn, request: HttpRequest):
        self.server = server
        self.conn = conn
        self.request = request
        self.headers = request.headers
        self.path = request.target
        self.command = request.method
        self.close_connection = not request.keep_alive
        self.wfile = _BodyWriter(self)
        # Raw-body readers (KV import posts octet-stream): the body is
        # already buffered, serve it back as a file.
        self.rfile = io.BytesIO(request.body)
        self._head_lines: list = []
        self._head_sent = False
        self._chunked = False
        self._content_length: Optional[int] = None
        self._body_written = 0
        self.deferred = False
        self._done = False
        self._done_mu = threading.Lock()
        self._timeout_handle = None
        self._grace_handle = None

    # -- HttpJsonApi contract ------------------------------------------- #
    def _read_body(self) -> bytes:
        return self.request.body

    # -- BaseHTTPRequestHandler surface --------------------------------- #
    def send_response(self, code: int, message: Optional[str] = None) -> None:
        reason = message or _REASONS.get(code, "")
        self._head_lines = [f"HTTP/1.1 {code} {reason}"]

    def send_header(self, keyword: str, value: str) -> None:
        k = keyword.lower()
        if k == "content-length":
            self._content_length = int(value)
        elif k == "transfer-encoding" and "chunked" in value.lower():
            self._chunked = True
            # Arms the slow-client buffer cap for this exchange.
            self.conn.streaming = True
        elif k == "connection" and "close" in value.lower():
            self.close_connection = True
        self._head_lines.append(f"{keyword}: {value}")

    def end_headers(self) -> None:
        if self._content_length is None and not self._chunked:
            # Unframed response: the only way to delimit it is to close.
            self.close_connection = True
        head = ("\r\n".join(self._head_lines) + "\r\n\r\n").encode("iso-8859-1")
        self._head_sent = True
        self.conn.enqueue(head)
        if self._content_length == 0:
            self._complete()

    def _write_body(self, data: bytes) -> None:
        if not self.conn.enqueue(data):
            raise BrokenPipeError("client disconnected")
        self._body_written += len(data)
        if (
            not self._chunked
            and self._content_length is not None
            and self._body_written >= self._content_length
        ):
            self._complete()

    # SseWriter.close() hook: the chunked terminator has been written.
    def on_sse_closed(self) -> None:
        self._complete()

    # -- deferred exchange ---------------------------------------------- #
    def hold(
        self, stream, timeout_s: float, fail: Callable[[], None]
    ) -> None:
        """Event-backend analog of the threaded handler's blocking
        `stream.done.wait()`: returns immediately, leaving the exchange
        parked on the connection. A loop timer enforces the deadline; a
        5 s grace follows the deadline fail (mirrors QuietHandler.hold)
        before the exchange is abandoned and the connection dropped."""
        def on_timeout() -> None:
            if stream.done.is_set():
                return
            try:
                fail()
            finally:
                # Arm under _done_mu: either _complete() already ran (don't
                # arm a timer nobody will cancel) or it will see the handle.
                with self._done_mu:
                    if not self._done:
                        self._grace_handle = self.server.call_later(
                            self.grace_s, on_grace
                        )

        def on_grace() -> None:
            if not stream.done.is_set():
                stream.abandon()
                self._complete(close=True)

        # Defer + gauge + timer all under _done_mu: a lane completing the
        # exchange concurrently either beats this block (we return — no
        # timer armed, no gauge bump) or _complete() sees the armed handle
        # and cancels it. Arming outside the lock would leak a 600 s timer
        # closure (pinning handler+connection+body) per lost race, and let
        # note_stream_end run before note_stream_begin (gauge reads -1).
        with self._done_mu:
            if self._done:
                return
            self.deferred = True
            self.server.note_stream_begin()
            self._timeout_handle = self.server.call_later(
                timeout_s, on_timeout
            )

    def finalize_after_app(self) -> None:
        """Pool worker, after the route function returned: a non-deferred
        exchange must be complete by now; repair it if the handler fell
        through without responding."""
        if self.deferred or self._done:
            return
        if not self._head_sent:
            try:
                self.send_error_json(500, "handler produced no response")
            except Exception:
                self._complete(close=True)
        else:
            self._complete(close=True)

    def _complete(self, close: bool = False) -> None:
        with self._done_mu:
            if self._done:
                return
            self._done = True
            was_deferred = self.deferred
            handles = (self._timeout_handle, self._grace_handle)
            self._timeout_handle = self._grace_handle = None
        for h in handles:
            if h is not None:
                h.cancel()
        if was_deferred:
            self.server.note_stream_end()
        self.server.post(lambda: self.conn.exchange_complete(self, close))
