"""selectors/epoll event-loop HTTP server for the control plane.

One loop thread owns every socket (accept, read, write readiness, timers,
idle sweep); a small worker pool runs route handlers; scheduler lanes
stream SSE tokens by enqueueing into connection outboxes and waking the
loop through a socketpair. Concurrency therefore scales with open sockets
— the ThreadingHTTPServer backend spends a thread per connection and a
second blocked thread per in-flight generation, which caps the control
plane near the thread budget; this backend carries >1k concurrent SSE
streams on loop + pool threads alone (tests/test_evserve.py drives 1024).

The reference's brpc front end is the same shape: an event-driven IO layer
with ProgressiveAttachment streams detached from worker threads
(call_data.h:150-193); this subsystem is its stdlib-only analog.
"""

from __future__ import annotations

import heapq
import logging
import selectors
import socket
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from itertools import count
from typing import Callable, Deque, Dict, List, Optional, Set

from xllm_service_tpu.api.evserve.connection import Connection
from xllm_service_tpu.api.evserve.handler import EvHandler
from xllm_service_tpu.api.evserve.parser import HttpRequest
from xllm_service_tpu.obs import MetricsRegistry

logger = logging.getLogger(__name__)

_IDLE_SWEEP_S = 1.0

# Loop-lag buckets (ms): the event loop's per-wakeup busy time is usually
# sub-millisecond — a fatter tail here means handlers or flushes are
# stalling every stream the loop carries.
_LOOP_LAG_BUCKETS_MS = (
    0.05, 0.1, 0.2, 0.5, 1, 2, 5, 10, 20, 50, 100, 200, 500, 1000,
)


class TimerHandle:
    __slots__ = ("deadline", "fn", "cancelled")

    def __init__(self, deadline: float, fn: Callable[[], None]):
        self.deadline = deadline
        self.fn = fn
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True
        # Drop the closure now: the heap entry itself lives until the
        # deadline lapses, and a deadline timer's closure holds the whole
        # handler/connection/request graph — at rate x timeout_s scale
        # that retention dominates memory, not the live concurrency.
        self.fn = None


class EventLoopHttpServer:
    """Uniform server surface (start/stop/host/port/stats) shared with
    HttpServerThread, selected by ServiceConfig.http_backend."""

    def __init__(
        self,
        host: str,
        port: int,
        app: Callable[[EvHandler], None],
        *,
        name: str = "evhttp",
        workers: int = 32,
        max_connections: int = 4096,
        idle_timeout_s: float = 120.0,
        max_stream_buffer: int = 512 * 1024,
        drain_timeout_s: float = 5.0,
        # Per-request body cap. The threaded backend never enforced one, so
        # the default must clear every legitimate control-plane body — the
        # biggest is a base64 multimodal part (video ~100 MB); 256 MB keeps
        # that headroom while still bounding a hostile Content-Length.
        max_body_bytes: int = 256 * 1024 * 1024,
    ):
        self._app = app
        self._name = name
        self.max_stream_buffer = max_stream_buffer
        self.max_body_bytes = max_body_bytes
        self._max_connections = max_connections
        self._idle_timeout_s = idle_timeout_s
        self._drain_timeout_s = drain_timeout_s

        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, port))
        self._lsock.listen(min(max_connections, 1024))
        self._lsock.setblocking(False)
        self.host, self.port = self._lsock.getsockname()[:2]

        self._sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)

        self._mu = threading.Lock()
        self._posted: Deque[Callable[[], None]] = deque()
        self._dirty: Set[Connection] = set()
        self._timers: List = []  # heap of (deadline, seq, TimerHandle)
        self._timer_seq = count()
        self._conns: Set[Connection] = set()

        self._pool = ThreadPoolExecutor(
            max_workers=max(2, workers), thread_name_prefix=f"{name}-worker"
        )
        self._running = False
        self._draining = False
        self._drain_deadline = 0.0
        self._thread = threading.Thread(
            target=self._loop, name=f"{name}-loop", daemon=True
        )

        # stats (gauges derived, counters monotonic)
        self._accepted_total = 0
        self._rejected_connections = 0
        self._requests_total = 0
        self._slow_client_closes = 0
        self._active_streams = 0

        # Per-plane registry (the master merges it under a plane label):
        # the loop-lag histogram is the event backend's health signal —
        # one loop thread carries every stream, so its busy time per
        # wakeup bounds how stale every connection's IO can get.
        self.metrics = MetricsRegistry()
        self._m_loop_lag = self.metrics.histogram(
            "xllm_http_loop_lag_ms",
            "Event-loop busy time per wakeup (non-select work)",
            buckets=_LOOP_LAG_BUCKETS_MS,
        )

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #

    def start(self) -> None:
        self._running = True
        self._sel.register(self._lsock, selectors.EVENT_READ, "listen")
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        self._thread.start()

    def stop(self, drain_s: Optional[float] = None) -> None:
        """Stop accepting, give in-flight streams `drain_s` to finish, then
        tear everything down."""
        if not self._running:
            return
        timeout = self._drain_timeout_s if drain_s is None else drain_s

        def begin() -> None:
            self._draining = True
            self._drain_deadline = time.monotonic() + timeout
            try:
                self._sel.unregister(self._lsock)
            except (KeyError, ValueError):
                pass
            try:
                self._lsock.close()
            except OSError:
                pass

        self.post(begin)
        self._thread.join(timeout=timeout + 5.0)
        self._running = False
        self.wake()
        self._pool.shutdown(wait=False)

    # ------------------------------------------------------------------ #
    # any-thread API (Connection/EvHandler call these)
    # ------------------------------------------------------------------ #

    def wake(self) -> None:
        try:
            self._wake_w.send(b"\0")
        except (BlockingIOError, OSError):
            pass  # wake pipe saturated: loop is already waking

    def post(self, fn: Callable[[], None]) -> None:
        with self._mu:
            self._posted.append(fn)
        self.wake()

    def request_flush(self, conn: Connection) -> None:
        with self._mu:
            self._dirty.add(conn)
        self.wake()

    def call_later(self, delay_s: float, fn: Callable[[], None]) -> TimerHandle:
        t = TimerHandle(time.monotonic() + delay_s, fn)
        with self._mu:
            heapq.heappush(self._timers, (t.deadline, next(self._timer_seq), t))
        self.wake()
        return t

    def note_slow_client(self) -> None:
        with self._mu:
            self._slow_client_closes += 1

    def note_stream_begin(self) -> None:
        with self._mu:
            self._active_streams += 1

    def note_stream_end(self) -> None:
        with self._mu:
            self._active_streams -= 1

    def stats(self) -> Dict[str, int]:
        with self._mu:
            conns = list(self._conns)
            return {
                "backend": "event",
                "open_connections": len(conns),
                "active_streams": self._active_streams,
                "buffered_bytes": sum(c.buffered_bytes for c in conns),
                "accepted_total": self._accepted_total,
                "rejected_connections": self._rejected_connections,
                "requests_total": self._requests_total,
                "slow_client_closes": self._slow_client_closes,
            }

    # ------------------------------------------------------------------ #
    # loop-thread internals
    # ------------------------------------------------------------------ #

    def update_interest(self, conn: Connection, want_write: bool) -> None:
        """Loop thread: recompute the selector registration. Read pauses
        while a NON-streaming outbox sits over the buffer cap (streaming
        overflow drops the client in enqueue instead) — the socket stops
        accepting new pipelined requests until the client drains what it
        already owes us. Read can only pause with bytes buffered, so the
        mask is never empty."""
        want_read = (
            conn.streaming
            or conn.buffered_bytes <= self.max_stream_buffer
        )
        events = (
            (selectors.EVENT_READ if want_read else 0)
            | (selectors.EVENT_WRITE if want_write else 0)
        )
        if conn.closed or conn.events_mask == events:
            return
        try:
            self._sel.modify(conn.sock, events, conn)
            conn.events_mask = events
        except (KeyError, ValueError, OSError):
            pass

    def forget_connection(self, conn: Connection) -> None:
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        with self._mu:
            self._conns.discard(conn)

    def start_exchange(self, conn: Connection, request: HttpRequest) -> None:
        with self._mu:
            self._requests_total += 1
        handler = EvHandler(self, conn, request)
        conn.in_flight = handler
        self._pool.submit(self._run_app, handler)

    def _run_app(self, handler: EvHandler) -> None:
        try:
            self._app(handler)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-response
        except Exception:
            logger.exception("%s: handler crashed on %s %s",
                            self._name, handler.command, handler.path)
            if not handler._head_sent and not handler._done:
                try:
                    handler.send_error_json(500, "internal server error")
                except Exception:
                    pass
        finally:
            try:
                handler.finalize_after_app()
            except Exception:
                logger.exception("%s: finalize failed", self._name)

    def _accept(self) -> None:
        while True:
            try:
                sock, addr = self._lsock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            self._accepted_total += 1
            if self._draining or len(self._conns) >= self._max_connections:
                self._rejected_connections += 1
                self._shed(sock)
                continue
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = Connection(self, sock, addr)
            with self._mu:
                self._conns.add(conn)
            self._sel.register(sock, selectors.EVENT_READ, conn)
            conn.events_mask = selectors.EVENT_READ

    _SHED_RESPONSE = (
        b"HTTP/1.1 503 Service Unavailable\r\n"
        b"Content-Type: application/json\r\n"
        b'Content-Length: 63\r\nConnection: close\r\n\r\n'
        b'{"error": {"message": "server overloaded", "type": "shedding"}}'
    )

    def _shed(self, sock: socket.socket) -> None:
        """Refuse an over-capacity (or draining) connection with a one-shot
        503 — load balancers and clients see an explicit shed, not a hang.
        Drain whatever request bytes already arrived first so close() sends
        FIN rather than RST-ing the 503 out of the client's receive queue."""
        sock.setblocking(False)
        try:
            sock.recv(65536)
        except OSError:
            pass
        try:
            sock.send(self._SHED_RESPONSE)
            sock.shutdown(socket.SHUT_WR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    def _next_timeout(self, now: float) -> float:
        with self._mu:
            if self._timers:
                deadline = self._timers[0][0]
                return max(0.0, min(deadline - now, _IDLE_SWEEP_S))
        return _IDLE_SWEEP_S

    def _loop(self) -> None:
        last_sweep = time.monotonic()
        while True:
            now = time.monotonic()
            if self._draining:
                busy = any(c.in_flight is not None for c in self._conns)
                if not busy or now >= self._drain_deadline:
                    break
            try:
                events = self._sel.select(self._next_timeout(now))
            except OSError:
                events = []
            busy_t0 = time.monotonic()
            for key, mask in events:
                tag = key.data
                if tag == "listen":
                    self._accept()
                elif tag == "wake":
                    try:
                        while self._wake_r.recv(4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                else:
                    conn: Connection = tag
                    if mask & selectors.EVENT_READ and not conn.closed:
                        conn.on_readable()
                    if mask & selectors.EVENT_WRITE and not conn.closed:
                        conn.on_writable()
            self._run_posted()
            self._flush_dirty()
            self._fire_timers()
            now = time.monotonic()
            self._m_loop_lag.observe((now - busy_t0) * 1000.0)
            if now - last_sweep >= _IDLE_SWEEP_S:
                last_sweep = now
                self._sweep_idle(now)
        # drain finished (or timed out): hard-close the stragglers
        for conn in list(self._conns):
            conn.close()
        self._run_posted()
        try:
            self._sel.close()
        except OSError:
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass

    def _run_posted(self) -> None:
        while True:
            with self._mu:
                if not self._posted:
                    return
                fn = self._posted.popleft()
            try:
                fn()
            except Exception:
                logger.exception("%s: posted callback failed", self._name)

    def _flush_dirty(self) -> None:
        with self._mu:
            dirty = list(self._dirty)
            self._dirty.clear()
        for conn in dirty:
            if not conn.closed:
                conn._flush_ready()

    def _fire_timers(self) -> None:
        now = time.monotonic()
        due = []
        with self._mu:
            while self._timers and self._timers[0][0] <= now:
                _, _, t = heapq.heappop(self._timers)
                if not t.cancelled:
                    due.append(t)
        for t in due:
            # Timer bodies may touch the scheduler — never run them on the
            # loop thread.
            self._pool.submit(self._run_timer, t)

    @staticmethod
    def _run_timer(t: TimerHandle) -> None:
        fn = t.fn  # cancel() may null it concurrently
        try:
            if not t.cancelled and fn is not None:
                fn()
        except Exception:
            logger.exception("evserve timer failed")

    def _sweep_idle(self, now: float) -> None:
        if self._idle_timeout_s <= 0:
            return
        for conn in list(self._conns):
            if (
                conn.in_flight is None
                and not conn.pending
                and now - conn.last_activity > self._idle_timeout_s
            ):
                conn.close()
