"""Event-driven SSE load client.

Drives N concurrent streaming completions against a front end from ONE
thread (selectors on the client side too) — the only honest way to prove
the server holds >1k concurrent streams, since a thread-per-stream client
would hit the same wall the threaded server does. Used by
tests/test_evserve.py and scripts/bench_frontend.py.
"""

from __future__ import annotations

import json
import selectors
import socket
import time
from typing import Any, Dict, List, Optional

_RECV = 65536


class StreamResult:
    __slots__ = ("ok", "error", "events", "ttft_s", "total_s", "status")

    def __init__(self):
        self.ok = False
        self.error: Optional[str] = None
        self.events: List[str] = []  # raw SSE data payloads, "[DONE]" last
        self.ttft_s: Optional[float] = None
        self.total_s: Optional[float] = None
        self.status: Optional[int] = None


class _Stream:
    def __init__(self, sock: socket.socket, payload: bytes):
        self.sock = sock
        self.to_send = memoryview(payload)
        self.result = StreamResult()
        self.t0 = time.monotonic()
        self.raw = bytearray()  # undecoded wire bytes
        self.head_done = False
        self.chunked = False
        self.chunk_need = -1  # -1: awaiting size line; >=0: data bytes left
        self.body = bytearray()  # decoded SSE text stream
        self.done = False

    def finish(self, ok: bool, error: Optional[str] = None) -> None:
        self.done = True
        self.result.ok = ok
        self.result.error = error
        self.result.total_s = time.monotonic() - self.t0
        try:
            self.sock.close()
        except OSError:
            pass


def _build_request(path: str, host: str, body: Dict[str, Any]) -> bytes:
    data = json.dumps(body).encode()
    return (
        f"POST {path} HTTP/1.1\r\nHost: {host}\r\n"
        f"Content-Type: application/json\r\nContent-Length: {len(data)}\r\n"
        f"Connection: close\r\n\r\n"
    ).encode() + data


def run_sse_load(
    addr: str,
    path: str,
    bodies: List[Dict[str, Any]],
    timeout_s: float = 120.0,
) -> List[StreamResult]:
    """Open one connection per body, stream all of them concurrently, and
    return per-stream results in input order."""
    host, _, port = addr.partition(":")
    target = (host, int(port))
    sel = selectors.DefaultSelector()
    streams: List[_Stream] = []
    for body in bodies:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setblocking(False)
        try:
            sock.connect(target)
        except BlockingIOError:
            pass
        except OSError as e:
            st = _Stream(sock, b"")
            st.finish(False, f"connect: {e}")
            streams.append(st)
            continue
        st = _Stream(sock, _build_request(path, addr, body))
        streams.append(st)
        sel.register(sock, selectors.EVENT_WRITE, st)

    live = sum(1 for s in streams if not s.done)
    deadline = time.monotonic() + timeout_s
    while live and time.monotonic() < deadline:
        for key, mask in sel.select(timeout=0.5):
            st: _Stream = key.data
            if st.done:
                continue
            if mask & selectors.EVENT_WRITE:
                err = st.sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
                if err:
                    sel.unregister(st.sock)
                    st.finish(False, f"connect: errno {err}")
                    live -= 1
                    continue
                try:
                    n = st.sock.send(st.to_send)
                except (BlockingIOError, InterruptedError):
                    continue
                except OSError as e:
                    sel.unregister(st.sock)
                    st.finish(False, f"send: {e}")
                    live -= 1
                    continue
                st.to_send = st.to_send[n:]
                if not len(st.to_send):
                    sel.modify(st.sock, selectors.EVENT_READ, st)
                continue
            try:
                data = st.sock.recv(_RECV)
            except (BlockingIOError, InterruptedError):
                continue
            except OSError as e:
                sel.unregister(st.sock)
                st.finish(False, f"recv: {e}")
                live -= 1
                continue
            if not data:
                sel.unregister(st.sock)
                st.finish(False, "connection closed mid-stream")
                live -= 1
                continue
            st.raw += data
            fin = _consume(st)
            if fin is not None:
                sel.unregister(st.sock)
                st.finish(*fin)
                live -= 1
    for st in streams:
        if not st.done:
            try:
                sel.unregister(st.sock)
            except (KeyError, ValueError):
                pass
            st.finish(False, "timeout")
    sel.close()
    return [s.result for s in streams]


def _consume(st: _Stream):
    """Advance one stream's parser; returns (ok, error) when finished,
    None while still streaming."""
    if not st.head_done:
        end = st.raw.find(b"\r\n\r\n")
        if end < 0:
            return None
        head = bytes(st.raw[:end]).decode("iso-8859-1")
        del st.raw[: end + 4]
        line = head.split("\r\n")[0].split()
        st.result.status = int(line[1]) if len(line) > 1 else 0
        st.chunked = "transfer-encoding: chunked" in head.lower()
        st.head_done = True
        if st.result.status != 200:
            return False, f"HTTP {st.result.status}"
        if not st.chunked:
            return False, "response not chunked"
    # chunked transfer decoding
    while True:
        if st.chunk_need < 0:
            nl = st.raw.find(b"\r\n")
            if nl < 0:
                break
            try:
                size = int(bytes(st.raw[:nl]).split(b";")[0], 16)
            except ValueError:
                return False, "bad chunk size"
            del st.raw[: nl + 2]
            if size == 0:
                return _finish_events(st)
            st.chunk_need = size
        else:
            if len(st.raw) < st.chunk_need + 2:
                break
            st.body += st.raw[: st.chunk_need]
            del st.raw[: st.chunk_need + 2]  # data + CRLF
            st.chunk_need = -1
            ret = _drain_events(st)
            if ret is not None:
                return ret
    return None


def _drain_events(st: _Stream):
    while True:
        sep = st.body.find(b"\n\n")
        if sep < 0:
            return None
        event = bytes(st.body[:sep]).decode("utf-8", "replace")
        del st.body[: sep + 2]
        for line in event.split("\n"):
            if not line.startswith("data: "):
                continue
            payload = line[len("data: "):]
            if st.result.ttft_s is None:
                st.result.ttft_s = time.monotonic() - st.t0
            st.result.events.append(payload)
            if payload == "[DONE]":
                return True, None
    return None


def _finish_events(st: _Stream):
    _drain_events(st)
    if st.result.events and st.result.events[-1] == "[DONE]":
        return True, None
    return False, "stream ended without [DONE]"
