"""Wire protocol for the service <-> instance control plane.

The reference speaks protobuf over brpc (proto/xllm_rpc_service.proto:
HeartbeatRequest :60-65, DisaggStreamGeneration(s) :120-136, service
:138-149) plus OpenAI JSON over HTTP with three injected fields
(service_request_id, token_ids, routing — http_service/service.cpp:334-341,
:405-412). This stack keeps the exact message shapes but carries them as
JSON over HTTP: one serialization across client, control, and coordination
planes, zero codegen, and the payloads are the same dicts the store
replicates.

Endpoints (instance-facing, on the master's rpc_port — mirrors the proto
service methods):
  POST /rpc/hello          {name}                          -> {ok}
  POST /rpc/register       {meta}                          -> {ok, lease_ttl_s}
  POST /rpc/heartbeat      {name, load_metrics?, latency_metrics?,
                            cache_event?}                  -> {ok, reregister?}
  POST /rpc/generations    {gens: [RequestOutput...]}      -> {cont: {srid: bool}}
  GET  /rpc/instance_info?name=                            -> meta
  GET  /rpc/static_prefill_list                            -> {instances: [...]}
  GET  /rpc/static_decode_list                             -> {instances: [...]}

Fenced failover additions (docs/FAULT_TOLERANCE.md, control plane):
every master->instance RPC body carries `master_epoch` (the fencing
epoch committed by the election transaction); instances persist the
highest seen and reject lower with HTTP 412 + {"fenced": true}. A
freshly elected master calls the instance-side

  POST /reconcile  {master_epoch, master, master_rpc, known: [wire srids],
                    orphan_ttl_s}
    -> {ok, name, epoch, manifest: [{service_request_id, request_ids,
        owning_epoch, delivered_tokens, prompt_tokens}], orphans,
        load_metrics, cache_hashes}

to rebuild its load/inflight/cache view; in-flight srids not in `known`
are reaped instance-side after orphan_ttl_s (engine work cancelled,
blocks freed), and the instance re-points heartbeats/pushes at
`master_rpc`.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from xllm_service_tpu.common.types import (
    FinishReason,
    LogProb,
    LogProbData,
    RequestOutput,
    SequenceOutput,
    Status,
    StatusCode,
    Usage,
)

# ---------------------------------------------------------------------------
# RequestOutput serde (proto analog: DisaggStreamGeneration, proto:120-136)
# ---------------------------------------------------------------------------


def _logprob_to_json(lp: LogProb) -> Dict[str, Any]:
    return {
        "token": lp.data.token,
        "token_id": lp.data.token_id,
        "logprob": lp.data.logprob,
        "top_logprobs": [
            {"token": t.token, "token_id": t.token_id, "logprob": t.logprob}
            for t in lp.top_logprobs
        ],
    }


def _logprob_from_json(j: Dict[str, Any]) -> LogProb:
    return LogProb(
        data=LogProbData(j.get("token", ""), int(j.get("token_id", 0)),
                         float(j.get("logprob", 0.0))),
        top_logprobs=[
            LogProbData(t.get("token", ""), int(t.get("token_id", 0)),
                        float(t.get("logprob", 0.0)))
            for t in j.get("top_logprobs", [])
        ],
    )


def output_to_json(out: RequestOutput) -> Dict[str, Any]:
    j: Dict[str, Any] = {
        "request_id": out.request_id,
        "service_request_id": out.service_request_id,
        "status_code": int(out.status.code),
        "status_message": out.status.message,
        "finished": out.finished,
        "cancelled": out.cancelled,
        "outputs": [
            {
                "index": s.index,
                "text": s.text,
                "token_ids": list(s.token_ids),
                "finish_reason": s.finish_reason.to_string(),
                "logprobs": [_logprob_to_json(lp) for lp in s.logprobs],
            }
            for s in out.outputs
        ],
    }
    if out.usage is not None:
        j["usage"] = {
            "num_prompt_tokens": out.usage.num_prompt_tokens,
            "num_generated_tokens": out.usage.num_generated_tokens,
        }
    return j


def output_from_json(j: Dict[str, Any]) -> RequestOutput:
    usage = None
    if "usage" in j and j["usage"] is not None:
        usage = Usage(
            num_prompt_tokens=int(j["usage"].get("num_prompt_tokens", 0)),
            num_generated_tokens=int(j["usage"].get("num_generated_tokens", 0)),
        )
    outputs = []
    for s in j.get("outputs", []):
        fr = s.get("finish_reason")
        outputs.append(
            SequenceOutput(
                index=int(s.get("index", 0)),
                text=s.get("text", ""),
                token_ids=[int(t) for t in s.get("token_ids", [])],
                finish_reason=FinishReason(fr) if fr else FinishReason.NONE,
                logprobs=[_logprob_from_json(lp) for lp in s.get("logprobs", [])],
            )
        )
    return RequestOutput(
        request_id=j.get("request_id", ""),
        service_request_id=j.get("service_request_id", ""),
        status=Status(StatusCode(int(j.get("status_code", 0))),
                      j.get("status_message", "")),
        outputs=outputs,
        usage=usage,
        finished=bool(j.get("finished", False)),
        cancelled=bool(j.get("cancelled", False)),
    )


# ---------------------------------------------------------------------------
# Forwarded-request augmentation (reference: service.cpp:334-341, 405-412)
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# KV handoff framing (PD disaggregation data plane)
# ---------------------------------------------------------------------------
#
# The DCN transport for KVHandoff payloads: one JSON header line, a NUL, then
# the raw KV bytes (C-order). The reference's analog is an engine-side RDMA
# pull keyed by the relayed cluster_ids/k_cache_ids handles (types.h:174-177);
# here the prefill side pushes over HTTP and the ids are chained block hashes.

import json as _json


def kv_frame_to_bytes(header: Dict[str, Any], kv=None) -> bytes:
    """Generic /kv/import frame: one JSON header, a NUL, then raw KV bytes
    (C-order). The monolithic handoff, the pipelined session's chunk
    messages, and the fabric /kv/fetch responses share this layout;
    `kv_dtype`/`kv_shape` are injected when a payload rides the body (the
    pull plane sends header-only frames).

    Sharded payloads (a tp>1 holder — docs/SHARDING.md): a device array
    sharded on the cache-head axis, or an already-split
    `shard_wire.ShardedKV`, serializes as N per-shard block sets
    back-to-back with `kv_shards` (per-shard head counts) and
    `kv_shard_shape` (the LOGICAL full shape) in the header — each
    shard's bytes come off its own device, no cross-shard host gather.
    Deliberately NOT `kv_shape`: the body's byte order is per-shard, so
    a receiver that doesn't know the shard axis must see "no payload"
    (and degrade to recompute / reject the frame) rather than
    frombuffer-reshape scrambled bytes that happen to have the right
    element count."""
    if kv is not None:
        import numpy as np

        from xllm_service_tpu.parallel.shard_wire import ShardedKV, to_host

        kv = to_host(kv)
        header = dict(header)
        header["kv_dtype"] = str(kv.dtype)
        if isinstance(kv, ShardedKV):
            header["kv_shards"] = kv.head_sizes
            header["kv_shard_shape"] = list(kv.shape)
            body = kv.tobytes()
        else:
            header["kv_shape"] = list(kv.shape)
            body = np.asarray(kv).tobytes()
    else:
        body = b""
    return _json.dumps(header).encode("utf-8") + b"\x00" + body


def kv_frame_split(data: bytes) -> "tuple[Dict[str, Any], bytes]":
    """Split one /kv/import frame into (header_dict, body_bytes)."""
    sep = data.index(b"\x00")
    return _json.loads(data[:sep].decode("utf-8")), data[sep + 1:]


def resolve_kv_dtype(name: str):
    """Wire dtype name -> np.dtype. bfloat16 (and friends) need ml_dtypes
    (jax ships it); np.dtype handles the standard names. Shared by the
    bytes plane (kv_frame_array) and the pull plane so the two can never
    diverge on a dtype-name fix."""
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def kv_frame_array(header: Dict[str, Any], body: bytes):
    """Decode a frame's body into the array its header describes (None
    for header-only frames). A `kv_shards` header yields a
    `shard_wire.ShardedKV` of the per-shard pieces — the consumer's
    executor lands each piece straight onto its own kv_cache_sharding
    (shard_wire.assemble) instead of re-gathering on the host; every
    shape gate keeps working because ShardedKV.shape is the logical full
    shape."""
    import numpy as np

    shards = header.get("kv_shards")
    if "kv_shape" not in header and not shards:
        return None
    dt = resolve_kv_dtype(header["kv_dtype"])
    if not shards:
        return np.frombuffer(body, dtype=dt).reshape(header["kv_shape"])
    shape = list(header["kv_shard_shape"])
    from xllm_service_tpu.parallel.shard_wire import HEAD_AXIS, ShardedKV

    pieces, off = [], 0
    per_head = 1
    for i, d in enumerate(shape):
        if i != HEAD_AXIS:
            per_head *= int(d)
    for h in shards:
        n = per_head * int(h)
        piece_shape = [
            int(h) if i == HEAD_AXIS else int(d)
            for i, d in enumerate(shape)
        ]
        # offset/count frombuffer: zero-copy views into the body, like
        # the flat branch above (slicing `body` would copy every shard).
        pieces.append(
            np.frombuffer(body, dtype=dt, count=n, offset=off).reshape(
                piece_shape
            )
        )
        off += n * dt.itemsize
    return ShardedKV(pieces)


def handoff_header(h, extra: Dict[str, Any]) -> Dict[str, Any]:
    """KV-free wire header for one handoff — the /kv/import frame header
    minus the kv_dtype/kv_shape fields kv_frame_to_bytes injects when the
    payload rides the body."""
    return {
        "request_id": h.request_id,
        "token_ids": list(h.token_ids),
        "first_token": int(h.first_token),
        "first_logprob": float(h.first_logprob),
        "num_full_blocks": int(h.num_full_blocks),
        "block_hashes": [b.hex() for b in h.block_hashes],
        "usage_prompt_tokens": int(h.usage_prompt_tokens),
        "kv_start_block": int(getattr(h, "kv_start_block", 0) or 0),
        **extra,
    }


def handoff_from_parts(header: Dict[str, Any], body: bytes):
    """Build a KVHandoff from an already-split frame (callers that peeked
    at the header — e.g. the /kv/import session dispatch — must not pay a
    second JSON decode of a token_ids-sized header)."""
    from xllm_service_tpu.runtime.engine import KVHandoff

    kv = kv_frame_array(header, body)
    h = KVHandoff(
        request_id=header["request_id"],
        token_ids=[int(t) for t in header["token_ids"]],
        first_token=int(header["first_token"]),
        first_logprob=float(header["first_logprob"]),
        num_full_blocks=int(header["num_full_blocks"]),
        block_hashes=[bytes.fromhex(x) for x in header["block_hashes"]],
        kv=kv,
        usage_prompt_tokens=int(header.get("usage_prompt_tokens", 0)),
        kv_start_block=int(header.get("kv_start_block", 0) or 0),
    )
    return h


def parse_prompt_field(prompt: Any) -> "tuple[str, List[int], str]":
    """OpenAI `prompt` accepts a string or an array of token ids.
    Returns (text, token_ids, error); exactly one of text/token_ids is
    filled on success. Batched string arrays are rejected explicitly."""
    if isinstance(prompt, str):
        return prompt, [], ""
    if isinstance(prompt, list):
        if not prompt:
            return "", [], "prompt is empty"
        if all(isinstance(t, int) for t in prompt):
            return "", [int(t) for t in prompt], ""
        return "", [], "batched string prompts are not supported; send one string"
    return "", [], "prompt must be a string or an array of token ids"


def augment_forwarded_request(
    body: Dict[str, Any],
    service_request_id: str,
    token_ids: List[int],
    routing,
    decode_response_to_service: bool = True,
    master_epoch: int = 0,
    kv_fabric: Optional[Dict[str, Any]] = None,
    trace: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Inject the service-side fields so the engine skips re-tokenization
    and knows its PD pair. `decode_response_to_service=False` selects the
    alternate PD response topology (reference: service.h:61-71 env switch):
    the decode peer streams tokens back THROUGH the prefill instance
    instead of pushing to the master directly. `master_epoch` is the
    dispatching master's fencing epoch (docs/FAULT_TOLERANCE.md): the
    instance persists the highest seen and 412-rejects anything lower, so
    a deposed master cannot double-dispatch into the fleet."""
    fwd = dict(body)
    fwd["service_request_id"] = service_request_id
    fwd["token_ids"] = list(token_ids)
    fwd["routing"] = routing.to_json()
    if not decode_response_to_service:
        fwd["routing"]["decode_response_to_service"] = False
    if master_epoch:
        fwd["master_epoch"] = int(master_epoch)
    if kv_fabric:
        # Prefix-fabric fetch hint (docs/KV_CACHE.md): the fleet-best
        # prefix holder for this prompt; the instance pulls the gap over
        # /kv/fetch while chunk-prefilling the uncovered tail.
        fwd["kv_fabric"] = dict(kv_fabric)
    if trace:
        # Distributed-tracing context (docs/OBSERVABILITY.md): the
        # instance threads it through every downstream plane it opens
        # (KV handoff, fabric fetch, encoder forward) and tags its span
        # ring emissions with the trace's request.
        fwd["trace"] = dict(trace)
    return fwd


def sampling_from_body(body, cfg, vocab_size=None):
    """OpenAI request body -> SamplingParams (forwarded and direct
    traffic share it; cfg supplies the max-new-tokens default; pass
    vocab_size to reject out-of-vocabulary logit_bias ids). Unseeded
    sampling draws a fresh per-request seed — only an explicit client
    seed (0 included) gives the deterministic stream."""
    import os

    from xllm_service_tpu.ops.sampling import SamplingParams

    max_tokens = int(
        body.get("max_tokens") or body.get("max_completion_tokens") or 0
    )
    lp = body.get("logprobs")
    top_lp = int(body.get("top_logprobs", 0) or 0)
    raw_seed = body.get("seed")
    seed = (
        int(raw_seed)
        if raw_seed is not None
        else int.from_bytes(os.urandom(4), "little")
    )
    raw_bias = body.get("logit_bias")
    if raw_bias is None:
        raw_bias = {}
    if not isinstance(raw_bias, dict):
        raise ValueError("logit_bias must be an object of token_id: bias")
    if len(raw_bias) > 300:
        raise ValueError("logit_bias supports at most 300 entries")
    try:
        # OpenAI clamps biases to [-100, 100]
        logit_bias = tuple(
            (int(k), max(-100.0, min(100.0, float(v))))
            for k, v in raw_bias.items()
        )
    except (TypeError, ValueError):
        raise ValueError("logit_bias must map token ids to numbers")
    if any(t < 0 for t, _ in logit_bias):
        raise ValueError("logit_bias token ids must be non-negative")
    if vocab_size and any(t >= vocab_size for t, _ in logit_bias):
        raise ValueError(
            f"logit_bias token ids must be < vocab size {vocab_size}"
        )
    return SamplingParams(
        temperature=float(body.get("temperature", 1.0)),
        top_p=float(body.get("top_p", 1.0)),
        top_k=int(body.get("top_k", 0) or 0),
        seed=seed,
        logprobs=bool(lp),
        top_logprobs=top_lp if top_lp else (int(lp) if isinstance(lp, int) else 0),
        max_new_tokens=max_tokens or cfg.max_new_tokens_default,
        ignore_eos=bool(body.get("ignore_eos", False)),
        presence_penalty=float(body.get("presence_penalty", 0.0) or 0.0),
        frequency_penalty=float(body.get("frequency_penalty", 0.0) or 0.0),
        logit_bias=logit_bias,
        min_p=float(body.get("min_p", 0.0) or 0.0),
    )
