"""KV-handoff plane of the instance server (PD disaggregation).

Split from api/instance.py (round-3 de-monolith): everything that moves
prefilled KV to a decode peer — the transfer worker loop, the handoff
sender (ack-ordered send with local-peer direct import, pull-plane offer,
bytes-plane fallback), the pipelined streaming session (per-prefill-chunk
KV export overlapped with the remaining prefill — docs/PD_DISAGGREGATION.md),
the /kv/import receiver, and decode-side admission. Mixed into
InstanceServer (api/instance.py); `self` is the server.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from xllm_service_tpu.api.http_utils import HttpJsonApi, post_bytes
from xllm_service_tpu.api.instance_registry import _LOCAL_INSTANCES, _LOCAL_MU
from xllm_service_tpu.api.protocol import (
    handoff_from_parts,
    handoff_header,
    kv_frame_array,
    kv_frame_split,
    kv_frame_to_bytes,
    resolve_kv_dtype,
    sampling_from_body,
)
from xllm_service_tpu.common import faults
from xllm_service_tpu.common.shortuuid import generate_uuid
from xllm_service_tpu.parallel.shard_wire import ShardedKV, to_host
from xllm_service_tpu.common.types import RequestOutput, Status, StatusCode
from xllm_service_tpu.tokenizer.tokenizer import IncrementalDetokenizer

logger = logging.getLogger("xllm_service_tpu.api.instance")

def _host_kv(kv):
    """Device payload → host wire form with NO cross-shard gather: a
    tp-sharded export becomes per-shard pieces (ShardedKV) that the
    bytes plane serializes shard-by-shard (docs/SHARDING.md); everything
    else is the flat np.asarray the old wire carried."""
    return to_host(kv)


def _device_resident(kv) -> bool:
    """True when `kv` still lives on device (pull-plane eligible); host
    np payloads AND per-shard host pieces (ShardedKV) ride the bytes
    plane."""
    return kv is not None and not isinstance(kv, (np.ndarray, ShardedKV))


# Receiver session table bounds: stale sessions (sender died mid-stream
# without an abort) are reaped past the TTL; the table itself is capped so
# a misbehaving sender cannot grow it without bound.
_KV_SESSION_TTL_S = 300.0
_KV_SESSION_CAP = 64


def _pd_streaming_enabled(cfg) -> bool:
    """Pipelined-handoff escape hatch: XLLM_PD_STREAMING=1|0 overrides
    EngineConfig.enable_pd_streaming either way. Read per request so the
    hatch can flip on a live instance."""
    env = os.environ.get("XLLM_PD_STREAMING", "")
    if env == "1":
        return True
    if env == "0":
        return False
    return bool(getattr(cfg, "enable_pd_streaming", True))


class _KVStreamSession:
    """Sender side of one pipelined PD handoff (docs/PD_DISAGGREGATION.md).

    The engine's chunked-prefill loop calls `send_chunk` (engine thread)
    after each partial chunk; the chunk's blocks are handed to the
    transfer worker pool and migrate — direct import for a colocated peer,
    pull-plane offer or bytes POST for a remote one — WHILE the next chunk
    is still prefilling. Chunk delivery is order-independent (the receiver
    commits content-addressed blocks into its prefix cache), so each
    chunk's offer completes asynchronously; the commit waits only for the
    session to drain. Any failure aborts the session: the engine then
    exports the FULL payload in the commit (monolithic retry — the blocks
    are still held at `_handoff` time), and blocks a failed chunk did
    deliver are merely unused cache entries on the peer.
    """

    def __init__(
        self, owner, srid: str, decode_name: str, epoch: int = 0,
        trace: Optional[Dict[str, Any]] = None,
    ):
        self.owner = owner
        self.srid = srid
        self.decode_name = decode_name
        # Fencing epoch of the master that routed this PD pair: the
        # session OPEN carries it so the decode peer's fence rejects KV
        # control traffic descending from a deposed master's dispatch.
        self.epoch = int(epoch or 0)
        # Trace context of the dispatching request: rides the session
        # OPEN so the decode peer's chunk-landing spans join the same
        # cross-process timeline.
        self.trace = trace if isinstance(trace, dict) else None
        self.session_id = generate_uuid(16)
        self.aborted = False
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._pending = 0
        self._next_idx = 0
        self.chunks_sent = 0
        self.chunks_delivered = 0
        self.blocks_delivered = 0
        # Admit-time routing: the master picked the decode peer before the
        # prefill was dispatched, so the peer address resolves HERE (HTTP
        # serving thread) and session-open can precede prefill-done without
        # a directory lookup on the engine thread. A colocated peer skips
        # the lookup entirely.
        self._addr = ""
        if owner._local_peer(decode_name) is None:
            try:
                self._addr = owner._resolve_instance_addr(decode_name)
            except Exception:
                self._addr = ""
        self._offer_session = None  # lazy: one per session, pull plane only
        # Set once chunk 0 (the session OPEN) is delivered: later chunks
        # wait on it so a worker racing chunk 1 ahead of the open can't
        # get refused by the receiver's session gate.
        self._opened = threading.Event()

    # ------------------------------------------------------ engine thread

    def send_chunk(self, chunk) -> bool:
        """Accept one KVStreamChunk for delivery (engine thread: must not
        block on the network — the actual send runs on the transfer pool).
        Returns False once the session is aborted; the engine then stops
        streaming and the final handoff goes monolithic."""
        if self.aborted:
            return False
        try:
            faults.point(
                "kv_stream.send",
                instance=self.owner.name, peer=self.decode_name,
                srid=self.srid, session=self.session_id,
                chunk=self._next_idx,
            )
        except faults.FaultInjected as fi:
            self._fail(str(fi))
            return False
        kv = chunk.kv
        # TOCTOU guard (same rule as the monolithic send): with no local
        # peer and no transfer server at all, the export would pin HBM
        # through the queue wait for no reason — copy to host now. A
        # bytes-plane-CACHED peer is deliberately NOT converted here:
        # that np.asarray is a blocking device sync on the engine thread,
        # and the worker converts at serialization anyway (queue pinning
        # stays bounded at the lane's maxsize).
        if (
            _device_resident(kv)
            and self.owner._local_peer(self.decode_name) is None
            and self.owner._kv_transfer is None
        ):
            kv = _host_kv(kv)
        idx = self._next_idx
        self._next_idx += 1
        with self._cv:
            self._pending += 1
        header_meta = {
            "idx": idx,
            "start_block": int(chunk.start_block),
            "expected_blocks": int(chunk.total_blocks_hint),
            "prompt_tokens": int(chunk.prompt_tokens),
        }
        hashes = list(chunk.block_hashes)
        try:
            # NON-blocking put on the DEDICATED stream lane (instance.py
            # _stream_q), unlike the monolithic path's backpressure:
            # send_chunk runs mid-prefill on the engine thread and a
            # streaming request multiplies queue traffic ~chunks-per-
            # prompt-fold, so one stuck decode peer can only saturate this
            # lane — the session then degrades to the monolithic fallback
            # (put_nowait -> abort) and neither the engine thread nor the
            # monolithic transfer pool ever stalls on a chunk's behalf.
            self.owner._stream_q.put_nowait(
                lambda: self._deliver(header_meta, hashes, kv)
            )
        except queue.Full:
            with self._cv:
                self._pending -= 1
                self._cv.notify_all()
            self._fail("transfer queue saturated")
            return False
        except BaseException:
            with self._cv:
                self._pending -= 1
                self._cv.notify_all()
            raise
        self.chunks_sent += 1
        return True

    # ---------------------------------------------------- transfer worker

    def _deliver(self, meta: Dict[str, Any], hashes: List[bytes], kv) -> None:
        try:
            if self.aborted:
                return
            peer = self.owner._local_peer(self.decode_name)
            if peer is not None:
                # Colocated peer: direct in-process landing, KV stays a
                # device array end-to-end (ICI-path analog).
                if not hasattr(peer.engine, "import_kv_blocks"):
                    self._fail("local peer engine has no streaming import")
                    return
                peer.engine.import_kv_blocks(hashes, kv)
                self._mark_delivered(len(hashes))
                self._opened.set()
                return
            # The receiver refuses chunks for a session it never opened —
            # a worker racing chunk N ahead of the open must wait for
            # chunk 0's ack (the event also sets on abort, so a failed
            # open releases the waiters immediately).
            if meta["idx"] > 0 and not self._opened.wait(30.0):
                self._fail("session open never completed")
                return
            if self.aborted:
                return
            # Mid-session TOCTOU: the colocated peer this chunk was
            # enqueued for may have deregistered since. With no pull plane
            # the payload must ride host bytes per-chunk — copy NOW, don't
            # strand the session.
            if _device_resident(kv) and self.owner._kv_transfer is None:
                kv = _host_kv(kv)
            addr = self._addr or self.owner._resolve_instance_addr(
                self.decode_name
            )
            if not addr:
                self._fail(f"decode instance {self.decode_name} unknown")
                return
            self._addr = addr
            err = self._post_chunk(addr, meta, hashes, kv)
            if err:
                self._fail(err)
            else:
                self._mark_delivered(len(hashes))
                self._opened.set()
        except Exception as e:  # noqa: BLE001 — session must fail closed
            self._fail(f"chunk delivery failed: {e}")
        finally:
            with self._cv:
                self._pending -= 1
                self._cv.notify_all()

    def _post_chunk(
        self, addr: str, meta: Dict[str, Any], hashes: List[bytes], kv
    ) -> str:
        """POST one chunk to the remote peer; '' on success. Chunk 0 is the
        session OPEN (carries the reservation hint). Delivery rides the
        shared _post_kv_frame protocol, with the session's offer registry
        (bulk-retract on abort) and a 409 session refusal treated as
        final — a bytes retry cannot fix a refused reservation."""
        header: Dict[str, Any] = {
            "kv_stream": {
                "id": self.session_id,
                "op": "open" if meta["idx"] == 0 else "chunk",
                **meta,
            },
            "service_request_id": self.srid,
            "block_hashes": [b.hex() for b in hashes],
        }
        if meta["idx"] == 0 and self.epoch:
            # Epoch fence on the /kv/import control plane: the session
            # OPEN is the admission decision (reservation), so it is the
            # message the receiver must be able to reject as stale.
            header["master_epoch"] = self.epoch
        if meta["idx"] == 0 and self.trace:
            header["trace"] = self.trace
        if self._offer_session is None and self.owner._kv_transfer is not None:
            self._offer_session = self.owner._kv_transfer.open_offer_session()
        return self.owner._post_kv_frame(
            addr, header, kv,
            offer_session=self._offer_session, final_codes=(409,),
        )

    def _mark_delivered(self, n_blocks: int) -> None:
        with self._mu:  # concurrent _deliver workers of one session
            self.chunks_delivered += 1
            self.blocks_delivered += n_blocks
        m = getattr(self.owner, "_m_kv_stream_chunks", None)
        if m is not None:
            m.inc()
        _span = getattr(self.owner, "_span", None)
        if _span is not None:
            _span(
                self.srid, "kv_chunk_sent",
                blocks=n_blocks, session=self.session_id,
                peer=self.decode_name,
            )

    def _fail(self, reason: str) -> None:
        with self._mu:
            if self.aborted:
                return
            self.aborted = True
        logger.warning(
            "KV stream session %s (%s -> %s) aborted: %s — commit falls "
            "back to the monolithic payload",
            self.session_id, self.owner.name, self.decode_name, reason,
        )
        m = getattr(self.owner, "_m_kv_stream_aborts", None)
        if m is not None:
            m.inc()
        self._opened.set()  # release any worker waiting on the open
        if self._offer_session is not None:
            # Outstanding offers may still be mid-pull: grace-retract.
            self._offer_session.retract_all_later()
        self._notify_peer_abort()

    def _notify_peer_abort(self) -> None:
        """Best-effort peer notification so its session entry (and its
        soft block reservation) clears before the TTL reap. On a
        dedicated short-lived thread: the stream lane may be SATURATED —
        that's a common abort cause — and a dropped notify would let
        dead sessions pile toward the receiver's cap, 409ing fresh
        sessions for up to the whole TTL."""
        if not self._addr:
            return
        payload = kv_frame_to_bytes(
            {
                "kv_stream": {"id": self.session_id, "op": "abort"},
                "service_request_id": self.srid,
            }
        )
        addr = self._addr

        def _notify():
            try:
                post_bytes(addr, "/kv/import", payload, timeout=5.0)
            except Exception:
                pass

        threading.Thread(
            target=_notify,
            name=f"kv-stream-abort-{self.session_id[:8]}",
            daemon=True,
        ).start()

    def dispose(self) -> None:
        """The request ended WITHOUT a handoff (cancel / reject / EOS on
        the very first token): stop further sends, drop offer keepalives,
        and clear the peer's session entry ahead of the TTL reap — 64
        cancelled streams inside one TTL would otherwise pin the
        receiver's session cap and 409 every fresh session. Not counted
        as an abort: nothing degraded, there is simply no commit coming."""
        with self._mu:
            if self.aborted:
                return
            self.aborted = True
        self._opened.set()
        if self._offer_session is not None:
            self._offer_session.retract_all_later()
        if self.chunks_sent:
            self._notify_peer_abort()

    # ------------------------------------------------------------- commit

    def wait_drained(self, timeout: float = 30.0) -> bool:
        """Block until every enqueued chunk job finished (delivered or
        failed) — the commit must not race its own session's tail."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._pending > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(left)
        return True

    def close(self) -> None:
        """Commit delivered (or request finished without a handoff): drop
        any offer keepalives still alive. A chunk job still in flight
        (wait_drained timed out) may have a peer MID-PULL on its offer —
        those get the grace window instead of an immediate retract, which
        could free the device buffer under the pull."""
        if self._offer_session is None:
            return
        with self._cv:
            pending = self._pending
        if pending > 0:
            self._offer_session.retract_all_later()
        else:
            self._offer_session.retract_all()


class KVHandoffMixin:
    def _init_kv_handoff(self) -> None:  # graftlint: init-only
        """Streaming-session state + handoff observability. Called from
        InstanceServer.__init__ once self.metrics exists; the series land
        in the instance exposition next to the engine's."""
        from xllm_service_tpu.obs import LATENCY_BUCKETS_MS

        # Receiver session table: sid -> {ts, expected, chunks, blocks}.
        self._kv_sessions: Dict[str, Dict[str, Any]] = {}  # guarded by: self._kv_sessions_mu
        self._kv_sessions_mu = threading.Lock()
        # Overlap accounting: numerator = full blocks that migrated through
        # stream chunks (delivered before prefill-done), denominator = ALL
        # migrated full blocks (streamed + commit-carried, monolithic
        # handoffs included).
        self._kv_stream_blocks_streamed = 0
        self._kv_mig_blocks_total = 0
        self._kv_stats_mu = threading.Lock()  # transfer-pool writers
        # (mode, stall_ms) ring for bench_serving --pd phase snapshots.
        self._kv_stall_samples: collections.deque = collections.deque(
            maxlen=1024
        )
        self._m_kv_stream_chunks = self.metrics.counter(
            "xllm_kv_stream_chunks_total",
            "Pipelined-handoff chunks delivered to decode peers (sender "
            "side)",
        )
        self._m_kv_stream_landed = self.metrics.counter(
            "xllm_kv_stream_chunks_landed_total",
            "Pipelined-handoff chunks accepted for landing into the local "
            "prefix cache (receiver side; landing runs on the engine "
            "thread — failures there count in "
            "xllm_engine_kv_chunk_land_errors_total)",
        )
        self._m_kv_stream_aborts = self.metrics.counter(
            "xllm_kv_stream_aborts_total",
            "Streaming handoff sessions aborted (commit fell back to the "
            "monolithic payload)",
        )
        self._m_kv_stall = self.metrics.histogram(
            "xllm_kv_handoff_stall_ms",
            "Prefill-done to decode-peer admission: master first-token ack "
            "wait + residual KV delivery (the PD critical-path stall)",
            buckets=LATENCY_BUCKETS_MS,
        )
        self.metrics.gauge(
            "xllm_kv_stream_overlap_frac",
            "Fraction of migrated full KV blocks that left before "
            "prefill-done (streamed chunks over all migrated blocks)",
        ).set_function(
            lambda: self._kv_stream_blocks_streamed
            / max(self._kv_mig_blocks_total, 1)
        )

    def _open_kv_stream(
        self, srid: str, decode_name: str, epoch=None, trace=None
    ) -> Optional[_KVStreamSession]:
        """Create the pipelined-handoff session for a PD-split request (or
        None when the escape hatch disables streaming). Costless for
        single-chunk prompts: the engine only streams on PARTIAL prefill
        chunks, so an unused session never opens on the wire. `epoch` is
        the dispatching master's fencing epoch, carried on the session
        OPEN so the decode peer can reject deposed-master control traffic."""
        if not _pd_streaming_enabled(self.cfg):
            return None
        try:
            epoch = int(epoch or 0)
        except (TypeError, ValueError):
            epoch = 0
        return _KVStreamSession(
            self, srid, decode_name, epoch=epoch, trace=trace
        )

    def _transfer_loop(self, q=None) -> None:
        q = q if q is not None else self._transfer_q
        while True:
            job = q.get()
            if job is None:
                return
            try:
                job()
            except Exception:
                logger.exception("KV transfer job failed")

    def _peer_on_bytes_plane(self, decode_name: str) -> bool:
        """True when the peer's RESOLVED address is capability-cached onto
        the bytes plane — a device payload queued for it would pin HBM for
        nothing (an unresolved peer stays device-resident optimistically;
        the first rejected pull fixes the cache)."""
        addr = self._peer_addrs.get(decode_name, "")
        return bool(addr) and addr in self._peer_no_pull

    def _resolve_instance_addr(self, name: str) -> str:
        addr = self._peer_addrs.get(name)
        if addr:
            return addr
        meta = self._master.instance_info(name) if self._master else None
        if meta is None:
            return ""
        self._peer_addrs[name] = meta.http_address
        return meta.http_address

    def _make_handoff_sender(
        self,
        srid: str,
        decode_name: str,
        body: Dict,
        detoks: Optional[Dict[int, IncrementalDetokenizer]] = None,
        seed: Optional[int] = None,
        respond_via_self: bool = False,
        kv_stream: Optional[_KVStreamSession] = None,
    ):
        sampling_fields = {
            k: body[k]
            for k in (
                "max_tokens", "max_completion_tokens", "temperature",
                "top_p", "top_k", "seed", "logprobs", "top_logprobs",
                "ignore_eos", "presence_penalty", "frequency_penalty",
                "logit_bias", "min_p",
            )
            if k in body
        }
        rf = body.get("response_format")
        rf = rf if isinstance(rf, dict) else {}
        guided_mode = {
            "json_object": "json", "json_schema": "json_schema"
        }.get(rf.get("type"))
        guided_schema = None
        if guided_mode == "json_schema":
            js = rf.get("json_schema")
            guided_schema = (
                js.get("schema") if isinstance(js, dict) else None
            )
        # adapter travels by NAME: rows are executor-local
        lora_name = (
            body.get("model")
            if body.get("model") in getattr(self, "lora_names", {})
            else ""
        )
        if seed is not None:
            # Forward the RESOLVED seed (possibly drawn at random for an
            # unseeded request) so the decode peer continues the same
            # RNG stream instead of drawing its own.
            sampling_fields["seed"] = seed

        def transfer(handoff, t_pf_done: float) -> None:
            # Runs on the transfer thread (never the engine thread): waits
            # for the master to ack the first-token push, then POSTs the KV
            # payload to the decode peer. The engine already released the
            # sequence's slot and blocks before enqueueing this job, so a
            # slow master/peer delays only this handoff, not the engine.
            #
            # TOCTOU guard: send() kept the KV device-resident because a
            # local peer existed at enqueue time; if that peer deregistered
            # since, copy to host NOW — before the ack wait below — so a
            # device export never sits pinned in HBM through it. With the
            # pull plane enabled, device-residency through the ack wait is
            # the point (the peer pulls from device memory), so the copy
            # is skipped.
            if (
                _device_resident(handoff.kv)
                and self._local_peer(decode_name) is None
                and (
                    self._kv_transfer is None
                    or self._peer_on_bytes_plane(decode_name)
                )
            ):
                handoff = dataclasses.replace(
                    handoff, kv=_host_kv(handoff.kv)
                )
            with self._push_acked_mu:
                acked = self._push_acked.get(srid)
            err = ""
            # Cross-instance ordering: the first token must be acked by the
            # master before the decode peer can start pushing, or a client
            # could see token 2 before token 1. The event stays in the dict
            # until AFTER the wait — popping first would race the ack.
            if acked is not None and not acked.wait(60.0):
                err = "first-token push never acked by master"
            with self._push_acked_mu:
                self._push_acked.pop(srid, None)
            if not err:
                extra = {
                    "service_request_id": srid,
                    "sampling": sampling_fields,
                    "guided": guided_mode,
                    "guided_schema": guided_schema,
                    "lora": lora_name,
                    "offline": bool(body.get("offline", False)),
                }
                if body.get("master_epoch"):
                    # Epoch fence rides the handoff control header too:
                    # the decode peer must reject a commit descending
                    # from a deposed master's dispatch.
                    extra["master_epoch"] = body["master_epoch"]
                if isinstance(body.get("trace"), dict):
                    # Trace context follows the request across the PD
                    # boundary: the decode peer's admission span joins
                    # the dispatching request's timeline.
                    extra["trace"] = body["trace"]
                if kv_stream is not None and kv_stream.chunks_sent:
                    # Streamed session: the commit trails its own chunks.
                    # Blocks land order-independently at the peer, but a
                    # commit overtaking an in-flight chunk would miss its
                    # prefix match and recompute for nothing.
                    drained = kv_stream.wait_drained(30.0)
                    if (
                        not drained or kv_stream.aborted
                    ) and handoff.kv_start_block > 0:
                        # A chunk died AFTER the engine built the
                        # tail-only payload (the full export is gone with
                        # the engine's blocks): the commit still lands and
                        # the peer recomputes the hole — byte-identical,
                        # just slower. Surface it; the overlap accounting
                        # below counts only blocks actually delivered.
                        logger.warning(
                            "KV stream session %s lost chunks after the "
                            "commit was built (drained=%s aborted=%s); "
                            "decode peer will recompute the gap",
                            kv_stream.session_id, drained,
                            kv_stream.aborted,
                        )
                    extra["kv_stream"] = {
                        "id": kv_stream.session_id,
                        "op": "commit",
                        "chunks": kv_stream.chunks_delivered,
                    }
                if respond_via_self:
                    # Alternate topology: decode relays its generations
                    # back through this (prefill) instance.
                    extra["respond_addr"] = self.address
                # Detokenizer carry-over: the decode peer continues from
                # this side's exact byte/char position.
                d0 = (detoks or {}).get(0)
                if d0 is not None:
                    ids, emitted = d0.export_state()
                    extra["detok_ids"] = ids
                    extra["detok_emitted"] = emitted
                peer = self._local_peer(decode_name)
                if peer is not None:
                    # Colocated peer: direct in-process import, no
                    # serialization (ICI-path analog).
                    try:
                        peer._admit_import(handoff, extra)
                    except Exception as e:
                        err = f"local decode peer import failed: {e}"
                else:
                    addr = self._resolve_instance_addr(decode_name)
                    if not addr:
                        err = f"decode instance {decode_name} unknown"
                    else:
                        err = self._post_handoff(addr, handoff, extra)
            if not err:
                # Handoff complete: this instance is done with the request
                # (the decode peer owns cancellation from here — including
                # its reconcile-manifest entry).
                with self._srid_mu:
                    self._srid_map.pop(srid, None)
                    self._srid_forget_locked(srid)
                # Stall + overlap observability: the stall spans prefill-
                # done to decode-peer admission; the overlap counters feed
                # the xllm_kv_stream_overlap_frac gauge. Only blocks the
                # session actually DELIVERED count as streamed — a chunk
                # lost after the tail-only payload was built must not
                # inflate the overlap fraction.
                streamed = int(getattr(handoff, "kv_start_block", 0) or 0)
                if kv_stream is not None:
                    streamed = min(streamed, kv_stream.blocks_delivered)
                stall_ms = (time.monotonic() - t_pf_done) * 1000
                self._m_kv_stall.observe(stall_ms)
                self._kv_stall_samples.append(
                    ("streamed" if streamed > 0 else "mono", stall_ms)
                )
                self._span(
                    srid, "handoff_commit",
                    peer=decode_name, stall_ms=round(stall_ms, 3),
                    streamed_blocks=streamed,
                )
                stall_thresh = float(
                    os.environ.get("XLLM_TRACE_STALL_MS", "")
                    or getattr(self.cfg, "trace_stall_ms", 2000.0)
                    or 2000.0
                )
                if stall_ms > stall_thresh:
                    self.flight.trigger(
                        "kv_handoff_stall", srid,
                        stall_ms=round(stall_ms, 3),
                        threshold_ms=stall_thresh, peer=decode_name,
                    )
                with self._kv_stats_mu:  # transfer pool: concurrent commits
                    self._kv_stream_blocks_streamed += streamed
                    self._kv_mig_blocks_total += int(handoff.num_full_blocks)
                if kv_stream is not None:
                    kv_stream.close()
            if err:
                if kv_stream is not None:
                    kv_stream._fail(f"commit failed: {err}")
                logger.error("handoff for %s failed: %s", srid, err)
                out = RequestOutput(
                    request_id=handoff.request_id,
                    service_request_id=srid,
                    status=Status(StatusCode.UNAVAILABLE, err),
                    finished=True,
                )
                with self._srid_mu:
                    self._srid_map.pop(srid, None)
                    self._srid_forget_locked(srid)
                self._push_q.put(out)

        def send(handoff) -> None:
            t_pf_done = time.monotonic()  # prefill just finished
            self._span(
                srid, "handoff_send",
                peer=decode_name,
                blocks=int(getattr(handoff, "num_full_blocks", 0) or 0),
            )
            # Engine-thread side. The KV export arrives as a DEVICE array;
            # it may only stay device-resident if a colocated peer will
            # take it directly (in-process import) or the pull plane will
            # serve it (the decode peer pulls from device memory) — on the
            # bytes path it would otherwise sit pinned in HBM through the
            # queue + up-to-60s ack wait while the engine has already
            # freed and re-budgeted those blocks (round-2 review finding).
            # Copy to host here for the bytes path; a peer that
            # (de)registers between enqueue and transfer still works —
            # both import paths accept either array kind.
            # NO host copy here for a bytes-plane-cached peer (unlike the
            # transfer()-side guard): the conversion of a full monolithic
            # payload is a blocking device sync that would stall the
            # ENGINE thread; queue pinning is bounded (maxsize 8) and
            # transfer() converts at dequeue, before the ack wait.
            if (
                handoff.kv is not None
                and self._local_peer(decode_name) is None
                and self._kv_transfer is None
            ):
                handoff = dataclasses.replace(
                    handoff, kv=_host_kv(handoff.kv)
                )
            self._transfer_q.put(lambda: transfer(handoff, t_pf_done))

        return send

    def _post_kv_frame(
        self,
        addr: str,
        header: Dict[str, Any],
        kv,
        offer_session=None,
        final_codes: tuple = (),
    ) -> str:
        """POST one /kv/import frame to `addr`; '' on success. The shared
        delivery protocol of the monolithic handoff and the streamed
        chunks: a device-resident `kv` is OFFERED on this process's
        transfer server (under `offer_session` when given, so a streaming
        session can bulk-retract on abort) and the POST carries only
        {addr, uuid, shape, dtype} — the peer pulls device-to-device
        before acking (runtime/transfer.py). A transport error leaves the
        offer on the grace window (the peer may STILL be pulling — an
        immediate retract could free the buffer under it); a rejected
        pull header caches the peer on the bytes plane (`_peer_no_pull`)
        and retries ONCE with body bytes, unless the status is in
        `final_codes` (e.g. a 409 session refusal, where a bytes retry
        would just fail again). Host (np) payloads ride the body."""
        xfer = self._kv_transfer
        use_pull = (
            xfer is not None
            and _device_resident(kv)
            and addr not in self._peer_no_pull
        )
        if use_pull:
            offers = offer_session if offer_session is not None else xfer
            uuid = offers.offer([kv])
            pull_header = dict(header)
            pull_header["kv_pull"] = {
                "addr": xfer.address,
                "uuid": uuid,
                "shape": [int(s) for s in kv.shape],
                "dtype": str(kv.dtype),
            }
            try:
                code, resp = post_bytes(
                    addr, "/kv/import", kv_frame_to_bytes(pull_header)
                )
            except Exception as e:
                # Lifetime hands over to the grace timer; a session-level
                # bulk retract must not cancel it (the peer may be
                # mid-pull), so the session forgets the uuid.
                xfer.retract_later(uuid)
                if offer_session is not None:
                    offer_session.forget(uuid)
                return f"decode peer unreachable: {e}"
            # A response means the peer finished (or never started) its
            # pull — the offer's keepalive can drop now.
            offers.retract(uuid)
            if code == 200:
                return ""
            if code in final_codes:
                return f"decode peer refused /kv/import: {resp}"
            # Capability cache: ONLY a peer that reports having no
            # transfer server (the _resolve_kv_pull rejection) rejects
            # every pull header — cache it on the bytes plane. Any other
            # rejection (transient pull failure, shape gate, fault
            # injection) retries on bytes WITHOUT poisoning the cache,
            # or future handoffs to a healthy pull peer would pay host
            # copies forever.
            try:
                msg = str((resp or {}).get("error", {}).get("message", ""))
            except Exception:
                msg = ""
            if "no transfer server" in msg:
                logger.warning(
                    "peer %s has no transfer server; using the bytes "
                    "plane for it from now on", addr,
                )
                self._peer_no_pull.add(addr)
            else:
                logger.warning(
                    "pull-plane /kv/import rejected by %s (%s); retrying "
                    "this message on the bytes plane", addr, resp,
                )
            kv = _host_kv(kv)
        try:
            code, resp = post_bytes(
                addr, "/kv/import", kv_frame_to_bytes(header, kv)
            )
        except Exception as e:
            return f"decode peer unreachable: {e}"
        if code != 200:
            return f"decode peer rejected /kv/import: {resp}"
        return ""

    def _post_handoff(self, addr: str, handoff, extra: Dict[str, Any]) -> str:
        """POST one handoff to a cross-process decode peer; returns "" on
        success, an error string otherwise (delivery protocol:
        _post_kv_frame)."""
        return self._post_kv_frame(
            addr, handoff_header(handoff, extra), handoff.kv
        )

    def _local_peer(self, decode_name: str) -> Optional["InstanceServer"]:
        """The colocated in-process peer eligible for direct (device-
        resident) KV handoff, or None. BOTH sides must opt in, and both
        must belong to the same master (name collisions across stacks in
        one process must not cross-deliver KV)."""
        if not self.cfg.enable_local_kv_transfer:
            return None
        with _LOCAL_MU:
            peer = _LOCAL_INSTANCES.get(decode_name)
        if peer is None or peer is self:
            return None
        if not peer.cfg.enable_local_kv_transfer or getattr(
            peer._master, "_addr", None
        ) != getattr(self._master, "_addr", ""):
            return None
        return peer

    def _resolve_kv_pull(self, p: Dict[str, Any]):
        """Pull-plane resolution for one /kv/import message: fetch the
        offered array straight from the sender's device memory BEFORE
        acking (the offer's lifetime is bounded by this round-trip and
        pull failures surface in the sender's response). Returns
        (kv, err) with exactly one side set."""
        if self._kv_transfer is None:
            return None, (
                "kv_pull offered but this instance has no transfer server "
                "(enable_kv_transfer_server)"
            )
        # Land the pull straight onto the local executor's payload
        # sharding (migration_sharding — the kv_cache_sharding-derived
        # layout): a tp-sharded consumer never bounces the payload
        # through one device and a later reshard; on a 1-device engine
        # this resolves to the same single-device landing as before.
        sharding = None
        ex = getattr(self.engine, "executor", None)
        if ex is not None and hasattr(ex, "migration_sharding"):
            try:
                sharding = ex.migration_sharding()
            except Exception:
                sharding = None
        try:
            kv = self._kv_transfer.pull_single(
                p["addr"], int(p["uuid"]), p["shape"],
                resolve_kv_dtype(p["dtype"]), sharding=sharding,
            )
        except Exception as e:
            return None, f"kv pull failed: {e}"
        return kv, ""

    def _handle_kv_import(self, h: HttpJsonApi) -> None:
        try:
            n = int(h.headers.get("Content-Length", 0))
            data = h.rfile.read(n)
            header, body = kv_frame_split(data)
        except Exception as e:
            h.send_error_json(400, f"bad handoff payload: {e}")
            return
        # Epoch fence on the /kv/import CONTROL plane: opens and commits
        # descend from a master's routing decision, so a deposed master's
        # pair choice must be rejectable here exactly like its dispatch.
        if self._fence_reject(h, header):
            return
        if header.get("fabric_blocks"):
            # Coordinated-eviction re-homing (docs/KV_CACHE.md): a peer is
            # shipping the last fleet replica of cold-tier victims.
            self._handle_fabric_import(h, header, body)
            return
        ss = header.get("kv_stream") or {}
        if ss and ss.get("op") != "commit":
            # Streaming-session control message (open / chunk / abort);
            # commits fall through to the ordinary handoff admission below.
            self._handle_kv_stream_msg(h, ss, header, body)
            return
        if ss:
            with self._kv_sessions_mu:
                self._kv_sessions.pop(str(ss.get("id", "")), None)
        try:
            handoff = handoff_from_parts(header, body)
        except Exception as e:
            h.send_error_json(400, f"bad handoff payload: {e}")
            return
        if "kv_pull" in header:
            kv, err = self._resolve_kv_pull(header["kv_pull"])
            if err:
                h.send_error_json(400, err)
                return
            handoff = dataclasses.replace(handoff, kv=kv)
        rid = self._admit_import(handoff, header)
        h.send_json({"ok": True, "request_id": rid})

    def _kv_session_open(self, sid: str, ss: Dict[str, Any]) -> str:
        """Session-open admission: reap stale sessions, bound the table,
        and soft-reserve the expected block count against the pool (racy
        reads by design — the engine thread owns the manager; a reservation
        miss only degrades the session to monolithic, and real pressure at
        landing time still degrades to recompute)."""
        expected = max(int(ss.get("expected_blocks", 0) or 0), 0)
        bm = getattr(self.engine, "block_mgr", None)
        now = time.monotonic()
        with self._kv_sessions_mu:
            for key in [
                k
                for k, v in self._kv_sessions.items()
                if now - v["ts"] > _KV_SESSION_TTL_S
            ]:
                del self._kv_sessions[key]
            if sid in self._kv_sessions:
                return ""  # duplicate open (sender retry): keep the entry
            if len(self._kv_sessions) >= _KV_SESSION_CAP:
                return "too many open KV stream sessions"
            if bm is not None and expected:
                # free-list blocks INCLUDE evictable cached ones (the
                # landing path may LRU-evict), which is exactly the
                # reservation semantics wanted here.
                free = int(getattr(bm, "num_free_blocks", 0))
                if expected > free:
                    return (
                        f"cannot reserve {expected} blocks "
                        f"({free} free)"
                    )
            self._kv_sessions[sid] = {
                "ts": now, "expected": expected, "chunks": 0, "blocks": 0,
            }
        return ""

    def _handle_kv_stream_msg(
        self,
        h: HttpJsonApi,
        ss: Dict[str, Any],
        header: Dict[str, Any],
        body: bytes,
    ) -> None:
        """Receive side of the pipelined handoff: land one chunk's blocks
        into the local prefix cache (engine thread does the actual
        allocate/import/commit), keyed only by their chained hashes — the
        session's later commit picks them up through the ordinary prefix
        match, so chunk order (and even chunk loss) never affects
        correctness."""
        sid = str(ss.get("id", ""))
        op = ss.get("op", "")
        if op == "abort":
            with self._kv_sessions_mu:
                self._kv_sessions.pop(sid, None)
            h.send_json({"ok": True})
            return
        if op not in ("open", "chunk"):
            h.send_error_json(400, f"bad kv_stream op {op!r}")
            return
        try:
            faults.point(
                "kv_stream.recv",
                instance=self.name, session=sid,
                srid=header.get("service_request_id", ""),
                chunk=ss.get("idx", -1),
            )
        except faults.FaultInjected as fi:
            h.send_error_json(503, str(fi))
            return
        if not hasattr(self.engine, "import_kv_blocks"):
            h.send_error_json(
                400, "this instance cannot land streamed KV chunks"
            )
            return
        if op == "open":
            err = self._kv_session_open(sid, ss)
            if err:
                h.send_error_json(409, err)
                return
        else:
            # Session gate: chunks land blocks (and can LRU-evict hot
            # cache) — only sessions that passed the open-time
            # reservation may do that. A refused/reaped/never-opened
            # session's chunks get 409, aborting the sender to the
            # monolithic fallback.
            with self._kv_sessions_mu:
                known = sid in self._kv_sessions
            if not known:
                h.send_error_json(409, f"unknown KV stream session {sid}")
                return
        try:
            hashes = [
                bytes.fromhex(x) for x in header.get("block_hashes", [])
            ]
        except ValueError:
            h.send_error_json(400, "malformed block hashes")
            return
        if not hashes:
            h.send_error_json(400, "stream chunk carries no blocks")
            return
        if "kv_pull" in header:
            kv, err = self._resolve_kv_pull(header["kv_pull"])
            if err:
                h.send_error_json(400, err)
                return
        else:
            try:
                kv = kv_frame_array(header, body)
            except Exception:
                kv = None
            if kv is None:
                h.send_error_json(400, "stream chunk carries no KV payload")
                return
        # Cheap shape gate HERE (the engine lands chunks asynchronously,
        # after this response): a PD pair config mismatch must surface to
        # the sender so it aborts to the monolithic path instead of
        # streaming garbage all session long.
        ex = getattr(self.engine, "executor", None)
        if ex is not None and hasattr(ex, "migration_shape"):
            expect = ex.migration_shape(len(hashes))
            if tuple(kv.shape) != tuple(expect):
                h.send_error_json(
                    400,
                    f"stream chunk KV shape {tuple(kv.shape)} != local "
                    f"cache layout {tuple(expect)}",
                )
                return
        self.engine.import_kv_blocks(hashes, kv)
        land_srid = str(header.get("service_request_id", ""))
        self._span(
            land_srid, "kv_chunk_landed", blocks=len(hashes), session=sid
        )
        with self._kv_sessions_mu:
            ent = self._kv_sessions.get(sid)
            if ent is not None:
                ent["chunks"] += 1
                ent["blocks"] += len(hashes)
                # Keep-alive: a >TTL prefill (huge context on a loaded
                # chip) must not get its LIVE session reaped out from
                # under its own chunks.
                ent["ts"] = time.monotonic()
        self._m_kv_stream_landed.inc()
        h.send_json({"ok": True, "session": sid})

    def _admit_import(self, handoff, header: Dict[str, Any]) -> str:
        """Decode-side admission of a handed-off sequence — shared by the
        HTTP /kv/import route and the in-process direct path (colocated
        peers skip serialization entirely; the single-host analog of the
        ICI device-to-device KV transfer)."""
        from xllm_service_tpu.runtime.engine import EngineRequest

        srid = header.get("service_request_id", "")
        sampling = sampling_from_body(header.get("sampling", {}), self.cfg)
        guided = header.get("guided")
        schema = header.get("guided_schema")
        if guided and self._ensure_guided_context():
            # decode peer cannot express the mask (tokenizer mismatch):
            # degrade to unconstrained rather than drop the request
            guided = schema = None
        if guided == "json_schema" and not isinstance(schema, dict):
            guided = schema = None
        lora_name = header.get("lora") or ""
        adapter_idx = getattr(self, "lora_names", {}).get(lora_name, 0)
        if lora_name and not adapter_idx:
            # Continuing on the base model would splice two different
            # models into one response — reject instead (the prefill side
            # also colocates LoRA requests, so this is belt and braces).
            logger.error(
                "handoff names adapter %r this instance does not serve; "
                "rejecting", lora_name,
            )
            self._push_q.put(RequestOutput(
                request_id=header.get("service_request_id", ""),
                service_request_id=srid,
                status=Status(
                    StatusCode.INVALID_ARGUMENT,
                    f"decode instance does not serve adapter {lora_name!r}",
                ),
                finished=True,
            ))
            return ""
        rid = generate_uuid(16)
        with self._srid_mu:
            self._srid_map.setdefault(srid, []).append(rid)
        # Fence high-water + reconcile-manifest entry for the adopted
        # sequence (colocated imports bypass the HTTP fence; the epoch
        # still raises the local high-water). The first token was already
        # delivered by the prefill side: classify as an open decode slot.
        self._fence_epoch_check(header.get("master_epoch"))
        self._srid_track(
            srid, max(len(handoff.token_ids) - 1, 0),
            header.get("master_epoch"), delivered=1,
        )
        relay_addr = header.get("respond_addr", "")
        if relay_addr:
            self._relay_addrs[srid] = relay_addr
        self._span(
            srid, "decode_admit",
            tokens=len(handoff.token_ids),
            full_blocks=int(getattr(handoff, "num_full_blocks", 0) or 0),
        )
        detoks: Dict[int, IncrementalDetokenizer] = {}
        if "detok_ids" in header:
            detoks[0] = IncrementalDetokenizer.from_state(
                self.tokenizer, header["detok_ids"],
                header.get("detok_emitted", 0),
            )
        self.engine.import_sequence(
            EngineRequest(
                request_id=rid,
                prompt_token_ids=handoff.token_ids[:-1],
                sampling=sampling,
                callback=self._make_push_callback(srid, detoks),
                guided=guided,
                schema=schema,
                adapter_idx=adapter_idx,
                offline=bool(header.get("offline", False)),
            ),
            handoff,
        )
        return rid
