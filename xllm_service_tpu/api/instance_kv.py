"""KV-handoff plane of the instance server (PD disaggregation).

Split from api/instance.py (round-3 de-monolith): everything that moves
prefilled KV to a decode peer — the transfer worker loop, the handoff
sender (ack-ordered send with local-peer direct import, pull-plane offer,
bytes-plane fallback), the /kv/import receiver, and decode-side
admission. Mixed into InstanceServer (api/instance.py); `self` is the
server.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, Optional

import numpy as np

from xllm_service_tpu.api.http_utils import HttpJsonApi, post_bytes
from xllm_service_tpu.api.instance_registry import _LOCAL_INSTANCES, _LOCAL_MU
from xllm_service_tpu.api.protocol import (
    handoff_from_bytes,
    handoff_to_bytes,
    sampling_from_body,
)
from xllm_service_tpu.common.shortuuid import generate_uuid
from xllm_service_tpu.common.types import RequestOutput, Status, StatusCode
from xllm_service_tpu.tokenizer.tokenizer import IncrementalDetokenizer

logger = logging.getLogger("xllm_service_tpu.api.instance")


class KVHandoffMixin:
    def _transfer_loop(self) -> None:
        while True:
            job = self._transfer_q.get()
            if job is None:
                return
            try:
                job()
            except Exception:
                logger.exception("KV transfer job failed")

    def _resolve_instance_addr(self, name: str) -> str:
        addr = self._peer_addrs.get(name)
        if addr:
            return addr
        meta = self._master.instance_info(name) if self._master else None
        if meta is None:
            return ""
        self._peer_addrs[name] = meta.http_address
        return meta.http_address

    def _make_handoff_sender(
        self,
        srid: str,
        decode_name: str,
        body: Dict,
        detoks: Optional[Dict[int, IncrementalDetokenizer]] = None,
        seed: Optional[int] = None,
        respond_via_self: bool = False,
    ):
        sampling_fields = {
            k: body[k]
            for k in (
                "max_tokens", "max_completion_tokens", "temperature",
                "top_p", "top_k", "seed", "logprobs", "top_logprobs",
                "ignore_eos", "presence_penalty", "frequency_penalty",
                "logit_bias", "min_p",
            )
            if k in body
        }
        rf = body.get("response_format")
        rf = rf if isinstance(rf, dict) else {}
        guided_mode = {
            "json_object": "json", "json_schema": "json_schema"
        }.get(rf.get("type"))
        guided_schema = None
        if guided_mode == "json_schema":
            js = rf.get("json_schema")
            guided_schema = (
                js.get("schema") if isinstance(js, dict) else None
            )
        # adapter travels by NAME: rows are executor-local
        lora_name = (
            body.get("model")
            if body.get("model") in getattr(self, "lora_names", {})
            else ""
        )
        if seed is not None:
            # Forward the RESOLVED seed (possibly drawn at random for an
            # unseeded request) so the decode peer continues the same
            # RNG stream instead of drawing its own.
            sampling_fields["seed"] = seed

        def transfer(handoff) -> None:
            # Runs on the transfer thread (never the engine thread): waits
            # for the master to ack the first-token push, then POSTs the KV
            # payload to the decode peer. The engine already released the
            # sequence's slot and blocks before enqueueing this job, so a
            # slow master/peer delays only this handoff, not the engine.
            #
            # TOCTOU guard: send() kept the KV device-resident because a
            # local peer existed at enqueue time; if that peer deregistered
            # since, copy to host NOW — before the ack wait below — so a
            # device export never sits pinned in HBM through it. With the
            # pull plane enabled, device-residency through the ack wait is
            # the point (the peer pulls from device memory), so the copy
            # is skipped.
            if (
                handoff.kv is not None
                and not isinstance(handoff.kv, np.ndarray)
                and self._local_peer(decode_name) is None
                and self._kv_transfer is None
            ):
                handoff = dataclasses.replace(
                    handoff, kv=np.asarray(handoff.kv)
                )
            with self._push_acked_mu:
                acked = self._push_acked.get(srid)
            err = ""
            # Cross-instance ordering: the first token must be acked by the
            # master before the decode peer can start pushing, or a client
            # could see token 2 before token 1. The event stays in the dict
            # until AFTER the wait — popping first would race the ack.
            if acked is not None and not acked.wait(60.0):
                err = "first-token push never acked by master"
            with self._push_acked_mu:
                self._push_acked.pop(srid, None)
            if not err:
                extra = {
                    "service_request_id": srid,
                    "sampling": sampling_fields,
                    "guided": guided_mode,
                    "guided_schema": guided_schema,
                    "lora": lora_name,
                    "offline": bool(body.get("offline", False)),
                }
                if respond_via_self:
                    # Alternate topology: decode relays its generations
                    # back through this (prefill) instance.
                    extra["respond_addr"] = self.address
                # Detokenizer carry-over: the decode peer continues from
                # this side's exact byte/char position.
                d0 = (detoks or {}).get(0)
                if d0 is not None:
                    ids, emitted = d0.export_state()
                    extra["detok_ids"] = ids
                    extra["detok_emitted"] = emitted
                peer = self._local_peer(decode_name)
                if peer is not None:
                    # Colocated peer: direct in-process import, no
                    # serialization (ICI-path analog).
                    try:
                        peer._admit_import(handoff, extra)
                    except Exception as e:
                        err = f"local decode peer import failed: {e}"
                else:
                    addr = self._resolve_instance_addr(decode_name)
                    if not addr:
                        err = f"decode instance {decode_name} unknown"
                    else:
                        err = self._post_handoff(addr, handoff, extra)
            if not err:
                # Handoff complete: this instance is done with the request
                # (the decode peer owns cancellation from here).
                with self._srid_mu:
                    self._srid_map.pop(srid, None)
            if err:
                logger.error("handoff for %s failed: %s", srid, err)
                out = RequestOutput(
                    request_id=handoff.request_id,
                    service_request_id=srid,
                    status=Status(StatusCode.UNAVAILABLE, err),
                    finished=True,
                )
                with self._srid_mu:
                    self._srid_map.pop(srid, None)
                self._push_q.put(out)

        def send(handoff) -> None:
            # Engine-thread side. The KV export arrives as a DEVICE array;
            # it may only stay device-resident if a colocated peer will
            # take it directly (in-process import) or the pull plane will
            # serve it (the decode peer pulls from device memory) — on the
            # bytes path it would otherwise sit pinned in HBM through the
            # queue + up-to-60s ack wait while the engine has already
            # freed and re-budgeted those blocks (round-2 review finding).
            # Copy to host here for the bytes path; a peer that
            # (de)registers between enqueue and transfer still works —
            # both import paths accept either array kind.
            if (
                handoff.kv is not None
                and self._local_peer(decode_name) is None
                and self._kv_transfer is None
            ):
                handoff = dataclasses.replace(
                    handoff, kv=np.asarray(handoff.kv)
                )
            self._transfer_q.put(lambda: transfer(handoff))

        return send

    def _post_handoff(self, addr: str, handoff, extra: Dict[str, Any]) -> str:
        """POST one handoff to a cross-process decode peer; returns "" on
        success, an error string otherwise.

        With the pull plane up and a device-resident payload, the KV is
        OFFERED on this process's transfer server and the POST carries
        only {addr, uuid, shape, dtype}; the peer pulls device-to-device
        before acking (runtime/transfer.py). A peer that rejects the pull
        header (no transfer server / pull failure) gets ONE retry on the
        bytes plane. Host (np) payloads always ride the bytes plane."""
        use_pull = (
            self._kv_transfer is not None
            and handoff.kv is not None
            and not isinstance(handoff.kv, np.ndarray)
            and addr not in self._peer_no_pull
        )
        if use_pull:
            kv_dev = handoff.kv
            uuid = self._kv_transfer.offer([kv_dev])
            header = dict(extra)
            header["kv_pull"] = {
                "addr": self._kv_transfer.address,
                "uuid": uuid,
                "shape": [int(s) for s in kv_dev.shape],
                "dtype": str(kv_dev.dtype),
            }
            try:
                payload = handoff_to_bytes(
                    dataclasses.replace(handoff, kv=None), header
                )
                code, resp = post_bytes(addr, "/kv/import", payload)
            except Exception as e:
                # The peer may STILL be pulling (e.g. our request timed
                # out while its pull was in flight) — an immediate
                # retract could free the buffer under it.
                self._kv_transfer.retract_later(uuid)
                return f"decode peer unreachable: {e}"
            # A response means the peer finished (or never started) its
            # pull — the offer's keepalive can drop now.
            self._kv_transfer.retract(uuid)
            if code == 200:
                return ""
            logger.warning(
                "pull-plane handoff rejected by %s (%s); using the bytes "
                "plane for this peer from now on", addr, resp,
            )
            # Capability cache: a peer without a transfer server rejects
            # EVERY pull header — don't pay the failing round trip per
            # handoff forever.
            self._peer_no_pull.add(addr)
            handoff = dataclasses.replace(handoff, kv=np.asarray(kv_dev))
        try:
            payload = handoff_to_bytes(handoff, extra)
            code, resp = post_bytes(addr, "/kv/import", payload)
            if code != 200:
                return f"decode peer rejected handoff: {resp}"
        except Exception as e:
            return f"decode peer unreachable: {e}"
        return ""

    def _local_peer(self, decode_name: str) -> Optional["InstanceServer"]:
        """The colocated in-process peer eligible for direct (device-
        resident) KV handoff, or None. BOTH sides must opt in, and both
        must belong to the same master (name collisions across stacks in
        one process must not cross-deliver KV)."""
        if not self.cfg.enable_local_kv_transfer:
            return None
        with _LOCAL_MU:
            peer = _LOCAL_INSTANCES.get(decode_name)
        if peer is None or peer is self:
            return None
        if not peer.cfg.enable_local_kv_transfer or getattr(
            peer._master, "_addr", None
        ) != getattr(self._master, "_addr", ""):
            return None
        return peer

    def _handle_kv_import(self, h: HttpJsonApi) -> None:
        try:
            n = int(h.headers.get("Content-Length", 0))
            data = h.rfile.read(n)
            handoff, header = handoff_from_bytes(data)
        except Exception as e:
            h.send_error_json(400, f"bad handoff payload: {e}")
            return
        if "kv_pull" in header:
            # Pull plane: the body carried no KV bytes — pull the payload
            # straight from the prefill peer's device memory into ours,
            # BEFORE acking (so the sender's offer lifetime is bounded by
            # this round-trip and pull failures surface in its response).
            if self._kv_transfer is None:
                h.send_error_json(
                    400, "kv_pull offered but this instance has no "
                    "transfer server (enable_kv_transfer_server)",
                )
                return
            p = header["kv_pull"]
            try:
                try:
                    dt = np.dtype(p["dtype"])
                except TypeError:
                    import ml_dtypes

                    dt = np.dtype(getattr(ml_dtypes, p["dtype"]))
                kv = self._kv_transfer.pull_single(
                    p["addr"], int(p["uuid"]), p["shape"], dt
                )
            except Exception as e:
                h.send_error_json(400, f"kv pull failed: {e}")
                return
            handoff = dataclasses.replace(handoff, kv=kv)
        rid = self._admit_import(handoff, header)
        h.send_json({"ok": True, "request_id": rid})

    def _admit_import(self, handoff, header: Dict[str, Any]) -> str:
        """Decode-side admission of a handed-off sequence — shared by the
        HTTP /kv/import route and the in-process direct path (colocated
        peers skip serialization entirely; the single-host analog of the
        ICI device-to-device KV transfer)."""
        from xllm_service_tpu.runtime.engine import EngineRequest

        srid = header.get("service_request_id", "")
        sampling = sampling_from_body(header.get("sampling", {}), self.cfg)
        guided = header.get("guided")
        schema = header.get("guided_schema")
        if guided and self._ensure_guided_context():
            # decode peer cannot express the mask (tokenizer mismatch):
            # degrade to unconstrained rather than drop the request
            guided = schema = None
        if guided == "json_schema" and not isinstance(schema, dict):
            guided = schema = None
        lora_name = header.get("lora") or ""
        adapter_idx = getattr(self, "lora_names", {}).get(lora_name, 0)
        if lora_name and not adapter_idx:
            # Continuing on the base model would splice two different
            # models into one response — reject instead (the prefill side
            # also colocates LoRA requests, so this is belt and braces).
            logger.error(
                "handoff names adapter %r this instance does not serve; "
                "rejecting", lora_name,
            )
            self._push_q.put(RequestOutput(
                request_id=header.get("service_request_id", ""),
                service_request_id=srid,
                status=Status(
                    StatusCode.INVALID_ARGUMENT,
                    f"decode instance does not serve adapter {lora_name!r}",
                ),
                finished=True,
            ))
            return ""
        rid = generate_uuid(16)
        with self._srid_mu:
            self._srid_map.setdefault(srid, []).append(rid)
        relay_addr = header.get("respond_addr", "")
        if relay_addr:
            self._relay_addrs[srid] = relay_addr
        detoks: Dict[int, IncrementalDetokenizer] = {}
        if "detok_ids" in header:
            detoks[0] = IncrementalDetokenizer.from_state(
                self.tokenizer, header["detok_ids"],
                header.get("detok_emitted", 0),
            )
        self.engine.import_sequence(
            EngineRequest(
                request_id=rid,
                prompt_token_ids=handoff.token_ids[:-1],
                sampling=sampling,
                callback=self._make_push_callback(srid, detoks),
                guided=guided,
                schema=schema,
                adapter_idx=adapter_idx,
                offline=bool(header.get("offline", False)),
            ),
            handoff,
        )
        return rid
