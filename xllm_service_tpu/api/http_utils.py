"""HTTP plumbing shared by the master and instance servers.

Replaces the reference's brpc server/ProgressiveAttachment machinery
(call_data.h:83-201) with chunked SSE writes over one of two backends
(make_http_server): the stdlib ThreadingHTTPServer, or the evserve
selectors/epoll event loop that detaches streams from threads.
Keep-alive JSON POSTs between tiers reuse an http.client connection per
(thread, host) — the analog of the reference's cached brpc channels
(instance_mgr.cpp:334-353).
"""

from __future__ import annotations

import http.client
import json
import random
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from xllm_service_tpu.common import faults


class RequestNotSentError(ConnectionError):
    """The request was never written to the socket — retrying it cannot
    double-apply a non-idempotent operation. Any other failure out of
    post_json/post_bytes is INDETERMINATE (the peer may have processed
    the request) and must not be blindly retried."""


def request_was_sent(exc: BaseException) -> bool:
    """True when `exc` leaves the request outcome indeterminate."""
    if isinstance(exc, RequestNotSentError):
        return False
    if isinstance(exc, faults.FaultInjected):
        return exc.sent
    return True


class HttpJsonApi:
    """JSON/routing helpers shared by BOTH server backends: QuietHandler
    (threaded, BaseHTTPRequestHandler) and evserve's EvHandler (event
    loop). Requires the host class to provide `headers`, `path`,
    `send_response/send_header/end_headers`, `wfile`, and `_read_body()`."""

    def read_json(self) -> Optional[Dict[str, Any]]:
        try:
            raw = self._read_body()
            return json.loads(raw.decode("utf-8")) if raw else {}
        except Exception:
            return None

    def send_json(
        self, obj: Any, status: int = 200,
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> None:
        data = json.dumps(obj).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def x_request_id(self) -> str:
        """Client correlation id (reference: call_data.h:41-47 reads
        x-request-id, falling back to x-ms-client-request-id)."""
        return (
            self.headers.get("x-request-id")
            or self.headers.get("x-ms-client-request-id")
            or ""
        )

    def send_error_json(
        self, status: int, message: str,
        etype: str = "invalid_request_error",
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> None:
        self.send_json(
            {"error": {"message": message, "type": etype}}, status,
            extra_headers=extra_headers,
        )

    def query(self) -> Dict[str, str]:
        q = parse_qs(urlparse(self.path).query)
        return {k: v[0] for k, v in q.items()}

    @property
    def route(self) -> str:
        return urlparse(self.path).path


class QuietHandler(HttpJsonApi, BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass

    def _read_body(self) -> bytes:
        n = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(n) if n else b"{}"

    def hold(self, stream, timeout_s: float, fail) -> None:
        """Block this handler thread until the scheduler finishes the
        exchange (thread-per-stream semantics). On deadline, `fail()` asks
        the scheduler to fail the request; if its lane still hasn't run
        after a 5 s grace, the exchange is abandoned with no response and
        the connection dropped so no late write can reach a reused socket.
        The event backend's EvHandler.hold has the same contract without
        the blocked thread."""
        if stream.done.wait(timeout_s):
            return
        fail()
        if not stream.done.wait(5.0):
            stream.abandon()
            self.close_connection = True

class SseWriter:
    """Server-sent-events writer over a chunked HTTP/1.1 response
    (the ProgressiveAttachment analog, call_data.h:150-193). Thread-safe:
    scheduler lanes write from their own threads."""

    def __init__(
        self,
        handler: BaseHTTPRequestHandler,
        extra_headers: Optional[Dict[str, str]] = None,
    ):
        self._h = handler
        self._mu = threading.Lock()
        self.closed = False
        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Cache-Control", "no-cache")
        handler.send_header("Connection", "keep-alive")
        handler.send_header("Transfer-Encoding", "chunked")
        for k, v in (extra_headers or {}).items():
            handler.send_header(k, v)
        handler.end_headers()

    def _chunk(self, data: bytes) -> bool:
        with self._mu:
            if self.closed:
                return False
            try:
                self._h.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
                self._h.wfile.flush()
                return True
            except (BrokenPipeError, ConnectionResetError, OSError):
                self.closed = True
                return False

    def send(self, payload: Dict[str, Any]) -> bool:
        return self._chunk(
            b"data: " + json.dumps(payload).encode("utf-8") + b"\n\n"
        )

    def send_done(self) -> bool:
        ok = self._chunk(b"data: [DONE]\n\n")
        self.close()
        return ok

    def close(self) -> None:
        with self._mu:
            if self.closed:
                return
            self.closed = True
            try:
                self._h.wfile.write(b"0\r\n\r\n")
                self._h.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass
        # Event backend: tells the EvHandler its chunked response is fully
        # framed so the exchange (and keep-alive slot) can complete.
        hook = getattr(self._h, "on_sse_closed", None)
        if hook is not None:
            hook()


class HttpServerThread:
    """One threaded HTTP server on its own accept thread (the reference runs
    each brpc server on a dedicated thread, master.cpp:38-58).

    stats() reports the request/accept counters the event backend also
    exposes, so the master's aggregated /metrics covers threaded planes
    too instead of silently omitting them."""

    def __init__(self, host: str, port: int, handler_cls):
        stats_mu = threading.Lock()

        class _Srv(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True
            request_queue_size = 128
            accepted_total = 0
            requests_total = 0

            def get_request(inner):
                req = super(_Srv, inner).get_request()
                with stats_mu:
                    _Srv.accepted_total += 1
                return req

            @staticmethod
            def count_request() -> None:
                with stats_mu:
                    _Srv.requests_total += 1

        self._srv_cls = _Srv
        self.server = _Srv((host, port), handler_cls)
        self.host, self.port = self.server.server_address[:2]
        self._thread = threading.Thread(
            target=self.server.serve_forever, name=f"http-{self.port}", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
        self._thread.join(timeout=2.0)

    def stats(self) -> Dict[str, Any]:
        return {
            "backend": "threaded",
            "accepted_total": self._srv_cls.accepted_total,
            "requests_total": self._srv_cls.requests_total,
        }


def make_http_server(
    backend: str,
    host: str,
    port: int,
    *,
    do_get=None,
    do_post=None,
    name: str = "http",
    workers: int = 32,
    max_connections: int = 4096,
    idle_timeout_s: float = 120.0,
    max_stream_buffer: int = 512 * 1024,
    drain_timeout_s: float = 5.0,
    max_body_bytes: int = 256 * 1024 * 1024,
):
    """Build one control-plane HTTP server on the selected backend.

    "threaded": stdlib ThreadingHTTPServer — a thread per connection plus a
    blocked thread per in-flight stream. "event": evserve's selectors/epoll
    loop — streams hold sockets, not threads, which is what carries the
    front end past ~1k concurrent SSE streams. Both return the same
    surface: start/stop/host/port/stats, and hand handlers the same
    HttpJsonApi + hold() API.
    """
    if backend == "threaded":

        class _Handler(QuietHandler):
            def do_GET(self):
                self.server.count_request()
                if do_get is None:
                    self.send_error_json(405, "method not allowed")
                else:
                    do_get(self)

            def do_POST(self):
                self.server.count_request()
                if do_post is None:
                    self.send_error_json(405, "method not allowed")
                else:
                    do_post(self)

        return HttpServerThread(host, port, _Handler)
    if backend != "event":
        raise ValueError(f"unknown http backend {backend!r}")

    from xllm_service_tpu.api.evserve import EventLoopHttpServer

    def app(h) -> None:
        if h.command == "GET" and do_get is not None:
            do_get(h)
        elif h.command == "POST" and do_post is not None:
            do_post(h)
        else:
            h.send_error_json(405, f"method {h.command} not allowed")

    return EventLoopHttpServer(
        host, port, app,
        name=name, workers=workers, max_connections=max_connections,
        idle_timeout_s=idle_timeout_s, max_stream_buffer=max_stream_buffer,
        drain_timeout_s=drain_timeout_s, max_body_bytes=max_body_bytes,
    )


# ---------------------------------------------------------------------------
# outbound JSON client with per-thread connection reuse
# ---------------------------------------------------------------------------

_tls = threading.local()


def _conn_for(addr: str, timeout: float) -> http.client.HTTPConnection:
    cache: Dict[str, http.client.HTTPConnection] = getattr(_tls, "conns", None) or {}
    _tls.conns = cache
    conn = cache.get(addr)
    if conn is None:
        host, _, port = addr.partition(":")
        conn = http.client.HTTPConnection(host, int(port or 80), timeout=timeout)
        cache[addr] = conn
    else:
        conn.timeout = timeout
        if conn.sock is not None:
            conn.sock.settimeout(timeout)
    return conn


def post_json(
    addr: str, path: str, body: Dict[str, Any], timeout: float = 30.0
) -> Tuple[int, Dict[str, Any]]:
    """POST with one retry, but ONLY on send-time failures (stale kept-alive
    connection). Once the request has been written, a failure is raised, not
    retried — POSTs here are not idempotent (a re-send would dispatch the
    same generation twice). Send-time failures surface as
    RequestNotSentError so callers (post_json_retrying) know a retry is
    safe; anything later is indeterminate."""
    payload = json.dumps(body).encode("utf-8")
    # Chaos hooks: "...send" simulates a request that never reaches the
    # peer (partition/refused), "...recv" one that was delivered but whose
    # response was lost (the indeterminate case).
    faults.point("post_json.send", addr=addr, path=path)
    for attempt in (0, 1):
        conn = _conn_for(addr, timeout)
        try:
            conn.request(
                "POST", path, body=payload,
                headers={"Content-Type": "application/json"},
            )
        except Exception as e:
            conn.close()
            getattr(_tls, "conns", {}).pop(addr, None)
            if attempt:
                raise RequestNotSentError(
                    f"POST {addr}{path} never sent: {e}"
                ) from e
            continue
        try:
            faults.point("post_json.recv", addr=addr, path=path)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, (json.loads(data) if data else {})
        except Exception:
            conn.close()
            getattr(_tls, "conns", {}).pop(addr, None)
            raise
    raise RuntimeError("unreachable")


class RetryBudget:
    """Global retry budget (token bucket): every first attempt deposits
    `ratio` tokens, every retry withdraws one. Caps retry traffic at
    ~ratio x the request rate fleet-wide, so one flapping instance can't
    amplify into a retry storm. A `min_tokens` floor keeps sporadic
    failures retryable at low request rates."""

    def __init__(
        self, ratio: float = 0.2, min_tokens: float = 10.0,
        max_tokens: float = 100.0,
    ):
        self._ratio = float(ratio)
        self._min = float(min_tokens)
        self._max = float(max_tokens)
        self._tokens = self._min
        self._mu = threading.Lock()
        self.exhausted_total = 0  # withdrawals refused

    def deposit(self) -> None:
        with self._mu:
            self._tokens = min(self._tokens + self._ratio, self._max)

    def withdraw(self) -> bool:
        with self._mu:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            self.exhausted_total += 1
            return False

    @property
    def tokens(self) -> float:
        with self._mu:
            return self._tokens


def post_json_retrying(
    addr: str,
    path: str,
    body: Dict[str, Any],
    timeout: float = 30.0,
    *,
    attempts: int = 3,
    budget: Optional[RetryBudget] = None,
    idempotent: bool = False,
    backoff_base_s: float = 0.05,
    backoff_max_s: float = 2.0,
) -> Tuple[int, Dict[str, Any]]:
    """post_json under jittered exponential backoff.

    Retries are gated three ways: the per-call `attempts` bound, the
    shared `budget` (a refused withdrawal ends the retries immediately),
    and the idempotency rule — non-idempotent calls retry ONLY failures
    proven send-time (`request_was_sent` False); an indeterminate failure
    re-raises at once so a generation can never be dispatched twice.
    """
    if budget is not None:
        budget.deposit()
    last: Optional[BaseException] = None
    for i in range(max(attempts, 1)):
        if i:
            if budget is not None and not budget.withdraw():
                break
            delay = min(backoff_base_s * (2 ** (i - 1)), backoff_max_s)
            time.sleep(delay * random.uniform(0.5, 1.5))
        try:
            return post_json(addr, path, body, timeout=timeout)
        except Exception as e:  # noqa: BLE001 — classified below
            last = e
            if not idempotent and request_was_sent(e):
                raise
    assert last is not None
    raise last


def post_bytes_raw(
    addr: str, path: str, data: bytes, timeout: float = 60.0
) -> Tuple[int, bytes]:
    """Binary POST returning the RAW response body (the /kv/fetch reply is
    a kv frame, not JSON). Same send-time-only retry rule as post_json."""
    for attempt in (0, 1):
        conn = _conn_for(addr, timeout)
        try:
            conn.request(
                "POST", path, body=data,
                headers={"Content-Type": "application/octet-stream"},
            )
        except Exception as e:
            conn.close()
            getattr(_tls, "conns", {}).pop(addr, None)
            if attempt:
                raise RequestNotSentError(
                    f"POST {addr}{path} never sent: {e}"
                ) from e
            continue
        try:
            resp = conn.getresponse()
            return resp.status, resp.read()
        except Exception:
            conn.close()
            getattr(_tls, "conns", {}).pop(addr, None)
            raise
    raise RuntimeError("unreachable")


def post_bytes(
    addr: str, path: str, data: bytes, timeout: float = 60.0
) -> Tuple[int, Dict[str, Any]]:
    """Binary POST with a JSON response (KV handoff payloads) — the raw
    transport with the body parsed."""
    status, body = post_bytes_raw(addr, path, data, timeout=timeout)
    return status, (json.loads(body) if body else {})


def get_raw(
    addr: str, path: str, timeout: float = 30.0
) -> Tuple[int, bytes, str]:
    """GET returning (status, body bytes, content type) — for verbatim
    passthrough."""
    for attempt in (0, 1):
        conn = _conn_for(addr, timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return (
                resp.status,
                resp.read(),
                resp.getheader("Content-Type", "application/octet-stream"),
            )
        except Exception:
            conn.close()
            getattr(_tls, "conns", {}).pop(addr, None)
            if attempt:
                raise
    raise RuntimeError("unreachable")


def get_json(addr: str, path: str, timeout: float = 30.0) -> Tuple[int, Any]:
    for attempt in (0, 1):
        conn = _conn_for(addr, timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            data = resp.read()
            try:
                return resp.status, json.loads(data) if data else {}
            except json.JSONDecodeError:
                return resp.status, data.decode("utf-8", "replace")
        except Exception:
            conn.close()
            getattr(_tls, "conns", {}).pop(addr, None)
            if attempt:
                raise
    raise RuntimeError("unreachable")
