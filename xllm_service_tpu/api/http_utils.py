"""HTTP plumbing shared by the master and instance servers.

Replaces the reference's brpc server/ProgressiveAttachment machinery
(call_data.h:83-201) with stdlib ThreadingHTTPServer + chunked SSE writes.
Keep-alive JSON POSTs between tiers reuse an http.client connection per
(thread, host) — the analog of the reference's cached brpc channels
(instance_mgr.cpp:334-353).
"""

from __future__ import annotations

import http.client
import json
import socketserver
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse


class QuietHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass

    # -- helpers -----------------------------------------------------------
    def read_json(self) -> Optional[Dict[str, Any]]:
        try:
            n = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(n) if n else b"{}"
            return json.loads(raw.decode("utf-8"))
        except Exception:
            return None

    def send_json(
        self, obj: Any, status: int = 200,
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> None:
        data = json.dumps(obj).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def x_request_id(self) -> str:
        """Client correlation id (reference: call_data.h:41-47 reads
        x-request-id, falling back to x-ms-client-request-id)."""
        return (
            self.headers.get("x-request-id")
            or self.headers.get("x-ms-client-request-id")
            or ""
        )

    def send_error_json(
        self, status: int, message: str,
        etype: str = "invalid_request_error",
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> None:
        self.send_json(
            {"error": {"message": message, "type": etype}}, status,
            extra_headers=extra_headers,
        )

    def query(self) -> Dict[str, str]:
        q = parse_qs(urlparse(self.path).query)
        return {k: v[0] for k, v in q.items()}

    @property
    def route(self) -> str:
        return urlparse(self.path).path


class SseWriter:
    """Server-sent-events writer over a chunked HTTP/1.1 response
    (the ProgressiveAttachment analog, call_data.h:150-193). Thread-safe:
    scheduler lanes write from their own threads."""

    def __init__(
        self,
        handler: BaseHTTPRequestHandler,
        extra_headers: Optional[Dict[str, str]] = None,
    ):
        self._h = handler
        self._mu = threading.Lock()
        self.closed = False
        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Cache-Control", "no-cache")
        handler.send_header("Connection", "keep-alive")
        handler.send_header("Transfer-Encoding", "chunked")
        for k, v in (extra_headers or {}).items():
            handler.send_header(k, v)
        handler.end_headers()

    def _chunk(self, data: bytes) -> bool:
        with self._mu:
            if self.closed:
                return False
            try:
                self._h.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
                self._h.wfile.flush()
                return True
            except (BrokenPipeError, ConnectionResetError, OSError):
                self.closed = True
                return False

    def send(self, payload: Dict[str, Any]) -> bool:
        return self._chunk(
            b"data: " + json.dumps(payload).encode("utf-8") + b"\n\n"
        )

    def send_done(self) -> bool:
        ok = self._chunk(b"data: [DONE]\n\n")
        self.close()
        return ok

    def close(self) -> None:
        with self._mu:
            if self.closed:
                return
            self.closed = True
            try:
                self._h.wfile.write(b"0\r\n\r\n")
                self._h.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass


class HttpServerThread:
    """One threaded HTTP server on its own accept thread (the reference runs
    each brpc server on a dedicated thread, master.cpp:38-58)."""

    def __init__(self, host: str, port: int, handler_cls):
        class _Srv(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True
            request_queue_size = 128

        self.server = _Srv((host, port), handler_cls)
        self.host, self.port = self.server.server_address[:2]
        self._thread = threading.Thread(
            target=self.server.serve_forever, name=f"http-{self.port}", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
        self._thread.join(timeout=2.0)


# ---------------------------------------------------------------------------
# outbound JSON client with per-thread connection reuse
# ---------------------------------------------------------------------------

_tls = threading.local()


def _conn_for(addr: str, timeout: float) -> http.client.HTTPConnection:
    cache: Dict[str, http.client.HTTPConnection] = getattr(_tls, "conns", None) or {}
    _tls.conns = cache
    conn = cache.get(addr)
    if conn is None:
        host, _, port = addr.partition(":")
        conn = http.client.HTTPConnection(host, int(port or 80), timeout=timeout)
        cache[addr] = conn
    else:
        conn.timeout = timeout
        if conn.sock is not None:
            conn.sock.settimeout(timeout)
    return conn


def post_json(
    addr: str, path: str, body: Dict[str, Any], timeout: float = 30.0
) -> Tuple[int, Dict[str, Any]]:
    """POST with one retry, but ONLY on send-time failures (stale kept-alive
    connection). Once the request has been written, a failure is raised, not
    retried — POSTs here are not idempotent (a re-send would dispatch the
    same generation twice)."""
    payload = json.dumps(body).encode("utf-8")
    for attempt in (0, 1):
        conn = _conn_for(addr, timeout)
        try:
            conn.request(
                "POST", path, body=payload,
                headers={"Content-Type": "application/json"},
            )
        except Exception:
            conn.close()
            getattr(_tls, "conns", {}).pop(addr, None)
            if attempt:
                raise
            continue
        try:
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, (json.loads(data) if data else {})
        except Exception:
            conn.close()
            getattr(_tls, "conns", {}).pop(addr, None)
            raise
    raise RuntimeError("unreachable")


def post_bytes(
    addr: str, path: str, data: bytes, timeout: float = 60.0
) -> Tuple[int, Dict[str, Any]]:
    """Binary POST (KV handoff payloads). Same send-time-only retry rule as
    post_json."""
    for attempt in (0, 1):
        conn = _conn_for(addr, timeout)
        try:
            conn.request(
                "POST", path, body=data,
                headers={"Content-Type": "application/octet-stream"},
            )
        except Exception:
            conn.close()
            getattr(_tls, "conns", {}).pop(addr, None)
            if attempt:
                raise
            continue
        try:
            resp = conn.getresponse()
            body = resp.read()
            return resp.status, (json.loads(body) if body else {})
        except Exception:
            conn.close()
            getattr(_tls, "conns", {}).pop(addr, None)
            raise
    raise RuntimeError("unreachable")


def get_raw(
    addr: str, path: str, timeout: float = 30.0
) -> Tuple[int, bytes, str]:
    """GET returning (status, body bytes, content type) — for verbatim
    passthrough."""
    for attempt in (0, 1):
        conn = _conn_for(addr, timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return (
                resp.status,
                resp.read(),
                resp.getheader("Content-Type", "application/octet-stream"),
            )
        except Exception:
            conn.close()
            getattr(_tls, "conns", {}).pop(addr, None)
            if attempt:
                raise
    raise RuntimeError("unreachable")


def get_json(addr: str, path: str, timeout: float = 30.0) -> Tuple[int, Any]:
    for attempt in (0, 1):
        conn = _conn_for(addr, timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            data = resp.read()
            try:
                return resp.status, json.loads(data) if data else {}
            except json.JSONDecodeError:
                return resp.status, data.decode("utf-8", "replace")
        except Exception:
            conn.close()
            getattr(_tls, "conns", {}).pop(addr, None)
            if attempt:
                raise
    raise RuntimeError("unreachable")
