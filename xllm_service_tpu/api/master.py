"""Master process: OpenAI-compatible HTTP front end + instance-facing RPC.

Composes one Scheduler with two HTTP servers on separate ports (the
evserve event loop by default, config.http_backend="threaded" for the
stdlib thread-per-connection backend) — the same process shape as the
reference master (reference: master.cpp:26-34
wires Scheduler->RPC->HTTP; :60-102 HTTP server; :104-139 RPC server; two
server threads at :38-58). The client plane parses OpenAI JSON, schedules,
injects service fields, and forwards to the prefill instance
(http_service/service.cpp:286-424, :147-191); the instance plane carries
registration, heartbeats, and the decode->service token stream
(rpc_service/service.cpp:107-206).

Divergences by design: registration is a real RPC that writes a leased
store key (the reference declares RegisterInstance but never overrides it —
instances write etcd directly; both paths work here), and /metrics serves
aggregated cluster metrics instead of a bare passthrough
(service.cpp:452-457), with ?instance= for the passthrough behavior.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Optional

from xllm_service_tpu.api.http_utils import (
    HttpJsonApi,
    RetryBudget,
    SseWriter,
    get_json,
    get_raw,
    make_http_server,
    post_json,
    post_json_retrying,
)
from xllm_service_tpu.api.protocol import (
    augment_forwarded_request,
    output_from_json,
    parse_prompt_field,
)
from xllm_service_tpu.cluster.instance_mgr import (
    HEALTH_STATE_VALUES,
    instance_key,
)
from xllm_service_tpu.common import faults
from xllm_service_tpu.common.config import ServiceConfig
from xllm_service_tpu.common.types import (
    InstanceMetaInfo,
    KvCacheEvent,
    LatencyMetrics,
    LoadMetrics,
    RequestAction,
    StatusCode,
    TraceContext,
)
from xllm_service_tpu.coordination.store import CoordinationStore
from xllm_service_tpu.obs import (
    ClockSync,
    MetricsRegistry,
    absorb_exposition,
    assemble_trace,
    blame_stages,
    render_families,
    trace_to_chrome,
)
from xllm_service_tpu.service import (
    ClientStream,
    Scheduler,
    ServiceRequest,
    make_service_request_id,
)
from xllm_service_tpu.service.scheduler import NotMasterError
from xllm_service_tpu.tokenizer import parse_messages

logger = logging.getLogger(__name__)

_HTTP_STATUS = {
    StatusCode.OK: 200,
    StatusCode.INVALID_ARGUMENT: 400,
    StatusCode.DEADLINE_EXCEEDED: 504,
    StatusCode.RESOURCE_EXHAUSTED: 429,
    StatusCode.UNAVAILABLE: 503,
    StatusCode.CANCELLED: 499,
}


class HttpClientStream(ClientStream):
    """Bridges scheduler lanes to one live HTTP exchange; the handler thread
    blocks on `done` while lane threads write (reference: StreamCallData +
    the early done->Run SSE trick, call_data.h:83-92)."""

    def __init__(
        self, handler: HttpJsonApi, streaming: bool, x_request_id: str = ""
    ):
        self._handler = handler
        self._streaming = streaming
        # Echoed on every response — success AND error (reference
        # CallData captures the same header pair; here it round-trips to
        # the client and lands in the request trace for correlation).
        self._extra_headers = (
            {"x-request-id": x_request_id} if x_request_id else None
        )
        self._sse: Optional[SseWriter] = None
        self.done = threading.Event()
        # Set when the handler thread gives up on the exchange (timeout):
        # any later lane write must be dropped, never land on the socket —
        # the connection may be serving another request by then.
        self._abandoned = threading.Event()

    def abandon(self) -> None:
        self._abandoned.set()
        self.done.set()

    def _ensure_sse(self) -> SseWriter:
        if self._sse is None:
            self._sse = SseWriter(self._handler, self._extra_headers)
        return self._sse

    def write(self, payload: Dict[str, Any]) -> bool:
        if self._abandoned.is_set():
            return False
        if not self._streaming:
            return True  # non-stream accumulates in the scheduler
        return self._ensure_sse().send(payload)

    def write_done(self) -> bool:
        ok = True
        if self._streaming and not self._abandoned.is_set():
            ok = self._ensure_sse().send_done()
        self.done.set()
        return ok

    def finish(self, payload: Dict[str, Any]) -> bool:
        if self._abandoned.is_set():
            return False
        try:
            self._handler.send_json(
                payload, extra_headers=self._extra_headers
            )
            return True
        except (BrokenPipeError, ConnectionResetError, OSError):
            return False
        finally:
            self.done.set()

    def finish_with_error(self, code: StatusCode, message: str) -> bool:
        if self._abandoned.is_set():
            return False
        try:
            if self._streaming and self._sse is not None:
                ok = self._sse.send(
                    {"error": {"message": message, "code": int(code)}}
                )
                self._sse.close()
                return ok
            self._handler.send_error_json(
                _HTTP_STATUS.get(code, 500), message, "service_error",
                extra_headers=self._extra_headers,
            )
            return True
        except (BrokenPipeError, ConnectionResetError, OSError):
            return False
        finally:
            self.done.set()


class Master:
    def __init__(
        self,
        config: ServiceConfig,
        store: Optional[CoordinationStore] = None,
        tokenizer=None,
    ):
        self.config = config
        # instance name -> lease id held on its registration key
        self._leases: Dict[str, int] = {}
        self._leases_mu = threading.Lock()
        self._request_timeout_s = 600.0
        self._killed = False

        # Both control-plane servers ride the configured backend ("event"
        # = evserve selectors loop, "threaded" = stdlib thread-per-conn).
        # They bind BEFORE the scheduler exists so the election identity
        # is this replica's REAL client-plane address (ephemeral :0 ports
        # resolve at bind) — the master key in the store then doubles as
        # the redirect target a standby's front door hands to clients.
        # Handlers only dereference self.scheduler at request time, after
        # start().
        server_opts = dict(
            workers=config.http_workers,
            max_connections=config.http_max_connections,
            idle_timeout_s=config.http_idle_timeout_s,
            max_stream_buffer=config.sse_max_buffered_kb * 1024,
            drain_timeout_s=config.http_drain_timeout_s,
            max_body_bytes=config.http_max_body_mb * 1024 * 1024,
        )
        self.http = make_http_server(
            config.http_backend, config.host, config.http_port,
            do_get=self.handle_client_get, do_post=self.handle_client_post,
            name="master-http", **server_opts,
        )
        self.rpc = make_http_server(
            config.http_backend, config.host, config.rpc_port,
            do_get=self.handle_rpc_get, do_post=self.handle_rpc_post,
            name="master-rpc", **server_opts,
        )
        self.scheduler = Scheduler(
            config, store=store, tokenizer=tokenizer,
            identity=f"{self.http.host}:{self.http.port}",
        )
        self._store = self.scheduler._store
        self.scheduler.advertised_rpc = self.rpc_address

        # Cluster-level registry: fleet shape + fault accounting the
        # aggregated /metrics adds on top of the scheduler's own series.
        mgr = self.scheduler.instance_mgr
        self.cluster_metrics = MetricsRegistry()
        inst_gauge = self.cluster_metrics.gauge(
            "xllm_cluster_instances",
            "Registered instances by current serving role",
            labelnames=("role",),
        )
        for i, role in enumerate(("prefill", "decode", "encode")):
            inst_gauge.labels(role=role).set_function(
                lambda i=i: mgr.counts()[i]
            )
        self.cluster_metrics.counter(
            "xllm_cluster_pd_flips_total",
            "Dynamic PREFILL<->DECODE role flips applied by the master",
        ).set_function(lambda: mgr.total_flips)
        # Reshaping observability (ISSUE 16 satellite): the same flip
        # counter under the service namespace plus a census gauge that —
        # unlike xllm_cluster_instances — includes the MIX serving role.
        self.cluster_metrics.counter(
            "xllm_service_role_flips_total",
            "Role flips applied by the master (all transitions, "
            "including MIX)",
        ).set_function(lambda: mgr.total_flips)
        census_gauge = self.cluster_metrics.gauge(
            "xllm_service_role_census",
            "Instances by current serving role, including MIX",
            labelnames=("role",),
        )
        for role in ("prefill", "decode", "encode", "mix"):
            census_gauge.labels(role=role).set_function(
                lambda r=role: float(mgr.role_census()[r])
            )
        self.cluster_metrics.counter(
            "xllm_cluster_breaker_ejections_total",
            "Instances ejected by the health circuit breaker",
        ).set_function(lambda: mgr.total_ejections)
        self.cluster_metrics.counter(
            "xllm_cluster_breaker_probe_recoveries_total",
            "Ejected instances re-admitted to probation by a /health probe",
        ).set_function(lambda: mgr.total_probe_recoveries)
        # Global retry budget over control-plane POSTs (dispatch/cancel/
        # encoder push): bounds fleet-wide retry amplification so one
        # flapping instance can't start a retry storm.
        self._retry_budget = RetryBudget(
            ratio=getattr(config, "retry_budget_ratio", 0.2),
            min_tokens=getattr(config, "retry_budget_min", 10.0),
        )
        self._retry_attempts = getattr(config, "dispatch_retry_attempts", 3)
        self.cluster_metrics.counter(
            "xllm_service_retry_budget_exhausted_total",
            "Control-plane retries refused by the exhausted retry budget",
        ).set_function(lambda: self._retry_budget.exhausted_total)

        def health_probe(meta) -> bool:
            # Breaker probe, deliberately POST-shaped: it exercises the
            # SAME plane dispatch failures implicated (post_json), so a
            # partition that kills dispatch also fails the probe instead
            # of falsely healing the instance. Identity is cross-checked —
            # a recycled port must not heal a dead instance's breaker.
            # The probe carries the fencing epoch like every other
            # master->instance RPC: a deposed master's probe gets a 412
            # and must not keep healing breakers it no longer owns.
            body: Dict[str, Any] = {}
            ep = self.scheduler.master_epoch
            if ep:
                body["master_epoch"] = ep
            code, resp = post_json(
                meta.http_address, "/health", body, timeout=2.0
            )
            return (
                code == 200
                and isinstance(resp, dict)
                and bool(resp.get("ok"))
                and resp.get("name") == meta.name
            )

        mgr.health_prober = health_probe

        def reconcile_transport(meta, body: Dict[str, Any]) -> Dict[str, Any]:
            # Takeover reconciliation RPC (docs/FAULT_TOLERANCE.md): the
            # scheduler builds the claim set; this adds the rpc-plane
            # address instances should re-point heartbeats/pushes to, and
            # carries it over the wire. Idempotent — a retried reconcile
            # returns the same manifest.
            body = dict(body, master_rpc=self.rpc_address)
            code, resp = post_json_retrying(
                meta.http_address, "/reconcile", body, timeout=5.0,
                attempts=2, budget=self._retry_budget, idempotent=True,
            )
            if code != 200:
                raise RuntimeError(f"reconcile HTTP {code}: {resp}")
            return resp

        self.scheduler.on_reconcile = reconcile_transport
        self._m_scrape_failures = self.cluster_metrics.counter(
            "xllm_cluster_scrape_failures_total",
            "Instance /metrics scrapes that failed during aggregation",
        )
        # Scrape COST, not just failures: one slow engine inflating the
        # fleet /metrics path shows up here before it times out.
        self._m_scrape_ms = self.cluster_metrics.histogram(
            "xllm_cluster_scrape_ms",
            "Per-instance /metrics scrape latency during aggregation",
            labelnames=("instance",),
        )
        self._m_scrape_conflicts = self.cluster_metrics.counter(
            "xllm_cluster_scrape_type_conflicts_total",
            "Instance metric families skipped during aggregation because "
            "their # TYPE disagreed with the first-seen kind",
        )
        # Per-instance monotonic-clock offset estimators, fed by the
        # heartbeat piggyback samples (docs/OBSERVABILITY.md, Distributed
        # tracing): GET /trace shifts instance spans into the master
        # clock domain with these.
        self._clocks: Dict[str, ClockSync] = {}
        self._clocks_mu = threading.Lock()
        # Long-lived scrape pool: its threads keep get_raw's thread-local
        # keep-alive connections warm across scrape intervals (a per-call
        # pool would pay thread start-up + a fresh TCP connect to every
        # instance on every scrape).
        from concurrent.futures import ThreadPoolExecutor

        self._scrape_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="metrics-scrape"
        )

        def notify_flip(name: str, attempt: int) -> None:
            # Role resolved at SEND time from the registry (not frozen at
            # event time): a delayed delivery racing a flip-back would
            # otherwise park the engine on a stale role.
            meta = self.scheduler.instance_mgr.get_instance(name)
            if meta is None:
                return  # deregistered since the flip: nothing to notify
            role = meta.current_type.name
            err = ""
            flip_body: Dict[str, Any] = {"role": role}
            if self.scheduler.master_epoch:
                flip_body["master_epoch"] = self.scheduler.master_epoch
            try:
                code, resp = post_json(
                    meta.http_address, "/flip", flip_body, timeout=5.0
                )
                if code != 200:
                    err = f"HTTP {code}: {resp}"
            except Exception as e:  # instance may be mid-restart
                err = str(e)
            if err:
                logger.warning(
                    "flip notify %s -> %s failed (attempt %d): %s",
                    name, role, attempt, err,
                )
                # Bounded retry on the next master-loop tick; a dead
                # instance leaves the registry and stops the retries
                # naturally, the bound stops a live-but-broken one.
                if attempt < 5:
                    self.scheduler.instance_mgr.requeue_flip(name, attempt + 1)

        self.scheduler.on_role_flip = notify_flip

    # ------------------------------------------------------------------ #
    def start(self) -> None:
        self.http.start()
        self.rpc.start()
        # The initial election may have completed inside the scheduler's
        # constructor, before advertised_rpc was installed — publish now.
        self.scheduler.advertise_master_rpc()
        logger.info(
            "master serving http=:%d rpc=:%d", self.http.port, self.rpc.port
        )

    def stop(self) -> None:
        if not self._killed:
            self.http.stop()
            self.rpc.stop()
        self.scheduler.stop(drain_timeout_s=0.0 if self._killed else 10.0)
        self._scrape_pool.shutdown(wait=False)

    def kill(self) -> None:
        """UNGRACEFUL master death for chaos tests/benches: both HTTP
        planes drop (in-flight exchanges included), the election
        keepalive stops WITHOUT revoking the lease — the master key
        lingers until TTL expiry, exactly like a crashed master process —
        and the scheduler's loops halt. Standbys take over only once the
        store's liveness mechanism fires; a later stop() still runs the
        remaining teardown."""
        self._killed = True
        self.scheduler._stop.set()
        self.scheduler._dispatch_gate.clear()
        self.scheduler._election.kill()
        for srv in (self.http, self.rpc):
            try:
                # ZERO drain: a crash does not finish in-flight streams.
                srv.stop(drain_s=0.0)
            except TypeError:  # threaded backend has no drain knob
                srv.stop()
        self._scrape_pool.shutdown(wait=False)

    @property
    def http_address(self) -> str:
        return f"{self.http.host}:{self.http.port}"

    @property
    def rpc_address(self) -> str:
        return f"{self.rpc.host}:{self.rpc.port}"

    # ------------------------------------------------------------------ #
    # client plane
    # ------------------------------------------------------------------ #

    def handle_client_get(self, h: HttpJsonApi) -> None:
        route = h.route
        if route == "/hello":
            h.send_json({"message": "hello from xllm-service-tpu master"})
        elif route == "/v1/models":
            names = set()
            for m in self.scheduler.instance_mgr.list_instances():
                if m.model_name:
                    names.add(m.model_name)
                names.update(m.lora_adapters)
            models = sorted(names)
            h.send_json(
                {
                    "object": "list",
                    "data": [
                        {"id": m, "object": "model", "owned_by": "xllm-service-tpu"}
                        for m in models
                    ],
                }
            )
        elif route == "/metrics":
            self._handle_metrics(h)
        elif route.startswith("/trace/"):
            self._handle_trace(h, route[len("/trace/"):])
        else:
            h.send_error_json(404, f"no route {route}")

    def _handle_trace(self, h: HttpJsonApi, srid: str) -> None:
        """Distributed-trace collector (docs/OBSERVABILITY.md): pull every
        participant's ring spans for one service_request_id, shift them
        into the master clock domain with the heartbeat-derived offsets,
        and return ONE assembled timeline + per-stage blame + a Perfetto
        trace_event export with one track per process."""
        if not srid:
            h.send_error_json(400, "service_request_id required")
            return
        sched = self.scheduler
        master_spans = sched.span_ring.for_request(srid)
        names = sched.trace_participants(srid)
        if not names:
            # Unknown to the participant index (evicted or pre-dispatch):
            # fall back to asking the whole (small) fleet.
            names = [
                m.name for m in sched.instance_mgr.list_instances()
            ]
        participants = []
        offsets: Dict[str, Any] = {}
        for name in names:
            meta = sched.instance_mgr.get_instance(name)
            if meta is None:
                continue
            try:
                code, resp = get_json(
                    meta.http_address, f"/trace?srid={srid}", timeout=5.0
                )
            except Exception:
                continue
            if code != 200 or not isinstance(resp, dict):
                continue
            spans = resp.get("spans") or []
            off = self.clock_offset_ms(name)
            offsets[name] = round(off, 3)
            if spans:
                participants.append((name, spans, off))
        if not master_spans and not participants:
            h.send_error_json(404, f"no spans recorded for {srid}")
            return
        merged = assemble_trace("master", master_spans, participants)
        h.send_json(
            {
                "service_request_id": srid,
                "processes": ["master"] + [p[0] for p in participants],
                "offsets_ms": offsets,
                "blame_ms": blame_stages(merged),
                "spans": merged,
                "chrome": trace_to_chrome(merged),
            }
        )

    def _handle_metrics(self, h: HttpJsonApi) -> None:
        inst = h.query().get("instance")
        if inst:
            # Passthrough to one instance (reference behavior,
            # service.cpp:452-457): forward body + content type verbatim so
            # the Prometheus exposition format survives.
            meta = self.scheduler.instance_mgr.get_instance(inst)
            if meta is None:
                h.send_error_json(404, f"unknown instance {inst}")
                return
            try:
                status, body, ctype = get_raw(meta.http_address, "/metrics")
                h.send_response(status)
                h.send_header("Content-Type", ctype)
                h.send_header("Content-Length", str(len(body)))
                h.end_headers()
                h.wfile.write(body)
            except Exception as e:
                h.send_error_json(502, f"instance unreachable: {e}")
            return
        body = self._aggregate_metrics().encode()
        h.send_response(200)
        h.send_header("Content-Type", "text/plain; version=0.0.4")
        h.send_header("Content-Length", str(len(body)))
        h.end_headers()
        h.wfile.write(body)

    def _aggregate_metrics(self) -> str:
        """Cluster-wide exposition: master-local registries (scheduler +
        cluster), per-plane HTTP front-end stats, per-instance load
        gauges, and every registered instance's own /metrics scraped and
        re-labelled under instance="...". One TYPE line per family with
        every origin's samples grouped beneath it — the Prometheus text
        parser rejects duplicate TYPE lines / ungrouped series, which
        would fail the whole scrape."""
        mgr = self.scheduler.instance_mgr
        fams: "OrderedDict[str, Any]" = OrderedDict()
        # Local registries go straight in as families — no render->parse
        # round trip for data already in memory in the target shape.
        # (cluster_metrics is snapshotted AFTER the scrape loop below so
        # the scrape-latency histogram includes THIS exposure's scrapes.)
        fams.update(self.scheduler.metrics.families())
        # Front-end planes: both backends report stats() now (the event
        # loop's full set; the threaded backend's request/accept
        # counters) — emit whichever keys each plane has.
        plane_stats = [
            (plane, srv.stats())
            for plane, srv in (("http", self.http), ("rpc", self.rpc))
        ]
        for key, kind, metric in (
            ("open_connections", "gauge", "xllm_http_open_connections"),
            ("active_streams", "gauge", "xllm_http_active_streams"),
            ("buffered_bytes", "gauge", "xllm_http_buffered_bytes"),
            ("accepted_total", "counter", "xllm_http_accepted_total"),
            ("requests_total", "counter", "xllm_http_requests_total"),
            # stats() keys predate the naming convention; the exported
            # counter names carry the mandatory _total suffix.
            ("slow_client_closes", "counter",
             "xllm_http_slow_client_closes_total"),
            ("rejected_connections", "counter",
             "xllm_http_rejected_connections_total"),
        ):
            samples = [
                (f'{{plane="{plane}"}}', str(st[key]))
                for plane, st in plane_stats
                if key in st
            ]
            if samples:
                fams[metric] = (kind, "", samples)
        # Event-loop registries (loop-lag histogram), one per plane.
        for plane, srv in (("http", self.http), ("rpc", self.rpc)):
            reg = getattr(srv, "metrics", None)
            if reg is not None:
                absorb_exposition(
                    fams, reg.render(), extra_labels={"plane": plane}
                )
        load = mgr.get_load_metrics()
        fams["xllm_instance_waiting_requests"] = ("gauge", "", [
            (f'{{instance="{name}"}}', str(m.waiting_requests_num))
            for name, m in sorted(load.items())
        ])
        fams["xllm_instance_kv_cache_usage"] = ("gauge", "", [
            (f'{{instance="{name}"}}', f"{m.gpu_cache_usage_perc:.4f}")
            for name, m in sorted(load.items())
        ])
        fams["xllm_instance_health_state"] = ("gauge", "", [
            (
                f'{{instance="{name}",state="{state}"}}',
                str(HEALTH_STATE_VALUES.get(state, 0)),
            )
            for name, state in sorted(mgr.health_states().items())
        ])
        # Scrape each instance's registry-rendered /metrics and merge its
        # engine series under an instance label. Scrapes run CONCURRENTLY
        # (a dead instance costs one 2 s timeout, not a serial stall that
        # blows the scraper's own deadline on a large fleet); failures
        # skip the instance (counted) — one dead engine must not fail the
        # fleet scrape. The merge itself stays on this thread, in name
        # order, so the exposition is deterministic.
        instances = sorted(mgr.list_instances(), key=lambda m: m.name)

        def scrape(meta):
            # Timed INSIDE the pool thread so the histogram measures the
            # instance's own /metrics latency, not queueing behind other
            # scrapes in the pool.
            t0 = time.monotonic()
            try:
                status, raw, _ = get_raw(
                    meta.http_address, "/metrics", timeout=2.0
                )
            finally:
                self._m_scrape_ms.labels(instance=meta.name).observe(
                    (time.monotonic() - t0) * 1000.0
                )
            if status != 200:
                raise RuntimeError(f"HTTP {status}")
            return raw.decode("utf-8", "replace")

        futures = [self._scrape_pool.submit(scrape, m) for m in instances]
        for meta, fut in zip(instances, futures):
            try:
                conflicts = absorb_exposition(
                    fams, fut.result(timeout=10.0),
                    extra_labels={"instance": meta.name},
                )
                if conflicts:
                    # Deterministic skip (first-seen kind wins); count the
                    # dropped families instead of losing them silently.
                    self._m_scrape_conflicts.inc(len(conflicts))
                    logger.warning(
                        "metrics aggregation skipped %d kind-conflicting "
                        "families from %s: %s",
                        len(conflicts), meta.name, ", ".join(conflicts),
                    )
            except Exception:
                self._m_scrape_failures.inc()
        # Cluster-level registry last: scrape_ms observations from the
        # loop above are already in it, so the histogram is never a
        # TYPE-only family on the first exposure. absorb via the families
        # dict, not update(): an instance-absorbed family of the same
        # name must not be clobbered.
        for name, fam in self.cluster_metrics.families().items():
            if name in fams:
                kind, _help, samples = fams[name]
                if kind == fam[0]:
                    fams[name] = (kind, fam[1] or _help, fam[2] + samples)
            else:
                fams[name] = fam
        return render_families(fams)

    def _redirect_if_standby(
        self, h: HttpJsonApi, xh: Optional[Dict[str, str]] = None
    ) -> bool:
        """Fenced front door (docs/FAULT_TOLERANCE.md): a replica that
        does not hold the master lease never accepts generation work — it
        307-redirects to the current master (Location + a JSON body
        naming it) or 503s when no master exists yet. A RECONCILING
        master still holds the lease and accepts (the dispatch gate parks
        the work until the takeover scan completes). Returns True when
        the exchange was handled here."""
        sched = self.scheduler
        if sched.is_master:
            return False
        cur = sched.current_master_identity()
        if cur and cur != sched.election_identity:
            h.send_json(
                {
                    "error": {
                        "message": (
                            "this replica is not the master; retry "
                            f"against {cur}"
                        ),
                        "type": "not_master",
                    },
                    "master": cur,
                },
                status=307,
                extra_headers={
                    **(xh or {}), "Location": f"http://{cur}{h.path}",
                },
            )
        else:
            h.send_error_json(
                503, "no master elected yet; retry shortly",
                etype="not_master", extra_headers=xh,
            )
        return True

    def handle_client_post(self, h: HttpJsonApi) -> None:
        route = h.route
        if route == "/v1/completions":
            self._serve_generation(h, chat=False)
        elif route == "/v1/chat/completions":
            self._serve_generation(h, chat=True)
        elif route == "/v1/embeddings":
            # The reference rejects embeddings outright (service.cpp:441-442);
            # serving them here EXCEEDS parity: the service tokenizes (same
            # injection contract as generation), an instance pools hidden
            # states.
            self._serve_embeddings(h)
        else:
            h.send_error_json(404, f"no route {route}")

    def _serve_embeddings(self, h: HttpJsonApi) -> None:
        if self._redirect_if_standby(h):
            return
        body = h.read_json()
        if body is None:
            h.send_error_json(400, "invalid JSON body")
            return
        raw = body.get("input")
        if isinstance(raw, str):
            raw = [raw]
        if isinstance(raw, list) and raw and all(
            isinstance(x, int) for x in raw
        ):
            raw = [raw]  # single pre-tokenized input
        if not isinstance(raw, list) or not raw:
            h.send_error_json(400, "input (string or array) is required")
            return
        token_lists: List[List[int]] = []
        for x in raw:
            if isinstance(x, str):
                ids = self.scheduler.tokenizer.encode(x)
            elif isinstance(x, list) and all(isinstance(i, int) for i in x):
                ids = list(x)
            else:
                h.send_error_json(400, "input items must be strings or id lists")
                return
            if not ids:
                h.send_error_json(400, "input item tokenized to nothing")
                return
            token_lists.append(ids)
        # Route like a prefill: the policy's pair choice keeps load skew
        # visible to it; embeddings are synchronous one-shot calls.
        routing = self.scheduler.route_only(token_lists[0])
        if routing is None:
            h.send_error_json(503, "no instances registered")
            return
        meta = self.scheduler.instance_mgr.get_instance(routing.prefill_name)
        if meta is None:
            h.send_error_json(503, "routed instance vanished")
            return
        try:
            code, resp = post_json(
                meta.http_address,
                "/v1/embeddings",
                {"model": body.get("model") or "", "token_ids": token_lists},
                timeout=120.0,
            )
        except Exception as e:
            h.send_error_json(502, f"instance unreachable: {e}")
            return
        if code != 200:
            h.send_error_json(502, f"instance rejected embeddings: {resp}")
            return
        h.send_json(resp)

    def _parse_request(
        self, body: Dict[str, Any], chat: bool
    ) -> ServiceRequest:
        req = ServiceRequest(
            service_request_id=make_service_request_id(
                "chatcmpl" if chat else "cmpl"
            ),
            model=body.get("model", ""),
            stream=bool(body.get("stream", False)),
            include_usage=bool(
                (body.get("stream_options") or {}).get("include_usage", False)
            ),
            echo=bool(body.get("echo", False)),
            offline=bool(body.get("offline", False)),
            n=int(body.get("n", 1)),
            max_tokens=int(
                body.get("max_tokens")
                or body.get("max_completion_tokens")
                or 0
            ),
            temperature=float(body.get("temperature", 1.0)),
            top_p=float(body.get("top_p", 1.0)),
            # Admission fair-share key: the OpenAI `user` field when the
            # client sends one, else the model name (service/admission.py).
            tenant=str(body.get("user") or body.get("model") or ""),
        )
        raw_stop = body.get("stop")
        if raw_stop is not None:
            if isinstance(raw_stop, str):
                raw_stop = [raw_stop]
            if not isinstance(raw_stop, list) or not all(
                isinstance(s, str) for s in raw_stop
            ):
                raise ValueError("stop must be a string or array of strings")
            if len(raw_stop) > 4:
                raise ValueError("stop supports at most 4 sequences")
            req.stop = [s for s in raw_stop if s]
        if chat:
            req.messages = parse_messages(body.get("messages", []))
            req.tools = body.get("tools")
            req.top_logprobs = int(body.get("top_logprobs", 0) or 0)
            if body.get("logprobs"):
                req.logprobs = max(1, req.top_logprobs)
        else:
            text, token_ids, err = parse_prompt_field(body.get("prompt", ""))
            if err:
                raise ValueError(err)
            req.prompt = text
            req.token_ids = token_ids
            lp = body.get("logprobs")
            req.logprobs = int(lp) if lp is not None else None
        return req

    def _serve_generation(self, h: HttpJsonApi, chat: bool) -> None:
        xrid = h.x_request_id()
        xh = {"x-request-id": xrid} if xrid else None
        if self._redirect_if_standby(h, xh):
            return
        body = h.read_json()
        if body is None:
            h.send_error_json(400, "invalid JSON body", extra_headers=xh)
            return
        if chat and not body.get("messages"):
            h.send_error_json(400, "messages is required", extra_headers=xh)
            return
        if not chat and not body.get("prompt"):
            h.send_error_json(400, "prompt is required", extra_headers=xh)
            return
        try:
            req = self._parse_request(body, chat)
        except (ValueError, TypeError) as e:
            h.send_error_json(400, str(e), extra_headers=xh)
            return
        status = self.scheduler.schedule(req)
        if not status.ok():
            eh = dict(xh) if xh else {}
            if status.code == StatusCode.RESOURCE_EXHAUSTED and req.retry_after_s:
                # Admission shed: tell well-behaved clients exactly when
                # to come back instead of letting them hammer the door.
                eh["Retry-After"] = str(int(req.retry_after_s))
            h.send_error_json(
                _HTTP_STATUS.get(status.code, 500), status.message,
                extra_headers=eh or None,
            )
            return

        if self.scheduler.instance_mgr.get_instance(req.routing.prefill_name) is None:
            # Unwind the SCHEDULE bookkeeping recorded by schedule() — the
            # request never dispatches. The admission slot goes back too.
            self.scheduler.admission.release(req)
            self.scheduler.instance_mgr.update_request_metrics(
                req.routing, RequestAction.CANCEL, len(req.token_ids)
            )
            h.send_error_json(
                503, "prefill instance vanished", extra_headers=xh
            )
            return
        if xrid and self.scheduler.tracer.enabled:
            self.scheduler.tracer.record(
                req.service_request_id, "x_request_id", xrid
            )
        # Mid-stream resume eligibility (docs/FAULT_TOLERANCE.md): token
        # replay reconstructs exactly one sequence, guided FSM state does
        # not survive a re-prefill of emitted tokens, and media embeddings
        # would need a fresh encode pass — all of those fall back to the
        # pre-token-only replay (then error-finish).
        req.resumable = (
            req.n <= 1
            and int(body.get("best_of") or 1) <= 1
            and not body.get("response_format")
            and not req.media_parts
        )
        stream = HttpClientStream(h, req.stream, x_request_id=xrid)

        path = "/v1/chat/completions" if chat else "/v1/completions"
        mgr = self.scheduler.instance_mgr

        def dispatch() -> None:
            # Forward to the CURRENT routed prefill instance (re-resolved
            # per call: re-dispatch after instance death changes routing;
            # reference: service.cpp:147-191, ack-mode — tokens return via
            # /rpc/generations). The wire id is attempt-versioned so a
            # replaced attempt's late pushes can't reach the client.
            meta = mgr.get_instance(req.routing.prefill_name)
            if meta is None:
                self.scheduler.fail_request(
                    req.service_request_id,
                    StatusCode.UNAVAILABLE,
                    "prefill instance vanished",
                )
                return
            wire = req.wire_srid or req.service_request_id
            epoch = self.scheduler.master_epoch
            # Distributed-tracing context: trace_id is the BASE service
            # id (stable across replay attempts), the parent span names
            # the attempt-versioned dispatch that spawned the downstream
            # work, origin_epoch fences stale traces.
            trace_ctx = TraceContext(
                trace_id=req.service_request_id,
                parent_span=f"dispatch:{wire}",
                origin_epoch=epoch,
            ).to_json()
            stream_mm = False
            if req.media_parts:
                from xllm_service_tpu.cluster.encoder_fabric import (
                    encoder_fabric_enabled,
                )

                # Encoder fabric (docs/EPD.md): dispatch the encoder
                # CONCURRENTLY with the text forward — the prefill peer
                # admits the text with an open stream handle and prefills
                # text chunks while the encoder's per-item session lands
                # embeddings (re-route retry across the encode tier on
                # failure).
                stream_mm = encoder_fabric_enabled(self.config)
            if req.media_parts and not stream_mm:
                # Legacy synchronous EPD (and the hatch-off path): the
                # encoder computes media embeddings and pushes them to
                # the prefill peer's /mm/import BEFORE the text request
                # arrives there. Re-pushing embeddings is idempotent, so
                # the retry wrapper may redeliver.
                enc = mgr.get_instance(req.routing.encode_name)
                if enc is None:
                    self.scheduler.fail_request(
                        req.service_request_id,
                        StatusCode.UNAVAILABLE,
                        "encode instance vanished",
                    )
                    return
                try:
                    code, resp = post_json_retrying(
                        enc.http_address,
                        "/encode",
                        {
                            "service_request_id": wire,
                            "parts": req.media_parts,
                            "positions": req.mm_positions,
                            "target": meta.http_address,
                            "master_epoch": epoch,
                            "trace": trace_ctx,
                        },
                        # Generous: the encoder's FIRST request pays its
                        # XLA compile inside this call.
                        timeout=180.0,
                        attempts=self._retry_attempts,
                        budget=self._retry_budget,
                        idempotent=True,
                    )
                except Exception as e:
                    code, resp = 0, str(e)
                if code != 200:
                    # Breaker signal only for transport failures and
                    # instance-side (5xx) errors: a client's bad media
                    # (4xx) must never eject a healthy encoder.
                    if code == 0 or code >= 500:
                        mgr.record_dispatch_failure(enc.name)
                    else:
                        mgr.record_dispatch_success(enc.name)
                    self.scheduler.fail_request(
                        req.service_request_id,
                        StatusCode.UNAVAILABLE,
                        f"encoder failed: {resp}",
                    )
                    return
                mgr.record_dispatch_success(enc.name)
            fwd = augment_forwarded_request(
                body, wire, req.resume_token_ids or req.token_ids,
                req.routing,
                decode_response_to_service=(
                    self.config.enable_decode_response_to_service
                ),
                master_epoch=epoch,
                # Skip the fetch hint when a replay re-routed onto the
                # holder itself (the instance also self-checks).
                kv_fabric=(
                    req.kv_fabric
                    if req.kv_fabric
                    and req.kv_fabric.get("holder")
                    != req.routing.prefill_name
                    else None
                ),
                trace=trace_ctx,
            )
            if req.resume_base:
                # Token-replay resume: the last resume_base token_ids are
                # replayed output, not prompt — the instance fences its
                # generation budget and (FakeEngine) its echo script on it.
                fwd["resume_from"] = req.resume_base
            if req.mm_positions:
                fwd["mm_positions"] = list(req.mm_positions)
                if req.mm_grids:
                    fwd["mm_grids"] = [list(g) for g in req.mm_grids]
            if stream_mm:
                # Encoder dispatch CONCURRENT with the text forward
                # (docs/EPD.md): stage E overlaps the forward round-trip,
                # prefill admission, and the text chunks. Concurrency —
                # not strict forward-first — also keeps a legacy prefill
                # (hatch off, blocking /mm/import wait inside its serve
                # handler) from deadlocking against this thread.
                threading.Thread(
                    target=self._encode_fabric_async,
                    args=(req, wire, meta, epoch),
                    name=f"encode-dispatch-{wire}",
                    daemon=True,
                ).start()
            try:
                # Dispatch is NOT idempotent: the wrapper only retries
                # failures proven send-time (request never written); an
                # indeterminate failure falls through to replay on another
                # instance under a fresh wire id.
                code, resp = post_json_retrying(
                    meta.http_address, path, fwd, timeout=30.0,
                    attempts=self._retry_attempts,
                    budget=self._retry_budget,
                )
                # Breaker signal: a 5xx is an instance-side failure (a
                # wedged engine behind a live HTTP plane must still trip
                # the breaker); a 4xx is the CLIENT's error and proves the
                # instance healthy.
                if code >= 500:
                    mgr.record_dispatch_failure(meta.name)
                else:
                    mgr.record_dispatch_success(meta.name)
                if code != 200:
                    # A 4xx from the instance is the CLIENT's error
                    # (e.g. invalid logit_bias) — relay it as such
                    # instead of masking it as a service failure.
                    msg = resp
                    fenced = isinstance(resp, dict) and resp.get("fenced")
                    if isinstance(resp, dict):
                        msg = (resp.get("error") or {}).get(
                            "message", resp
                        )
                    if fenced:
                        # 412 stale-epoch: the FLEET is telling this
                        # replica it was deposed — not a client error,
                        # not an instance failure. The client retries
                        # against the current master.
                        self.scheduler.fail_request(
                            req.service_request_id,
                            StatusCode.UNAVAILABLE,
                            "dispatch fenced (this master was deposed); "
                            "retry against "
                            + (
                                self.scheduler.current_master_identity()
                                or "the current master"
                            ),
                        )
                        return
                    self.scheduler.fail_request(
                        req.service_request_id,
                        StatusCode.INVALID_ARGUMENT
                        if 400 <= code < 500
                        else StatusCode.UNAVAILABLE,
                        f"prefill rejected: {msg}",
                    )
            except Exception as e:
                # Fast failure (connection refused / timeout): feed the
                # breaker, then try another instance before giving up —
                # lease expiry would take seconds to notice. Pre-token
                # requests replay whole; mid-stream ones resume by token
                # replay.
                mgr.record_dispatch_failure(meta.name)
                if not (
                    self.scheduler.redispatch_request(
                        req.service_request_id, exclude=meta.name
                    )
                    or self.scheduler.resume_request(
                        req.service_request_id, exclude=meta.name
                    )
                ):
                    self.scheduler.fail_request(
                        req.service_request_id,
                        StatusCode.UNAVAILABLE,
                        f"prefill unreachable: {e}",
                    )

        # The scheduler wraps dispatch with span/queue-delay
        # instrumentation; use its wrapper so re-dispatch and the first
        # forward are timed identically.
        dispatch = self.scheduler.record_new_request(
            req, stream,
            cancel_callback=lambda: self._cancel_on_instance(req),
            dispatch=dispatch,
        )

        if self.scheduler.should_defer_offline(req):
            self.scheduler.park_offline(req, dispatch)
        else:
            try:
                dispatch()
            except NotMasterError as e:
                # Demoted between the redirect check and the forward (or
                # the reconcile park timed out): error the exchange toward
                # the current master instead of leaving it to the deadline.
                self.scheduler.fail_request(
                    req.service_request_id, StatusCode.UNAVAILABLE, str(e)
                )

        # Hold the exchange open until the scheduler finishes it. The
        # threaded backend blocks this handler thread; the event backend
        # parks the exchange on the connection and returns, enforcing the
        # deadline with a loop timer — a stream holds a socket, not a
        # thread.
        def fail_deadline() -> None:
            self.scheduler.fail_request(
                req.service_request_id, StatusCode.DEADLINE_EXCEEDED, "timeout"
            )

        h.hold(stream, self._request_timeout_s, fail_deadline)

    def _encode_fabric_async(self, req, wire, prefill_meta, epoch) -> None:
        """Background encode dispatch for one media request (encoder
        fabric): runs concurrently with the text forward. When every
        encode candidate fails, the request error-finishes AND the
        prefill peer's parked work is cancelled so the stream-deadline
        reject never has to fire."""
        try:
            ok, emsg = self._dispatch_encode_fabric(
                req, wire, prefill_meta, epoch
            )
        except Exception as e:  # noqa: BLE001 — daemon thread must report
            ok, emsg = False, str(e)
        if ok:
            return
        try:
            post_json(
                prefill_meta.http_address, "/cancel",
                {"service_request_id": wire, "master_epoch": epoch},
                timeout=5.0,
            )
        except Exception:
            pass
        self.scheduler.fail_request(
            req.service_request_id,
            StatusCode.UNAVAILABLE,
            f"encoder failed: {emsg}",
        )

    def _dispatch_encode_fabric(self, req, wire, prefill_meta, epoch):
        """Encode-tier dispatch with re-route retry (encoder fabric,
        docs/EPD.md): try the scheduler-routed encoder first, then — on
        transport/5xx failure, which also feeds the breaker exactly like
        the LM tiers — re-resolve a DIFFERENT modality-covering encoder
        and try again, up to 3 candidates. Returns (ok, error_message).
        A 4xx is the client's bad media: no re-route, fail once."""
        mgr = self.scheduler.instance_mgr
        required = {
            {2: "audio", 4: "video"}.get(len(p["shape"]), "image")
            for p in req.media_parts
        }
        tried = set()
        enc_name = req.routing.encode_name
        last_err = "no ENCODE instance available"
        for _attempt in range(3):
            if not enc_name or enc_name in tried:
                enc_name = mgr.next_encode_instance(
                    required, exclude=tried
                )
            if not enc_name:
                break
            tried.add(enc_name)
            enc = mgr.get_instance(enc_name)
            if enc is None:
                enc_name = ""
                continue
            try:
                faults.point(
                    "encode.dispatch", instance=enc_name, srid=wire
                )
                code, resp = post_json_retrying(
                    enc.http_address,
                    "/encode",
                    {
                        "service_request_id": wire,
                        "parts": req.media_parts,
                        "positions": req.mm_positions,
                        "target": prefill_meta.http_address,
                        "master_epoch": epoch,
                        "trace": TraceContext(
                            trace_id=req.service_request_id,
                            parent_span=f"dispatch:{wire}",
                            origin_epoch=epoch,
                        ).to_json(),
                    },
                    # Generous: the encoder's FIRST request pays its XLA
                    # compile inside this call.
                    timeout=180.0,
                    attempts=self._retry_attempts,
                    budget=self._retry_budget,
                    idempotent=True,
                )
            except Exception as e:
                code, resp = 0, str(e)
            if code == 200:
                mgr.record_dispatch_success(enc_name)
                req.routing.encode_name = enc_name
                return True, ""
            last_err = str(resp)
            if code == 0 or code >= 500:
                # Instance-side failure: feed the breaker and re-route
                # to another encoder (third-role failover parity).
                mgr.record_dispatch_failure(enc_name)
                enc_name = ""
                continue
            # 4xx: the client's bad media — the encoder is healthy and a
            # re-route would just fail identically.
            mgr.record_dispatch_success(enc_name)
            return False, last_err
        return False, last_err

    def _cancel_on_instance(self, req: ServiceRequest) -> None:
        """Propagate a client cancel to the routed instance(s). /cancel is
        idempotent, so the retry wrapper may redeliver; failures feed the
        breaker and the xllm_service_cancel_errors_total counter instead
        of vanishing silently (a dead cancel path leaks engine work)."""
        for name in {req.routing.prefill_name, req.routing.decode_name}:
            meta = self.scheduler.instance_mgr.get_instance(name)
            if meta is None:
                continue
            try:
                post_json_retrying(
                    meta.http_address,
                    "/cancel",
                    {
                        "service_request_id": (
                            req.wire_srid or req.service_request_id
                        ),
                        "master_epoch": self.scheduler.master_epoch,
                    },
                    timeout=5.0,
                    attempts=self._retry_attempts,
                    budget=self._retry_budget,
                    idempotent=True,
                )
                self.scheduler.instance_mgr.record_dispatch_success(name)
            except Exception as e:
                self.scheduler.m_cancel_errors.inc()
                self.scheduler.instance_mgr.record_dispatch_failure(name)
                logger.debug(
                    "cancel of %s on %s failed: %s",
                    req.service_request_id, name, e,
                )

    # ------------------------------------------------------------------ #
    # instance plane
    # ------------------------------------------------------------------ #

    def handle_rpc_get(self, h: HttpJsonApi) -> None:
        route = h.route
        mgr = self.scheduler.instance_mgr
        if route == "/rpc/instance_info":
            name = h.query().get("name", "")
            meta = mgr.get_instance(name)
            if meta is None:
                h.send_error_json(404, f"unknown instance {name}")
            else:
                h.send_json(meta.to_json())
        elif route == "/rpc/static_prefill_list":
            h.send_json({"instances": mgr.prefill_instances()})
        elif route == "/rpc/static_decode_list":
            h.send_json({"instances": mgr.decode_instances()})
        else:
            h.send_error_json(404, f"no route {route}")

    def handle_rpc_post(self, h: HttpJsonApi) -> None:
        route = h.route
        body = h.read_json()
        if body is None:
            h.send_error_json(400, "invalid JSON body")
            return
        if route == "/rpc/hello":
            h.send_json({"ok": True, "name": body.get("name", "")})
        elif route == "/rpc/register":
            self._handle_register(h, body)
        elif route == "/rpc/heartbeat":
            self._handle_heartbeat(h, body)
        elif route == "/rpc/deregister":
            self._handle_deregister(h, body)
        elif route == "/rpc/generations":
            self._handle_generations(h, body)
        elif route == "/rpc/fabric/evict_offer":
            self._handle_evict_offer(h, body)
        else:
            h.send_error_json(404, f"no route {route}")

    def _handle_evict_offer(self, h: HttpJsonApi, body: Dict[str, Any]) -> None:
        """Coordinated multi-tier eviction (docs/KV_CACHE.md): an instance
        about to drop blocks from its coldest tier asks where they should
        live. Per-hash verdicts come from the scheduler's PrefixFabric;
        a non-master replica refuses (its index view may be stale)."""
        if not self.scheduler.is_master:
            h.send_error_json(503, "not the master", etype="not_master")
            return
        try:
            hashes = [
                bytes.fromhex(x) for x in body.get("block_hashes") or []
            ]
        except ValueError:
            h.send_error_json(400, "malformed block hashes")
            return
        decisions = self.scheduler.prefix_fabric.evict_decisions(
            str(body.get("name") or ""), hashes
        )
        h.send_json({"ok": True, "decisions": decisions})

    def _handle_register(self, h: HttpJsonApi, body: Dict[str, Any]) -> None:
        try:
            meta = InstanceMetaInfo.from_json(body.get("meta", body))
        except Exception as e:
            h.send_error_json(400, f"bad meta: {e}")
            return
        if not meta.name:
            h.send_error_json(400, "meta.name required")
            return
        ttl = max(
            3.0 * self.config.heartbeat_interval_s,
            self.config.instance_lease_min_ttl_s,
        )
        lease = self._store.grant_lease(ttl)
        self._store.set(instance_key(meta), meta.serialize(), lease_id=lease)
        with self._leases_mu:
            # A stale prior lease is left to expire on its own; revoking it
            # here would delete the key the new lease now owns.
            self._leases[meta.name] = lease
        h.send_json(
            {
                "ok": True,
                "lease_ttl_s": ttl,
                "heartbeat_interval_s": self.config.heartbeat_interval_s,
            }
        )

    def _handle_deregister(self, h: HttpJsonApi, body: Dict[str, Any]) -> None:
        """Graceful shutdown: revoke the instance's registration lease NOW
        (DELETE event -> registry drop -> routing stops immediately),
        instead of leaving a dead endpoint routable until the TTL lapses.
        Ungraceful death keeps the lease-expiry path (sweeper)."""
        name = body.get("name", "")
        if not name:
            h.send_error_json(400, "name required")
            return
        with self._leases_mu:
            lease = self._leases.pop(name, None)
        if lease is not None:
            self._store.revoke_lease(lease)
        h.send_json({"ok": True, "removed": lease is not None})

    def _record_clock_sample(self, name: str, clk: Any) -> None:
        """One heartbeat's monotonic-offset bounds for `name` (clock
        alignment, docs/OBSERVABILITY.md): the request's send stamp gives
        an UPPER bound on (master_mono - instance_mono); the echoed reply
        stamp from the PREVIOUS response gives a LOWER bound."""
        if not isinstance(clk, dict):
            return
        now_ms = time.monotonic() * 1000.0
        with self._clocks_mu:
            sync = self._clocks.setdefault(name, ClockSync())
        try:
            if clk.get("send_mono_ms") is not None:
                sync.sample_upper(now_ms - float(clk["send_mono_ms"]))
            if (
                clk.get("echo_master_mono_ms") is not None
                and clk.get("echo_recv_mono_ms") is not None
            ):
                sync.sample_lower(
                    float(clk["echo_master_mono_ms"])
                    - float(clk["echo_recv_mono_ms"])
                )
        except (TypeError, ValueError):
            pass

    def clock_offset_ms(self, name: str) -> float:
        with self._clocks_mu:
            sync = self._clocks.get(name)
        return sync.offset_ms() if sync is not None else 0.0

    def _handle_heartbeat(self, h: HttpJsonApi, body: Dict[str, Any]) -> None:
        name = body.get("name", "")
        if not self.scheduler.is_master:
            # Deposed (or never-elected) replica: do NOT keepalive the
            # instance's lease — this replica doesn't own the fleet — and
            # hand back the ACTIVE master's advertised rpc address so the
            # instance re-points even if a /reconcile never reached it.
            h.send_json(
                {
                    "ok": False,
                    "master_rpc": self.scheduler.current_master_rpc(),
                }
            )
            return
        with self._leases_mu:
            lease = self._leases.get(name)
        alive = lease is not None and self._store.keepalive(lease)
        if not alive or self.scheduler.instance_mgr.get_instance(name) is None:
            # Lease lost (or this replica never saw the registration):
            # tell the engine to re-register (the etcd-expiry analog).
            h.send_json({"ok": False, "reregister": True})
            return
        self._record_clock_sample(name, body.get("clock"))
        load = body.get("load_metrics")
        lat = body.get("latency_metrics")
        cache = body.get("cache_event")
        self.scheduler.handle_instance_heartbeat(
            name,
            load_metrics=LoadMetrics.from_json(load) if load else None,
            latency_metrics=LatencyMetrics.from_json(lat) if lat else None,
            cache_event=KvCacheEvent.from_json(cache) if cache else None,
        )
        # Role reconciliation (flip notifications are best-effort + bounded
        # retry; a restart or a dropped event would otherwise desync the
        # engine's serving role from the registry forever): on mismatch,
        # queue a fresh notification.
        reported = body.get("serving_role", "")
        meta = self.scheduler.instance_mgr.get_instance(name)
        if (
            reported
            and meta is not None
            and reported != meta.current_type.name
            # Only PD/MIX roles are flip-notifiable; an ENCODE instance
            # can never accept /flip, so a mismatch there must not loop.
            and meta.current_type.name in ("PREFILL", "DECODE", "MIX")
        ):
            self.scheduler.instance_mgr.requeue_flip(name, 1)
        resp: Dict[str, Any] = {"ok": True}
        if isinstance(body.get("clock"), dict):
            # Reply stamp: the instance echoes it (with its own receive
            # stamp) on the NEXT beat, closing the offset's lower bound.
            resp["clock"] = {
                "master_mono_ms": round(time.monotonic() * 1000.0, 3)
            }
        if self.scheduler.take_cache_resync(name):
            # Breaker ejection pruned this instance's KV-index locations;
            # deltas can't rebuild them — ask for the full committed-block
            # snapshot on the next beat (docs/KV_CACHE.md).
            resp["resync_cache"] = True
        h.send_json(resp)

    def _handle_generations(self, h: HttpJsonApi, body: Dict[str, Any]) -> None:
        try:
            pushed_epoch = int(body.get("master_epoch") or 0)
        except (TypeError, ValueError):
            pushed_epoch = 0
        if not self.scheduler.is_master or (
            pushed_epoch and pushed_epoch > self.scheduler.master_epoch
        ):
            # A deposed master must not answer the token stream: its
            # `cont` map would authoritatively cancel work the CURRENT
            # master dispatched. That covers both the replica that KNOWS
            # it was demoted and the split-brain window where the fleet's
            # fence epoch (stamped on the push) has already moved past
            # this replica's term but its keepalive hasn't failed yet.
            # 503 makes the instance's push loop retry; by the next
            # attempt its heartbeat has re-pointed.
            h.send_error_json(
                503,
                "not the master; retry against "
                + (self.scheduler.current_master_rpc() or "current master"),
                etype="not_master",
            )
            return
        cont: Dict[str, bool] = {}
        for j in body.get("gens", []):
            try:
                out = output_from_json(j)
            except Exception:
                continue
            cont[out.service_request_id] = self.scheduler.handle_generation(out)
        h.send_json({"cont": cont})


def main(argv=None) -> None:
    logging.basicConfig(level=logging.INFO)
    config = ServiceConfig.from_args(argv)
    master = Master(config)
    master.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        master.stop()


if __name__ == "__main__":
    main()
