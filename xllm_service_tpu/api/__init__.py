"""API tier: master (HTTP+RPC), instance server, control-plane client, wire protocol."""

from xllm_service_tpu.api.client import HeartbeatLoop, MasterClient
from xllm_service_tpu.api.fake_engine import FakeEngine
from xllm_service_tpu.api.master import Master
from xllm_service_tpu.api.protocol import (
    augment_forwarded_request,
    output_from_json,
    output_to_json,
)

__all__ = [
    "HeartbeatLoop",
    "MasterClient",
    "FakeEngine",
    "Master",
    "augment_forwarded_request",
    "output_from_json",
    "output_to_json",
]
