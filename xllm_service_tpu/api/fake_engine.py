"""Scriptable fake engine for cluster/service integration tests.

The reference's closest analog is examples/rpc_client_test.cpp:44-58 — a
fake instance that registers and heartbeats forever. This grows that idea
into a full engine stand-in (SURVEY.md §4 test plan): same interface as
runtime.engine.InferenceEngine (add_request/cancel/start/stop/metrics/
cache-event/profiling), but generation is a thread that echoes the prompt
(or a scripted list) token by token, so service-tier e2e tests exercise the
real HTTP/RPC/scheduler stack without JAX in the process.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from xllm_service_tpu.common.types import (
    FinishReason,
    KvCacheEvent,
    LatencyMetrics,
    LoadMetrics,
    RequestOutput,
    SequenceOutput,
    Status,
    StatusCode,
    Usage,
)


class _FakeEmbedExecutor:
    hidden_size = 32

    def embed_tokens(self, inputs):
        import numpy as np

        out = np.zeros((len(inputs), self.hidden_size), np.float32)
        for i, ids in enumerate(inputs):
            rng = np.random.default_rng(abs(hash(tuple(ids))) % 2**32)
            v = rng.standard_normal(self.hidden_size).astype(np.float32)
            out[i] = v / np.linalg.norm(v)
        return out


class FakeEngine:
    def __init__(
        self,
        token_delay_s: float = 0.005,
        script: Optional[Sequence[int]] = None,
        ttft_ms: float = 20.0,
        fail_admission: bool = False,
    ):
        self.token_delay_s = token_delay_s
        self.script = list(script) if script is not None else None
        self.ttft_ms = ttft_ms
        self.fail_admission = fail_admission
        self._cancelled: Dict[str, bool] = {}
        self._mu = threading.Lock()
        self._active = 0
        self._cache_event = KvCacheEvent()
        self.cache_hashes: set = set()
        self.requests_seen: List = []
        # /v1/embeddings surface: deterministic unit vectors derived from
        # the token ids (the instance HTTP layer calls
        # engine.executor.embed_tokens like the real engine's).
        self.executor = _FakeEmbedExecutor()

    # -- engine interface ---------------------------------------------- #
    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass

    def add_request(self, req) -> None:
        self.requests_seen.append(req)
        if self.fail_admission:
            req.callback(
                RequestOutput(
                    request_id=req.request_id,
                    status=Status(StatusCode.RESOURCE_EXHAUSTED, "no blocks"),
                    finished=True,
                )
            )
            return
        if getattr(req, "prefill_only", False) and req.handoff is not None:
            t = threading.Thread(target=self._run_prefill_only, args=(req,),
                                 daemon=True)
        else:
            t = threading.Thread(target=self._run, args=(req,), daemon=True)
        with self._mu:
            self._active += 1
        t.start()

    def import_sequence(self, req, handoff) -> None:
        """Continue a handed-off sequence: emit tokens AFTER the first one
        (mirrors InferenceEngine.import_sequence)."""
        with self._mu:
            self._active += 1
        threading.Thread(
            target=self._run, args=(req,), kwargs={"skip_first": True},
            daemon=True,
        ).start()

    def _run_prefill_only(self, req) -> None:
        from xllm_service_tpu.runtime.engine import KVHandoff

        try:
            tokens = (
                self.script if self.script is not None
                else list(reversed(req.prompt_token_ids))
            ) or [0]
            time.sleep(self.ttft_ms / 1000.0)
            first = tokens[0]
            req.callback(
                RequestOutput(
                    request_id=req.request_id,
                    outputs=[SequenceOutput(index=0, token_ids=[first])],
                    usage=Usage(len(req.prompt_token_ids), 1),
                    finished=False,
                )
            )
            req.handoff(
                KVHandoff(
                    request_id=req.request_id,
                    token_ids=list(req.prompt_token_ids) + [first],
                    first_token=first,
                    first_logprob=0.0,
                    num_full_blocks=0,
                    block_hashes=[],
                    kv=None,
                    usage_prompt_tokens=len(req.prompt_token_ids),
                )
            )
        finally:
            with self._mu:
                self._active -= 1

    def cancel(self, request_id: str) -> None:
        with self._mu:
            self._cancelled[request_id] = True

    def get_load_metrics(self) -> LoadMetrics:
        with self._mu:
            return LoadMetrics(self._active, min(1.0, 0.1 * self._active))

    def get_latency_metrics(self, window_s: float = 30.0) -> LatencyMetrics:
        return LatencyMetrics(int(self.ttft_ms), int(self.token_delay_s * 1000))

    def take_cache_event(self) -> KvCacheEvent:
        with self._mu:
            ev, self._cache_event = self._cache_event, KvCacheEvent()
            return ev

    def seed_cache_event(self, ev: KvCacheEvent) -> None:
        with self._mu:
            self._cache_event = ev
            # Snapshot view (reconcile): stored hashes persist until a
            # later event removes them.
            self.cache_hashes |= set(ev.stored_cache)
            self.cache_hashes -= set(ev.removed_cache)

    def cache_snapshot(self):
        """Full committed-block view for POST /reconcile (the real engine
        reads its block manager; tests seed cache_hashes directly)."""
        with self._mu:
            return sorted(self.cache_hashes)

    def cache_snapshot_event(self) -> KvCacheEvent:
        """Heartbeat cache-resync payload (post-ejection index rebuild);
        the fake has one tier, so the snapshot is all stored."""
        with self._mu:
            return KvCacheEvent(stored_cache=set(self.cache_hashes))

    def profiling_data(self) -> Tuple[List, List]:
        ttft = [(n, self.ttft_ms + 0.01 * n) for n in (64, 256, 1024, 4096)]
        tpot = [
            (b, t, self.token_delay_s * 1000 + 0.1 * b)
            for b in (1, 8, 32)
            for t in (256, 4096)
        ]
        return ttft, tpot

    # -- generation ------------------------------------------------------ #
    def _run(self, req, skip_first: bool = False) -> None:
        from xllm_service_tpu.common import faults

        try:
            resume_from = int(getattr(req, "resume_from", 0) or 0)
            prompt = list(req.prompt_token_ids)
            if resume_from:
                # Token-replay resume: the replayed suffix is generation
                # output, not prompt — the echo script derives from the
                # ORIGINAL prompt so the continuation is byte-identical to
                # the unfaulted stream, and the replayed tokens are
                # skipped instead of re-emitted.
                prompt = prompt[:-resume_from]
            full = (
                list(self.script)
                if self.script is not None
                else list(reversed(prompt))
            ) or [0]
            # The serving layer already shrank max_new_tokens by the
            # replayed count; the total budget fences the ORIGINAL script.
            n = min(len(full), resume_from + req.sampling.max_new_tokens)
            tokens = full[:max(n, 1)]
            gen_offset = 0
            if skip_first:
                tokens = tokens[1:] or [0]
                gen_offset = 1
            if resume_from:
                gen_offset = resume_from
                tokens = tokens[resume_from:]
                if not tokens:
                    # Everything was already delivered before the kill —
                    # close the stream cleanly with no fresh tokens.
                    req.callback(
                        RequestOutput(
                            request_id=req.request_id,
                            outputs=[SequenceOutput(
                                index=0, token_ids=[],
                                finish_reason=FinishReason.STOP,
                            )],
                            usage=Usage(len(req.prompt_token_ids), 0),
                            finished=True,
                        )
                    )
                    return
            time.sleep(self.ttft_ms / 1000.0)
            for i, tok in enumerate(tokens):
                try:
                    # Chaos hook: "drop" goes silent mid-stream (a hung or
                    # dying engine), "error" surfaces an engine failure,
                    # "delay" stretches the token gap.
                    faults.point(
                        "fake_engine.step",
                        instance=getattr(self, "instance_name", ""),
                        request_id=req.request_id,
                        step=gen_offset + i,
                    )
                except faults.FaultInjected as fi:
                    if fi.action == "error":
                        req.callback(
                            RequestOutput(
                                request_id=req.request_id,
                                status=Status(
                                    StatusCode.UNAVAILABLE, str(fi)
                                ),
                                finished=True,
                            )
                        )
                    return
                with self._mu:
                    if self._cancelled.pop(req.request_id, False):
                        req.callback(
                            RequestOutput(
                                request_id=req.request_id,
                                status=Status(StatusCode.CANCELLED, "cancelled"),
                                finished=True,
                                cancelled=True,
                            )
                        )
                        return
                last = i == len(tokens) - 1
                out = RequestOutput(
                    request_id=req.request_id,
                    outputs=[
                        SequenceOutput(
                            index=0,
                            token_ids=[tok],
                            finish_reason=(
                                FinishReason.STOP if last else FinishReason.NONE
                            ),
                        )
                    ],
                    # Resumed requests report FRESH generation only (the
                    # service adds the replayed count back; the prompt it
                    # subtracts) — skip_first (PD import) keeps reporting
                    # the running total including the prefill token.
                    usage=Usage(
                        len(req.prompt_token_ids),
                        (i + 1) if resume_from else (gen_offset + i + 1),
                    ),
                    finished=last,
                )
                keep = req.callback(out)
                if keep is False:
                    return
                if not last:
                    time.sleep(self.token_delay_s)
        finally:
            with self._mu:
                self._active -= 1
