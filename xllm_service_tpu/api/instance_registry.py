"""Process-local instance registry.

Colocated PD peers hand KV off through direct calls; the KV payload stays
a DEVICE array end-to-end on this path (engine._handoff exports to a
device buffer; the peer's import pads and scatters device-side) — the
single-host analog of the ICI device_put path. Only the HTTP/DCN route
copies to host, at serialization time. Lives in its own module so
api/instance.py and the KV-handoff mixin share it without a cycle.
"""

from __future__ import annotations

import threading
from typing import Dict

_LOCAL_INSTANCES: Dict[str, "object"] = {}
_LOCAL_MU = threading.Lock()
