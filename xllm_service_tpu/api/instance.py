"""Engine instance server: the TPU engine behind the cluster protocol.

The reference's engine tier is the absent xLLM submodule; this is its
TPU-native replacement's front door (SURVEY.md §2.3 lists the service-side
touchpoints that constrain it): per-instance OpenAI HTTP endpoints (the
service forwards raw JSON to `instance/v1/...`, service.cpp:163-190),
registration + heartbeats with load/latency/cache events, and the
decode->service `Generations` push. Detokenization happens here — the
engine speaks token ids only.

Serves two modes on the same endpoints:
  * forwarded service traffic (body carries service_request_id+token_ids):
    ack immediately, stream tokens back via /rpc/generations;
  * direct client traffic: run locally, return/stream OpenAI JSON itself.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time

from typing import Any, Callable, Dict, List, Optional

from xllm_service_tpu.api.client import HeartbeatLoop, MasterClient
from xllm_service_tpu.api.http_utils import HttpJsonApi, make_http_server
from xllm_service_tpu.api.protocol import sampling_from_body  # noqa: F401 — re-export
from xllm_service_tpu.common import faults
from xllm_service_tpu.common.config import EngineConfig
from xllm_service_tpu.common.types import (
    InstanceMetaInfo,
    InstanceType,
    RequestOutput,
)
from xllm_service_tpu.obs import (
    FlightRecorder,
    MetricsRegistry,
    SpanRing,
    absorb_exposition,
    render_families,
)
from xllm_service_tpu.service.response_handler import ResponseHandler
from xllm_service_tpu.tokenizer import ChatTemplate, create_tokenizer
from xllm_service_tpu.tokenizer.tokenizer import IncrementalDetokenizer

logger = logging.getLogger(__name__)


# Process-local instance registry (api/instance_registry.py): colocated PD
# peers hand KV off through direct calls; re-exported here for tests.
from xllm_service_tpu.api.instance_registry import (  # noqa: E402
    _LOCAL_INSTANCES,
    _LOCAL_MU,
)
from xllm_service_tpu.api.instance_fabric import FabricMixin  # noqa: E402
from xllm_service_tpu.api.instance_kv import KVHandoffMixin  # noqa: E402
from xllm_service_tpu.api.instance_mm import MultimodalMixin  # noqa: E402
from xllm_service_tpu.api.instance_serving import ServingMixin  # noqa: E402


class InstanceServer(
    KVHandoffMixin, FabricMixin, MultimodalMixin, ServingMixin
):
    def __init__(
        self,
        engine_cfg: EngineConfig,
        master_rpc_addr: str = "",
        host: str = "127.0.0.1",
        port: int = 0,
        tokenizer_path: str = "",
        heartbeat_interval_s: float = 3.0,
        engine=None,
        lora_adapters=None,  # {name: peft-dir path OR adapter dict}
    ):
        # Deferred imports keep jax out of service-only processes.
        if engine is None:
            if engine_cfg.instance_type == "ENCODE":
                # EPD stage E: this instance hosts the vision encoder
                # instead of an LM engine (engine_cfg.model names a
                # VisionConfig, e.g. vit-tiny).
                from xllm_service_tpu.runtime.vision_executor import (
                    EncoderEngine,
                )

                engine = EncoderEngine(
                    model=engine_cfg.model,
                    checkpoint_path=engine_cfg.checkpoint_path,
                    dtype=engine_cfg.dtype,
                    cfg=engine_cfg,
                )
            else:
                from xllm_service_tpu.runtime.engine import InferenceEngine
                from xllm_service_tpu.runtime.executor import ModelExecutor

                engine = InferenceEngine(
                    engine_cfg, executor=ModelExecutor(engine_cfg)
                )
        self.engine = engine
        self.cfg = engine_cfg
        # Multi-LoRA registry: adapter name -> row in the executor's
        # stacks; OpenAI `model` fields naming an adapter route to it.
        self.lora_names: Dict[str, int] = {}
        if lora_adapters:
            if not hasattr(engine, "set_lora_adapters"):
                raise ValueError(
                    "lora_adapters requires a real inference engine"
                )
            loaded = {}
            for name, spec in lora_adapters.items():
                if isinstance(spec, str):
                    from xllm_service_tpu.runtime.weights import (
                        load_lora_checkpoint,
                    )

                    spec = load_lora_checkpoint(
                        spec, self.engine.executor.cfg
                    )
                loaded[name] = spec
            self.lora_names = self.engine.set_lora_adapters(loaded)
        self.tokenizer = create_tokenizer(tokenizer_path)
        self.chat_template = ChatTemplate(self.tokenizer)
        self._responses = ResponseHandler()

        # Front door on the configured backend (EngineConfig.http_backend;
        # "threaded" default — see the config comment there).
        self.http = make_http_server(
            getattr(engine_cfg, "http_backend", "threaded"), host, port,
            do_get=self.handle_get, do_post=self.handle_post,
            name=f"inst-{engine_cfg.instance_name or port}",
        )
        self.name = engine_cfg.instance_name or f"{host}:{self.http.port}"
        # Tag the engine so its fault-injection points (FakeEngine's step
        # loop) can be matched per instance in a chaos spec.
        setattr(self.engine, "instance_name", self.name)
        self.meta = InstanceMetaInfo(
            name=self.name,
            rpc_address=f"{host}:{self.http.port}",
            http_address=f"{host}:{self.http.port}",
            model_name=engine_cfg.model,
            type=InstanceType.parse(engine_cfg.instance_type),
            dp_size=engine_cfg.dp_size,
            tp_size=engine_cfg.tp_size,
            lora_adapters=sorted(self.lora_names),
        )
        # Fixed-role instances SERVE their declared role from beat one —
        # the field otherwise defaults to PREFILL and an ENCODE instance
        # would heartbeat a role mismatch the master can never reconcile
        # (/flip only swaps PREFILL<->DECODE), looping flip notifications
        # forever. MIX keeps the default: the master assigns its first
        # serving role and the reconciliation beat self-heals.
        if self.meta.type in (
            InstanceType.PREFILL, InstanceType.DECODE, InstanceType.ENCODE
        ):
            self.meta.current_type = self.meta.type
        if self.meta.type == InstanceType.ENCODE:
            # Advertise the hosted modality: encoders serve ONE tower
            # (vision_executor.EncoderEngine), and the scheduler must
            # route each media request to an encoder covering every
            # requested modality (review finding, r5).
            mods = []
            vis = getattr(self.engine, "executor", None)
            if vis is not None:
                mods.append("image")
                if getattr(getattr(vis, "cfg", None), "arch", "") in (
                    "qwen2vl", "qwen25vl"
                ):
                    mods.append("video")
            if getattr(self.engine, "audio_executor", None) is not None:
                mods.append("audio")
            self.meta.modalities = mods
        ttft, tpot = self.engine.profiling_data()
        self.meta.ttft_profiling_data = ttft
        self.meta.tpot_profiling_data = tpot

        # Instance-front-door registry: heartbeat-visible load/latency as
        # pull gauges (any engine, FakeEngine included) plus the
        # speculative-decoding counters when the engine runs a verifier.
        # /metrics renders this merged with the engine's OWN registry
        # (runtime/engine.py step/preemption/prefix-cache series).
        self.metrics = MetricsRegistry()
        self.metrics.gauge(
            "xllm_engine_waiting_requests", "Engine admission queue depth",
        ).set_function(
            lambda: self.engine.get_load_metrics().waiting_requests_num
        )
        self.metrics.gauge(
            "xllm_engine_kv_cache_usage", "Fraction of KV blocks in use",
        ).set_function(
            lambda: self.engine.get_load_metrics().gpu_cache_usage_perc
        )
        self.metrics.gauge(
            "xllm_engine_recent_max_ttft_ms",
            "Max TTFT over the engine's recent window",
        ).set_function(
            lambda: self.engine.get_latency_metrics().recent_max_ttft
        )
        self.metrics.gauge(
            "xllm_engine_recent_max_tbt_ms",
            "Max time-between-tokens over the engine's recent window",
        ).set_function(
            lambda: self.engine.get_latency_metrics().recent_max_tbt
        )
        # Spec series only when this instance actually runs a verifier —
        # a spec-off engine exporting a 0x "realized speedup" gauge would
        # skew fleet dashboards (and FakeEngine has no spec at all).
        if getattr(
            getattr(self.engine, "cfg", None), "speculative_tokens", 0
        ) > 0:
            self.metrics.counter(
                "xllm_engine_spec_verify_steps_total",
                "Speculative verify steps run",
            ).set_function(lambda: self.engine.spec_steps)
            self.metrics.counter(
                "xllm_engine_spec_tokens_emitted_total",
                "Tokens emitted by speculative verify steps",
            ).set_function(lambda: self.engine.spec_tokens_emitted)
            self.metrics.gauge(
                "xllm_engine_spec_tokens_per_slot_step",
                "Realized speculative speedup over plain decode",
            ).set_function(
                lambda: self.engine.spec_tokens_emitted
                / max(self.engine.spec_slot_steps, 1)
            )

        # Distributed tracing + anomaly flight recorder (obs/flight.py,
        # docs/OBSERVABILITY.md). The ring is always-on (the recorder
        # dumps it on fenced RPCs / KV stalls); span EMISSION is gated by
        # the XLLM_TRACE hatch — with it off the engine's span hook stays
        # None and the token path does no per-step tracing work at all.
        self.trace_enabled = os.environ.get(
            "XLLM_TRACE", "1"
        ).lower() not in ("0", "false", "off")
        self.span_ring = SpanRing(
            self.name,
            int(os.environ.get("XLLM_TRACE_RING", "") or 2048),
        )
        self.flight = FlightRecorder(
            self.span_ring,
            os.path.join(
                os.environ.get("XLLM_TRACE_DIR", "trace"),
                f"flight-{self.name}",
            ),
            registry=self.metrics,
        )
        if self.trace_enabled:
            # Engine-side emission (prefill chunks, step batches): the
            # engine loop calls hook(srid, stage, **fields) per step /
            # chunk — never per token — only while a hook is installed.
            setattr(self.engine, "span_hook", self.span_ring.emit)

        # Pipelined PD handoff state + metrics (instance_kv mixin):
        # streaming-session tables and the handoff stall/overlap series.
        self._init_kv_handoff()

        self._master: Optional[MasterClient] = (
            MasterClient(master_rpc_addr) if master_rpc_addr else None
        )
        # Prefix-fabric state + metrics (instance_fabric mixin): peer
        # fetch dedup tables, the evict-offer worker, and the
        # xllm_fabric_* series. After self._master — the evictor side
        # needs it to ask /rpc/fabric/evict_offer.
        self._init_fabric()
        self._heartbeat: Optional[HeartbeatLoop] = (
            HeartbeatLoop(
                self._master,
                self.meta,
                interval_s=heartbeat_interval_s,
                collect_load=self._collect_load,
                collect_latency=self.engine.get_latency_metrics,
                collect_cache_event=self.engine.take_cache_event,
                collect_cache_snapshot=getattr(
                    self.engine, "cache_snapshot_event", None
                ),
            )
            if self._master
            else None
        )
        # decode->service push pipeline
        self._push_q: "queue.Queue[Optional[RequestOutput]]" = queue.Queue()
        self._push_thread = threading.Thread(
            target=self._push_loop, name=f"gen-push-{self.name}", daemon=True
        )
        # service_request_id -> engine request_ids (n>1 fans out to one
        # engine request per sequence; /cancel and dropped-stream feedback
        # cancel them all)
        self._srid_map: Dict[str, List[str]] = {}
        self._srid_mu = threading.Lock()
        # Per-srid reconcile manifest state (same lock): owning master
        # epoch, prompt-token count, and delivered-token count — what a
        # freshly elected master needs to rebuild its load charges from
        # POST /reconcile (docs/FAULT_TOLERANCE.md, control plane).
        self._srid_info: Dict[str, Dict[str, int]] = {}
        # Epoch fence: highest master epoch this instance has seen on any
        # control RPC. RPCs stamped with a LOWER epoch come from a
        # deposed master and are rejected with 412 — split-brain dispatch
        # is structurally impossible, not just unlikely.
        self._fence_mu = threading.Lock()
        self._fence_epoch = 0
        self._m_fenced = self.metrics.counter(
            "xllm_instance_fenced_rpcs_total",
            "Master RPCs rejected for carrying a stale fencing epoch "
            "(split-brain dispatch attempts)",
        )
        self._m_orphans = self.metrics.counter(
            "xllm_service_orphan_reaped_total",
            "In-flight requests reaped after a master takeover did not "
            "reclaim them within the orphan TTL (engine work cancelled, "
            "KV blocks freed)",
        )
        # decode-peer address cache (PD disagg handoff target)
        self._peer_addrs: Dict[str, str] = {}
        # Alternate PD response topology (service.h:61-71 analog): srid ->
        # prefill-instance address to relay generations through instead of
        # pushing to the master directly.
        self._relay_addrs: Dict[str, str] = {}
        # EPD multimodal state + instruments (instance_mm mixin): the
        # monolithic /mm/import landing table, the streamed-handoff
        # session handles, and the reap/wait/overlap series.
        self._init_mm()
        # srid -> set once a generations push carrying it was acked by the
        # master; the handoff sender waits on this so the decode peer's
        # tokens can never reach the master before the first token
        self._push_acked: Dict[str, threading.Event] = {}
        self._push_acked_mu = threading.Lock()
        # PD handoff transfer pipeline: the engine thread only enqueues
        # (the KV payload is already a host copy and the slot/blocks are
        # released before send); the master-ack wait + KV POST run here so
        # a slow master or decode peer never stalls admission/decode. A
        # small worker POOL bounds head-of-line blocking: one stuck peer
        # (60s ack wait + HTTP timeout) delays only its own lane. The queue
        # is BOUNDED so a stuck master/peer backpressures the engine thread
        # (blocking put) instead of accumulating unbounded host KV copies.
        self._transfer_q: "queue.Queue[Optional[Callable[[], None]]]" = (
            queue.Queue(maxsize=8)
        )
        self._transfer_threads = [
            threading.Thread(
                target=self._transfer_loop,
                name=f"kv-xfer-{self.name}-{i}",
                daemon=True,
            )
            for i in range(4)
        ]
        # Pipelined-handoff chunk lane (docs/PD_DISAGGREGATION.md): chunk
        # jobs get their OWN bounded queue + workers so one streaming
        # session to a stuck decode peer can only saturate this lane —
        # chunk sends then fail fast (put_nowait -> session abort ->
        # monolithic fallback) and the monolithic plane's engine-thread
        # backpressure never engages on a chunk's behalf.
        self._stream_q: "queue.Queue[Optional[Callable[[], None]]]" = (
            queue.Queue(maxsize=8)
        )
        self._stream_threads = [
            threading.Thread(
                target=self._transfer_loop,
                args=(self._stream_q,),
                name=f"kv-stream-{self.name}-{i}",
                daemon=True,
            )
            for i in range(2)
        ]
        # Cross-process device-to-device KV plane (runtime/transfer.py):
        # offers ride this process's TransferServer; the /kv/import control
        # message carries only {addr, uuid, shape, dtype} and the decode
        # peer pulls straight into its device memory. ENCODE instances and
        # disabled configs keep the bytes-in-body plane.
        self._kv_transfer = None
        # Peers that rejected a kv_pull header (no transfer server): the
        # bytes plane is used for them without another failing round trip.
        self._peer_no_pull: set = set()
        if engine_cfg.enable_kv_transfer_server and (
            engine_cfg.instance_type != "ENCODE"
        ):
            from xllm_service_tpu.runtime.transfer import get_transfer_server

            self._kv_transfer = get_transfer_server(
                engine_cfg.kv_transfer_listen
            )

    # ------------------------------------------------------------------ #
    def _collect_load(self):
        """Heartbeat load snapshot: the engine's own metrics stamped with
        the KV-handoff stall EWMA folded from _kv_stall_samples — the
        goodput controller's live disaggregation-cost signal (0.0 until
        this instance has completed a handoff)."""
        lm = self.engine.get_load_metrics()
        samples = list(self._kv_stall_samples)
        if samples:
            ewma = samples[0][1]
            for _, stall_ms in samples[1:]:
                ewma += 0.3 * (stall_ms - ewma)
            lm.kv_stall_ms_ewma = ewma
        return lm

    def start(self) -> None:
        with _LOCAL_MU:
            _LOCAL_INSTANCES[self.name] = self
        self.engine.start()
        self.http.start()
        self._push_thread.start()
        for t in self._transfer_threads:
            t.start()
        for t in self._stream_threads:
            t.start()
        if self._heartbeat is not None:
            self._heartbeat.start()
        logger.info("instance %s serving on :%d", self.name, self.http.port)

    def crash(self) -> None:
        """UNGRACEFUL death for fault-injection tests/benches: heartbeats
        stop, the HTTP server drops (in-flight requests included), the
        engine halts, and the generations push channel goes silent — all
        with NO deregistration. The master learns via lease expiry /
        disconnected pruning exactly as for a crashed engine process;
        mid-stream requests die (error-finish after removal) instead of
        quietly completing through a still-alive push loop. A later
        stop() still runs the remaining thread teardown."""
        self._crashed = True  # push loop drops everything from here on
        with _LOCAL_MU:
            if _LOCAL_INSTANCES.get(self.name) is self:
                del _LOCAL_INSTANCES[self.name]
        if self._heartbeat is not None:
            self._heartbeat.stop()
        if not getattr(self, "_http_stopped", False):
            self._http_stopped = True
            self.http.stop()
        self.engine.stop()

    def stop(self) -> None:
        with _LOCAL_MU:
            if _LOCAL_INSTANCES.get(self.name) is self:
                del _LOCAL_INSTANCES[self.name]
        if self._heartbeat is not None:
            self._heartbeat.stop()
        if self._master is not None:
            # Graceful shutdown: leave the registry NOW (best-effort) so
            # the master stops routing here immediately — crash death
            # still falls to lease-TTL expiry.
            try:
                self._master.deregister(self.name)
            except Exception:
                pass
        self._push_q.put(None)
        self._push_thread.join(timeout=5.0)
        if self._fabric_evict_thread is not None:
            try:
                self._fabric_evict_q.put_nowait(None)
            except queue.Full:
                pass  # daemon thread; bounded queue must not block stop
        for _ in self._transfer_threads:
            self._transfer_q.put(None)
        for t in self._transfer_threads:
            t.join(timeout=5.0)
        for _ in self._stream_threads:
            try:
                # The lane is bounded and may be saturated by a stuck peer
                # (the exact scenario it isolates) — never let shutdown
                # block behind it; the workers are daemons and the join
                # below is already time-bounded.
                self._stream_q.put(None, timeout=1.0)
            except queue.Full:
                break
        for t in self._stream_threads:
            t.join(timeout=5.0)
        if not getattr(self, "_http_stopped", False):
            self._http_stopped = True
            self.http.stop()
        self.engine.stop()

    @property
    def address(self) -> str:
        return f"{self.http.host}:{self.http.port}"

    # ------------------------------------------------------------------ #
    # decode -> service push (proto analog: Generations RPC)
    # ------------------------------------------------------------------ #

    def _push_loop(self) -> None:
        while True:
            out = self._push_q.get()
            if out is None:
                return
            if getattr(self, "_crashed", False):
                continue  # crashed instances push nothing (fault injection)
            batch = [out]
            # micro-batch whatever else is queued (DisaggStreamGenerations
            # carries a list for the same reason)
            while True:
                try:
                    nxt = self._push_q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._push_q.put(None)
                    break
                batch.append(nxt)
            # Partition by destination: master push (default topology) vs
            # relay through the request's prefill instance (alternate
            # topology — service.h:61-71). The master group goes FIRST and
            # relay retries are short with a direct-to-master fallback, so
            # a dead relay peer can't head-of-line-block direct streams.
            groups: Dict[str, List[RequestOutput]] = {}
            for out in batch:
                dest = self._relay_addrs.get(out.service_request_id, "")
                groups.setdefault(dest, []).append(out)
            cont: Dict[str, bool] = {}
            for dest in sorted(groups, key=bool):  # "" (master) first
                group = groups[dest]
                got = None
                backoffs = (0.2, 0.5, 1.0) if dest else (
                    0.2, 0.5, 1.0, 2.0, 5.0, 10.0
                )
                for backoff in backoffs:
                    try:
                        if dest:
                            got = self._relay_generations(dest, group)
                        else:
                            # Stamped with the fence high-water: a master
                            # whose term is older 503s instead of judging
                            # (split-brain window), and the retry lands at
                            # the successor once the heartbeat re-points.
                            got = self._master.push_generations(
                                group, epoch=self._fence_epoch
                            )
                        break
                    except Exception:
                        # Destination briefly unreachable: the batch may
                        # hold a request's only finished=True marker —
                        # retry, don't drop (a drop strands the client
                        # until its timeout).
                        time.sleep(backoff)
                if got is None and dest:
                    # Relay peer is gone: downgrade to the direct topology
                    # rather than stranding the client.
                    logger.warning(
                        "relay peer %s unreachable; pushing %d outputs "
                        "directly to master", dest, len(group),
                    )
                    for out in group:
                        self._relay_addrs.pop(out.service_request_id, None)
                    try:
                        got = self._master.push_generations(
                            group, epoch=self._fence_epoch
                        )
                    except Exception:
                        got = None
                if got is None:
                    logger.error(
                        "generations push to %s failed permanently; "
                        "dropping %d outputs", dest or "master", len(group),
                    )
                    for out in group:
                        if out.finished:
                            self._relay_addrs.pop(
                                out.service_request_id, None
                            )
                    continue
                cont.update(got)
                for out in group:
                    if out.finished:
                        self._relay_addrs.pop(out.service_request_id, None)
            for srid, keep in cont.items():
                with self._push_acked_mu:
                    ev = self._push_acked.get(srid)
                if ev is not None:
                    ev.set()
                if not keep:
                    self._relay_addrs.pop(srid, None)
                    with self._srid_mu:
                        rids = self._srid_map.pop(srid, None) or []
                        self._srid_forget_locked(srid)
                    for rid in rids:
                        self.engine.cancel(rid)

    def _relay_generations(
        self, addr: str, outputs: List[RequestOutput]
    ) -> Dict[str, bool]:
        """Decode side of the alternate topology: hand the token batch to
        the prefill instance, which forwards it to the master and returns
        the master's continue map."""
        from xllm_service_tpu.api.http_utils import post_json
        from xllm_service_tpu.api.protocol import output_to_json

        code, resp = post_json(
            addr,
            "/rpc/relay_generations",
            {"gens": [output_to_json(o) for o in outputs]},
            timeout=5.0,
        )
        if code != 200:
            raise RuntimeError(f"relay peer {addr} returned {code}")
        return resp.get("cont", {})

    # ------------------------------------------------------------------ #
    # HTTP surface
    # ------------------------------------------------------------------ #

    def _metrics_body(self) -> str:
        """Instance exposition: the front-door registry merged with the
        engine's own (runtime/engine.py registers its step/preemption/
        prefix-cache/host-tier series there; FakeEngine has none)."""
        from collections import OrderedDict

        fams = OrderedDict()
        absorb_exposition(fams, self.metrics.render())
        engine_reg = getattr(self.engine, "metrics", None)
        if engine_reg is not None and hasattr(engine_reg, "render"):
            absorb_exposition(fams, engine_reg.render())
        return render_families(fams)

    def handle_get(self, h: HttpJsonApi) -> None:
        route = h.route
        if route == "/hello":
            h.send_json({"message": f"hello from instance {self.name}"})
        elif route == "/health":
            # Breaker probe target: answering at all proves the HTTP plane
            # is up; the payload lets the prober cross-check identity (a
            # port reused by a different instance must not heal the old
            # name's breaker).
            h.send_json(
                {
                    "ok": True,
                    "name": self.name,
                    "role": self.meta.current_type.name,
                }
            )
        elif route == "/metrics":
            body = self._metrics_body().encode()
            h.send_response(200)
            h.send_header("Content-Type", "text/plain; version=0.0.4")
            h.send_header("Content-Length", str(len(body)))
            h.end_headers()
            h.wfile.write(body)
        elif route == "/v1/models":
            h.send_json(
                {
                    "object": "list",
                    "data": [{"id": self.cfg.model, "object": "model"}]
                    + [
                        {"id": n, "object": "model",
                         "parent": self.cfg.model}
                        for n in sorted(self.lora_names)
                    ],
                }
            )
        elif route == "/trace":
            # Trace-collector pull (docs/OBSERVABILITY.md): this process's
            # ring spans, filtered to one request when ?srid= is given.
            # Timestamps are THIS process's monotonic clock — the master
            # shifts them with the heartbeat-derived offset.
            srid = h.query().get("srid", "")
            spans = (
                self.span_ring.for_request(srid)
                if srid
                else self.span_ring.snapshot()
            )
            h.send_json(
                {
                    "process": self.name,
                    "spans": spans,
                    "ring": self.span_ring.stats(),
                }
            )
        else:
            h.send_error_json(404, f"no route {route}")

    def _span(self, srid: str, stage: str, **fields: Any) -> None:
        """One instance-side span into the flight ring (no-op with the
        XLLM_TRACE hatch off — the serving paths stay allocation-free)."""
        if self.trace_enabled:
            self.span_ring.emit(srid, stage, **fields)

    # ------------------------------------------------------------------ #
    # epoch fencing + takeover reconciliation (docs/FAULT_TOLERANCE.md)
    # ------------------------------------------------------------------ #

    def _fence_epoch_check(self, epoch) -> int:
        """Raise the high-water fencing epoch; returns 0 when `epoch` is
        acceptable (absent / current / newer) or the current fence value
        the caller is behind. Only stamped RPCs participate — direct
        client traffic carries no epoch and always passes."""
        try:
            e = int(epoch)
        except (TypeError, ValueError):
            return 0
        if e <= 0:
            return 0
        with self._fence_mu:
            if e < self._fence_epoch:
                return self._fence_epoch
            self._fence_epoch = e
        return 0

    def _fence_reject(self, h: HttpJsonApi, body) -> bool:
        """412-reject an RPC stamped with a stale master epoch (counted).
        The DISTINCT status + `fenced` marker lets the deposed master
        tell "you are not the master anymore" apart from a client error —
        it must stop dispatching, not blame the request."""
        stamped = (body or {}).get("master_epoch")
        cur = self._fence_epoch_check(stamped)
        if not cur:
            return False
        self._m_fenced.inc()
        # Anomaly trigger: a fenced RPC means split-brain dispatch was
        # just attempted — capture the surrounding span window.
        self.flight.trigger(
            "fenced_rpc",
            str((body or {}).get("service_request_id") or ""),
            stale_epoch=stamped, fence_epoch=cur,
        )
        logger.warning(
            "instance %s fenced an RPC from a deposed master "
            "(epoch %s < %d)", self.name, stamped, cur,
        )
        h.send_json(
            {
                "error": {
                    "message": (
                        f"stale master epoch {stamped} < {cur}: this "
                        "master was deposed"
                    ),
                    "type": "stale_epoch",
                },
                "fenced": True,
                "epoch": cur,
            },
            status=412,
        )
        return True

    def _srid_track(
        self, srid: str, prompt_tokens: int, epoch, delivered: int = 0
    ) -> None:
        """Register one forwarded request's reconcile-manifest entry
        (caller does NOT hold _srid_mu)."""
        if not srid:
            return
        try:
            e = int(epoch or 0)
        except (TypeError, ValueError):
            e = 0
        with self._srid_mu:
            self._srid_info[srid] = {
                "prompt_tokens": int(prompt_tokens),
                "delivered": int(delivered),
                "epoch": e,
            }

    def _srid_note_delivered(self, srid: str, n: int) -> None:
        if not srid or n <= 0:
            return
        with self._srid_mu:
            info = self._srid_info.get(srid)
            if info is not None:
                info["delivered"] += n

    def _srid_forget_locked(self, srid: str) -> None:
        """Drop the manifest entry; caller holds _srid_mu."""
        self._srid_info.pop(srid, None)

    def _handle_reconcile(self, h: HttpJsonApi, body: Dict[str, Any]) -> None:
        """Takeover reconciliation target (POST /reconcile): return this
        instance's in-flight request manifest, current load, and the
        committed prefix-cache block hashes so a freshly elected master
        rebuilds its cluster view instead of starting amnesiac. In-flight
        srids the new master does not claim (`known`) are ORPHANS: a TTL
        timer reaps them — engine requests cancelled, blocks freed — so
        a dead master's requests never leak KV. The epoch fence already
        ran (handle_post), so a stale master can neither read manifests
        nor steal the heartbeat target."""
        try:
            # Chaos hook: a dropped receive exercises the master's
            # skip-and-continue takeover path.
            faults.point(
                "reconcile.recv",
                instance=self.name, epoch=body.get("master_epoch", 0),
            )
        except faults.FaultInjected as fi:
            h.send_error_json(503, str(fi))
            return
        known = set(body.get("known") or [])
        try:
            ttl = float(body.get("orphan_ttl_s") or 10.0)
        except (TypeError, ValueError):
            ttl = 10.0
        new_rpc = str(body.get("master_rpc") or "")
        if (
            new_rpc
            and self._master is not None
            and self._master._addr != new_rpc
        ):
            # Follow the new master: heartbeats, re-registration, and the
            # generations push all re-point here — the old master's
            # in-process lease table died with it, so the next beat gets
            # `reregister` and a fresh lease from the survivor.
            logger.info(
                "instance %s re-pointing control plane %s -> %s "
                "(master takeover)", self.name, self._master._addr, new_rpc,
            )
            self._master._addr = new_rpc
        with self._srid_mu:
            inflight = list(self._srid_map.keys())
            manifest = []
            for srid in inflight:
                info = self._srid_info.get(srid, {})
                manifest.append({
                    "service_request_id": srid,
                    "request_ids": list(self._srid_map.get(srid) or []),
                    "owning_epoch": int(info.get("epoch", 0)),
                    "delivered_tokens": int(info.get("delivered", 0)),
                    "prompt_tokens": int(info.get("prompt_tokens", 0)),
                })
            # Garbage entries (request finished between pops): drop.
            for srid in list(self._srid_info):
                if srid not in self._srid_map:
                    self._srid_info.pop(srid, None)
        orphans = [s for s in inflight if s not in known]
        if orphans:
            t = threading.Timer(
                ttl, self._reap_orphans, args=(list(orphans),)
            )
            t.daemon = True
            t.start()
        snap = getattr(self.engine, "cache_snapshot", None)
        hashes: List[str] = []
        if callable(snap):
            try:
                hashes = [bytes(x).hex() for x in snap()]
            except Exception:
                hashes = []
        h.send_json({
            "ok": True,
            "name": self.name,
            "epoch": self._fence_epoch,
            "manifest": manifest,
            "orphans": orphans,
            "load_metrics": self.engine.get_load_metrics().to_json(),
            "cache_hashes": hashes,
        })

    def _reap_orphans(self, srids: List[str]) -> None:
        """Orphan-TTL expiry: requests no reconciliation claimed are dead
        weight — cancel their engine work (frees slots + KV blocks) and
        drop every per-srid table entry. Requests that finished or were
        re-claimed (srid gone from the map) are skipped."""
        reaped = 0
        for srid in srids:
            with self._srid_mu:
                rids = self._srid_map.pop(srid, None)
                self._srid_info.pop(srid, None)
            if rids is None:
                continue
            for rid in rids:
                try:
                    self.engine.cancel(rid)
                except Exception:
                    pass
            self._relay_addrs.pop(srid, None)
            with self._push_acked_mu:
                self._push_acked.pop(srid, None)
            reaped += 1
        if reaped:
            self._m_orphans.inc(reaped)
            logger.warning(
                "instance %s reaped %d orphaned request(s) unclaimed by "
                "the takeover reconciliation", self.name, reaped,
            )

    def handle_post(self, h: HttpJsonApi) -> None:
        route = h.route
        if route == "/kv/import":  # binary body, not JSON
            self._handle_kv_import(h)
            return
        if route == "/kv/fetch":  # binary body, not JSON
            self._handle_kv_fetch(h)
            return
        body = h.read_json()
        if body is None:
            h.send_error_json(400, "invalid JSON body")
            return
        # Epoch fence FIRST, on every control RPC: a deposed master's
        # dispatch/cancel/flip/probe/reconcile must fail identically.
        if self._fence_reject(h, body):
            return
        if route == "/reconcile":
            self._handle_reconcile(h, body)
        elif route == "/health":
            # POST twin of the GET probe: the master's breaker probes the
            # dispatch (POST) plane, not just GET reachability.
            h.send_json(
                {
                    "ok": True,
                    "name": self.name,
                    "role": self.meta.current_type.name,
                }
            )
        elif route == "/v1/completions":
            self._serve(h, body, chat=False)
        elif route == "/v1/chat/completions":
            self._serve(h, body, chat=True)
        elif route == "/v1/embeddings":
            self._handle_embeddings(h, body)
        elif route == "/encode":
            self._handle_encode(h, body)
        elif route == "/mm/import":
            self._handle_mm_import(h, body)
        elif route == "/mm/open":
            self._handle_mm_open(h, body)
        elif route == "/mm/chunk":
            self._handle_mm_chunk(h, body)
        elif route == "/mm/commit":
            self._handle_mm_commit(h, body)
        elif route == "/mm/abort":
            self._handle_mm_abort(h, body)
        elif route == "/rpc/relay_generations":
            # Prefill side of the alternate PD response topology: forward
            # the decode peer's token batch to the master synchronously so
            # the continue map (cancellation feedback) flows back through
            # the same exchange.
            from xllm_service_tpu.api.protocol import output_from_json

            if self._master is None:
                h.send_error_json(503, "no master connection to relay to")
                return
            try:
                outs = [output_from_json(j) for j in body.get("gens", [])]
            except Exception as e:
                h.send_error_json(400, f"bad generations payload: {e}")
                return
            try:
                cont = self._master.push_generations(
                    outs, epoch=self._fence_epoch
                )
            except Exception as e:
                h.send_error_json(502, f"master push failed: {e}")
                return
            h.send_json({"ok": True, "cont": cont})
        elif route == "/flip":
            # Dynamic PD-ratio role flip (SURVEY §7 hard part 4): the
            # master's registry changed this instance's serving role; now
            # the ENGINE learns it too (round-1 weak item 8 — reference
            # never notifies, instance_mgr.cpp:759-807). MIX engines serve
            # both roles with identical compiled shapes (bucketed prefill +
            # fixed decode batch + persistent jit cache), so no
            # recompilation is needed — the role re-points heartbeat
            # metadata and is observable on /metrics.
            role = str(body.get("role", ""))
            if role not in ("PREFILL", "DECODE", "MIX"):
                h.send_error_json(400, f"bad role {role!r}")
                return
            # current_type is the SERVING role; meta.type stays the
            # DECLARED type (MIX) — clobbering it would make a lease-blip
            # re-register permanently strip flip eligibility.
            self.meta.current_type = InstanceType.parse(role)
            setattr(self.engine, "serving_role", role)
            logger.info("instance %s now serving role %s", self.name, role)
            h.send_json({"ok": True, "role": role})
        elif route == "/cancel":
            srid = body.get("service_request_id", "")
            with self._srid_mu:
                rids = self._srid_map.pop(srid, None) or []
                self._srid_forget_locked(srid)
            for rid in rids:
                self.engine.cancel(rid)
            h.send_json({"ok": True, "cancelled": bool(rids)})
        else:
            h.send_error_json(404, f"no route {route}")

    def _detokenize(
        self, out: RequestOutput, detoks: Dict[int, IncrementalDetokenizer]
    ) -> None:
        """Per-request incremental detokenization: characters spanning token
        boundaries are held back until complete (detoks carries one state
        per sequence index for the request's lifetime)."""
        for s in out.outputs:
            if s.token_ids and not s.text:
                d = detoks.get(s.index)
                if d is None:
                    d = detoks[s.index] = IncrementalDetokenizer(self.tokenizer)
                s.text = d.push(s.token_ids)
                if out.finished:
                    s.text += d.flush()
            for lp in s.logprobs:
                if not lp.data.token:
                    lp.data.token = self.tokenizer.id_to_token(lp.data.token_id)


def main(argv=None) -> None:
    import argparse

    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser("xllm-service-tpu instance")
    parser.add_argument("--model", default="llama3-tiny")
    parser.add_argument("--master-rpc-addr", default="")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--tokenizer-path", default="")
    parser.add_argument("--instance-type", default="MIX")
    parser.add_argument("--checkpoint-path", default="")
    parser.add_argument("--dtype", default="bfloat16")
    parser.add_argument("--block-size", type=int, default=128)
    parser.add_argument("--num-blocks", type=int, default=0)
    parser.add_argument("--max-running-requests", type=int, default=16)
    parser.add_argument("--max-seq-len", type=int, default=2048)
    parser.add_argument(
        "--prefill-buckets", default="128,256,512,1024,2048",
        help="comma-separated prefill padding buckets",
    )
    parser.add_argument(
        "--kv-cache-dtype", default="auto", choices=["auto", "int8"],
        help="int8 halves decode HBM traffic and doubles pool capacity",
    )
    parser.add_argument(
        "--weight-dtype", default="auto", choices=["auto", "int8", "int4"],
        help="int8: per-out-channel W8 halves weight HBM traffic and "
        "per-device param residency; int4: group-wise W4 quarters them",
    )
    parser.add_argument("--dp-size", type=int, default=1)
    parser.add_argument("--tp-size", type=int, default=1)
    parser.add_argument("--ep-size", type=int, default=1)
    parser.add_argument("--sp-size", type=int, default=1)
    parser.add_argument(
        "--sp-prefill-threshold", type=int, default=0,
        help="uncached-suffix length that routes prefill to the sp ring",
    )
    parser.add_argument(
        "--max-prefill-tokens", type=int, default=8192,
        help="strict per-step prefill budget (long prompts chunk across "
        "steps with decode interleaved)",
    )
    parser.add_argument(
        "--compilation-cache-dir", default="",
        help="persistent XLA jit cache (restarts skip the per-shape compiles)",
    )
    parser.add_argument(
        "--speculative-tokens", type=int, default=0,
        help="prompt-lookup speculative decoding: draft k tokens/step and "
        "verify in one pass (exact; 0 disables)",
    )
    parser.add_argument(
        "--speculative-ngram-max", type=int, default=3,
        help="longest suffix n-gram the drafter matches",
    )
    parser.add_argument(
        "--sync-engine", action="store_true",
        help="disable the overlapped decode pipeline (fully synchronous "
        "stepping; XLLM_SYNC_ENGINE=1|0 overrides either way)",
    )
    parser.add_argument(
        "--lora", action="append", default=[], metavar="NAME=PATH",
        help="register a peft-layout LoRA adapter served under model "
        "NAME (repeatable)",
    )
    args = parser.parse_args(argv)
    # Restore standard JAX env semantics: some environments force a
    # platform at interpreter start (sitecustomize), overriding
    # JAX_PLATFORMS; an explicit env var wins here.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    cfg = EngineConfig(
        model=args.model,
        checkpoint_path=args.checkpoint_path,
        instance_type=args.instance_type,
        dtype=args.dtype,
        block_size=args.block_size,
        num_blocks=args.num_blocks,
        max_running_requests=args.max_running_requests,
        max_seq_len=args.max_seq_len,
        prefill_buckets=[int(b) for b in args.prefill_buckets.split(",")],
        kv_cache_dtype=args.kv_cache_dtype,
        weight_dtype=args.weight_dtype,
        dp_size=args.dp_size,
        tp_size=args.tp_size,
        ep_size=args.ep_size,
        sp_size=args.sp_size,
        sp_prefill_threshold=args.sp_prefill_threshold,
        max_prefill_tokens=args.max_prefill_tokens,
        compilation_cache_dir=args.compilation_cache_dir,
        speculative_tokens=args.speculative_tokens,
        speculative_ngram_max=args.speculative_ngram_max,
        sync_engine=args.sync_engine,
    )
    lora = {}
    for spec in args.lora:
        name, _, path = spec.partition("=")
        if not name or not path:
            parser.error(f"--lora expects NAME=PATH, got {spec!r}")
        lora[name] = path
    srv = InstanceServer(
        cfg,
        master_rpc_addr=args.master_rpc_addr,
        host=args.host,
        port=args.port,
        tokenizer_path=args.tokenizer_path,
        lora_adapters=lora or None,
    )
    srv.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
