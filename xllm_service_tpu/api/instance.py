"""Engine instance server: the TPU engine behind the cluster protocol.

The reference's engine tier is the absent xLLM submodule; this is its
TPU-native replacement's front door (SURVEY.md §2.3 lists the service-side
touchpoints that constrain it): per-instance OpenAI HTTP endpoints (the
service forwards raw JSON to `instance/v1/...`, service.cpp:163-190),
registration + heartbeats with load/latency/cache events, and the
decode->service `Generations` push. Detokenization happens here — the
engine speaks token ids only.

Serves two modes on the same endpoints:
  * forwarded service traffic (body carries service_request_id+token_ids):
    ack immediately, stream tokens back via /rpc/generations;
  * direct client traffic: run locally, return/stream OpenAI JSON itself.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import queue
import threading
import time

import numpy as np
from typing import Any, Callable, Dict, List, Optional, Tuple

from xllm_service_tpu.api.client import HeartbeatLoop, MasterClient
from xllm_service_tpu.api.http_utils import (
    HttpServerThread,
    QuietHandler,
    SseWriter,
    post_bytes,
    post_json,
)
from xllm_service_tpu.api.protocol import handoff_from_bytes, handoff_to_bytes
from xllm_service_tpu.common.config import EngineConfig
from xllm_service_tpu.common.shortuuid import generate_uuid
from xllm_service_tpu.common.types import (
    InstanceMetaInfo,
    InstanceType,
    RequestOutput,
    StatusCode,
)
from xllm_service_tpu.api.protocol import parse_prompt_field
from xllm_service_tpu.ops.sampling import SamplingParams
from xllm_service_tpu.service.response_handler import (
    ResponseHandler,
    accumulate_sequences,
)
from xllm_service_tpu.service.request import ServiceRequest
from xllm_service_tpu.tokenizer import ChatTemplate, create_tokenizer, parse_messages
from xllm_service_tpu.tokenizer.tokenizer import IncrementalDetokenizer

logger = logging.getLogger(__name__)


def sampling_from_body(body: Dict[str, Any], cfg: EngineConfig) -> SamplingParams:
    max_tokens = int(
        body.get("max_tokens") or body.get("max_completion_tokens") or 0
    )
    lp = body.get("logprobs")
    top_lp = int(body.get("top_logprobs", 0) or 0)
    raw_seed = body.get("seed")
    # OpenAI semantics: unseeded sampling varies per call. Only an explicit
    # client seed (any value, 0 included) gives the deterministic stream;
    # otherwise draw a fresh per-request seed.
    seed = (
        int(raw_seed)
        if raw_seed is not None
        else int.from_bytes(os.urandom(4), "little")
    )
    return SamplingParams(
        temperature=float(body.get("temperature", 1.0)),
        top_p=float(body.get("top_p", 1.0)),
        top_k=int(body.get("top_k", 0) or 0),
        seed=seed,
        logprobs=bool(lp),
        top_logprobs=top_lp if top_lp else (int(lp) if isinstance(lp, int) else 0),
        max_new_tokens=max_tokens or cfg.max_new_tokens_default,
        ignore_eos=bool(body.get("ignore_eos", False)),
        presence_penalty=float(body.get("presence_penalty", 0.0) or 0.0),
        frequency_penalty=float(body.get("frequency_penalty", 0.0) or 0.0),
    )


# Process-local instance registry: colocated PD peers hand KV off through
# direct calls. The KV payload stays a DEVICE array end-to-end on this path
# (engine._handoff exports to a device buffer; the peer's import pads and
# scatters device-side) — the single-host analog of the ICI device_put
# path. Only the HTTP/DCN route copies to host, at serialization time.
_LOCAL_INSTANCES: Dict[str, "InstanceServer"] = {}
_LOCAL_MU = threading.Lock()


class InstanceServer:
    def __init__(
        self,
        engine_cfg: EngineConfig,
        master_rpc_addr: str = "",
        host: str = "127.0.0.1",
        port: int = 0,
        tokenizer_path: str = "",
        heartbeat_interval_s: float = 3.0,
        engine=None,
    ):
        # Deferred imports keep jax out of service-only processes.
        if engine is None:
            if engine_cfg.instance_type == "ENCODE":
                # EPD stage E: this instance hosts the vision encoder
                # instead of an LM engine (engine_cfg.model names a
                # VisionConfig, e.g. vit-tiny).
                from xllm_service_tpu.runtime.vision_executor import (
                    EncoderEngine,
                )

                engine = EncoderEngine(
                    model=engine_cfg.model,
                    checkpoint_path=engine_cfg.checkpoint_path,
                    dtype=engine_cfg.dtype,
                )
            else:
                from xllm_service_tpu.runtime.engine import InferenceEngine
                from xllm_service_tpu.runtime.executor import ModelExecutor

                engine = InferenceEngine(
                    engine_cfg, executor=ModelExecutor(engine_cfg)
                )
        self.engine = engine
        self.cfg = engine_cfg
        self.tokenizer = create_tokenizer(tokenizer_path)
        self.chat_template = ChatTemplate(self.tokenizer)
        self._responses = ResponseHandler()

        instance_self = self

        class Handler(QuietHandler):
            def do_GET(self):
                instance_self.handle_get(self)

            def do_POST(self):
                instance_self.handle_post(self)

        self.http = HttpServerThread(host, port, Handler)
        self.name = engine_cfg.instance_name or f"{host}:{self.http.port}"
        self.meta = InstanceMetaInfo(
            name=self.name,
            rpc_address=f"{host}:{self.http.port}",
            http_address=f"{host}:{self.http.port}",
            model_name=engine_cfg.model,
            type=InstanceType.parse(engine_cfg.instance_type),
            dp_size=engine_cfg.dp_size,
            tp_size=engine_cfg.tp_size,
        )
        ttft, tpot = self.engine.profiling_data()
        self.meta.ttft_profiling_data = ttft
        self.meta.tpot_profiling_data = tpot

        self._master: Optional[MasterClient] = (
            MasterClient(master_rpc_addr) if master_rpc_addr else None
        )
        self._heartbeat: Optional[HeartbeatLoop] = (
            HeartbeatLoop(
                self._master,
                self.meta,
                interval_s=heartbeat_interval_s,
                collect_load=self.engine.get_load_metrics,
                collect_latency=self.engine.get_latency_metrics,
                collect_cache_event=self.engine.take_cache_event,
            )
            if self._master
            else None
        )
        # decode->service push pipeline
        self._push_q: "queue.Queue[Optional[RequestOutput]]" = queue.Queue()
        self._push_thread = threading.Thread(
            target=self._push_loop, name=f"gen-push-{self.name}", daemon=True
        )
        # service_request_id -> engine request_ids (n>1 fans out to one
        # engine request per sequence; /cancel and dropped-stream feedback
        # cancel them all)
        self._srid_map: Dict[str, List[str]] = {}
        self._srid_mu = threading.Lock()
        # decode-peer address cache (PD disagg handoff target)
        self._peer_addrs: Dict[str, str] = {}
        # Alternate PD response topology (service.h:61-71 analog): srid ->
        # prefill-instance address to relay generations through instead of
        # pushing to the master directly.
        self._relay_addrs: Dict[str, str] = {}
        # EPD: media embeddings landed by the encoder stage, keyed by srid;
        # the forwarded request waits on its event before admission.
        # Values: (embeds, positions, arrival_ts) — TTL-reaped.
        self._mm_imports: Dict[str, Tuple[Any, List[int], float]] = {}
        self._mm_events: Dict[str, threading.Event] = {}
        self._mm_mu = threading.Lock()
        # srid -> set once a generations push carrying it was acked by the
        # master; the handoff sender waits on this so the decode peer's
        # tokens can never reach the master before the first token
        self._push_acked: Dict[str, threading.Event] = {}
        self._push_acked_mu = threading.Lock()
        # PD handoff transfer pipeline: the engine thread only enqueues
        # (the KV payload is already a host copy and the slot/blocks are
        # released before send); the master-ack wait + KV POST run here so
        # a slow master or decode peer never stalls admission/decode. A
        # small worker POOL bounds head-of-line blocking: one stuck peer
        # (60s ack wait + HTTP timeout) delays only its own lane. The queue
        # is BOUNDED so a stuck master/peer backpressures the engine thread
        # (blocking put) instead of accumulating unbounded host KV copies.
        self._transfer_q: "queue.Queue[Optional[Callable[[], None]]]" = (
            queue.Queue(maxsize=8)
        )
        self._transfer_threads = [
            threading.Thread(
                target=self._transfer_loop,
                name=f"kv-xfer-{self.name}-{i}",
                daemon=True,
            )
            for i in range(4)
        ]
        # Cross-process device-to-device KV plane (runtime/transfer.py):
        # offers ride this process's TransferServer; the /kv/import control
        # message carries only {addr, uuid, shape, dtype} and the decode
        # peer pulls straight into its device memory. ENCODE instances and
        # disabled configs keep the bytes-in-body plane.
        self._kv_transfer = None
        # Peers that rejected a kv_pull header (no transfer server): the
        # bytes plane is used for them without another failing round trip.
        self._peer_no_pull: set = set()
        if engine_cfg.enable_kv_transfer_server and (
            engine_cfg.instance_type != "ENCODE"
        ):
            from xllm_service_tpu.runtime.transfer import get_transfer_server

            self._kv_transfer = get_transfer_server(
                engine_cfg.kv_transfer_listen
            )

    # ------------------------------------------------------------------ #
    def start(self) -> None:
        with _LOCAL_MU:
            _LOCAL_INSTANCES[self.name] = self
        self.engine.start()
        self.http.start()
        self._push_thread.start()
        for t in self._transfer_threads:
            t.start()
        if self._heartbeat is not None:
            self._heartbeat.start()
        logger.info("instance %s serving on :%d", self.name, self.http.port)

    def stop(self) -> None:
        with _LOCAL_MU:
            if _LOCAL_INSTANCES.get(self.name) is self:
                del _LOCAL_INSTANCES[self.name]
        if self._heartbeat is not None:
            self._heartbeat.stop()
        if self._master is not None:
            # Graceful shutdown: leave the registry NOW (best-effort) so
            # the master stops routing here immediately — crash death
            # still falls to lease-TTL expiry.
            try:
                self._master.deregister(self.name)
            except Exception:
                pass
        self._push_q.put(None)
        self._push_thread.join(timeout=5.0)
        for _ in self._transfer_threads:
            self._transfer_q.put(None)
        for t in self._transfer_threads:
            t.join(timeout=5.0)
        self.http.stop()
        self.engine.stop()

    def _transfer_loop(self) -> None:
        while True:
            job = self._transfer_q.get()
            if job is None:
                return
            try:
                job()
            except Exception:
                logger.exception("KV transfer job failed")

    @property
    def address(self) -> str:
        return f"{self.http.host}:{self.http.port}"

    # ------------------------------------------------------------------ #
    # decode -> service push (proto analog: Generations RPC)
    # ------------------------------------------------------------------ #

    def _push_loop(self) -> None:
        while True:
            out = self._push_q.get()
            if out is None:
                return
            batch = [out]
            # micro-batch whatever else is queued (DisaggStreamGenerations
            # carries a list for the same reason)
            while True:
                try:
                    nxt = self._push_q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._push_q.put(None)
                    break
                batch.append(nxt)
            # Partition by destination: master push (default topology) vs
            # relay through the request's prefill instance (alternate
            # topology — service.h:61-71). The master group goes FIRST and
            # relay retries are short with a direct-to-master fallback, so
            # a dead relay peer can't head-of-line-block direct streams.
            groups: Dict[str, List[RequestOutput]] = {}
            for out in batch:
                dest = self._relay_addrs.get(out.service_request_id, "")
                groups.setdefault(dest, []).append(out)
            cont: Dict[str, bool] = {}
            for dest in sorted(groups, key=bool):  # "" (master) first
                group = groups[dest]
                got = None
                backoffs = (0.2, 0.5, 1.0) if dest else (
                    0.2, 0.5, 1.0, 2.0, 5.0, 10.0
                )
                for backoff in backoffs:
                    try:
                        if dest:
                            got = self._relay_generations(dest, group)
                        else:
                            got = self._master.push_generations(group)
                        break
                    except Exception:
                        # Destination briefly unreachable: the batch may
                        # hold a request's only finished=True marker —
                        # retry, don't drop (a drop strands the client
                        # until its timeout).
                        time.sleep(backoff)
                if got is None and dest:
                    # Relay peer is gone: downgrade to the direct topology
                    # rather than stranding the client.
                    logger.warning(
                        "relay peer %s unreachable; pushing %d outputs "
                        "directly to master", dest, len(group),
                    )
                    for out in group:
                        self._relay_addrs.pop(out.service_request_id, None)
                    try:
                        got = self._master.push_generations(group)
                    except Exception:
                        got = None
                if got is None:
                    logger.error(
                        "generations push to %s failed permanently; "
                        "dropping %d outputs", dest or "master", len(group),
                    )
                    for out in group:
                        if out.finished:
                            self._relay_addrs.pop(
                                out.service_request_id, None
                            )
                    continue
                cont.update(got)
                for out in group:
                    if out.finished:
                        self._relay_addrs.pop(out.service_request_id, None)
            for srid, keep in cont.items():
                with self._push_acked_mu:
                    ev = self._push_acked.get(srid)
                if ev is not None:
                    ev.set()
                if not keep:
                    self._relay_addrs.pop(srid, None)
                    with self._srid_mu:
                        rids = self._srid_map.pop(srid, None) or []
                    for rid in rids:
                        self.engine.cancel(rid)

    def _relay_generations(
        self, addr: str, outputs: List[RequestOutput]
    ) -> Dict[str, bool]:
        """Decode side of the alternate topology: hand the token batch to
        the prefill instance, which forwards it to the master and returns
        the master's continue map."""
        from xllm_service_tpu.api.http_utils import post_json
        from xllm_service_tpu.api.protocol import output_to_json

        code, resp = post_json(
            addr,
            "/rpc/relay_generations",
            {"gens": [output_to_json(o) for o in outputs]},
            timeout=5.0,
        )
        if code != 200:
            raise RuntimeError(f"relay peer {addr} returned {code}")
        return resp.get("cont", {})

    # ------------------------------------------------------------------ #
    # HTTP surface
    # ------------------------------------------------------------------ #

    def handle_get(self, h: QuietHandler) -> None:
        route = h.route
        if route == "/hello":
            h.send_json({"message": f"hello from instance {self.name}"})
        elif route == "/metrics":
            lm = self.engine.get_load_metrics()
            lat = self.engine.get_latency_metrics()
            body = (
                "# TYPE xllm_engine_waiting_requests gauge\n"
                f"xllm_engine_waiting_requests {lm.waiting_requests_num}\n"
                "# TYPE xllm_engine_kv_cache_usage gauge\n"
                f"xllm_engine_kv_cache_usage {lm.gpu_cache_usage_perc:.4f}\n"
                "# TYPE xllm_engine_recent_max_ttft_ms gauge\n"
                f"xllm_engine_recent_max_ttft_ms {lat.recent_max_ttft}\n"
                "# TYPE xllm_engine_recent_max_tbt_ms gauge\n"
                f"xllm_engine_recent_max_tbt_ms {lat.recent_max_tbt}\n"
            ).encode()
            h.send_response(200)
            h.send_header("Content-Type", "text/plain; version=0.0.4")
            h.send_header("Content-Length", str(len(body)))
            h.end_headers()
            h.wfile.write(body)
        elif route == "/v1/models":
            h.send_json(
                {
                    "object": "list",
                    "data": [{"id": self.cfg.model, "object": "model"}],
                }
            )
        else:
            h.send_error_json(404, f"no route {route}")

    def handle_post(self, h: QuietHandler) -> None:
        route = h.route
        if route == "/kv/import":  # binary body, not JSON
            self._handle_kv_import(h)
            return
        body = h.read_json()
        if body is None:
            h.send_error_json(400, "invalid JSON body")
            return
        if route == "/v1/completions":
            self._serve(h, body, chat=False)
        elif route == "/v1/chat/completions":
            self._serve(h, body, chat=True)
        elif route == "/v1/embeddings":
            self._handle_embeddings(h, body)
        elif route == "/encode":
            self._handle_encode(h, body)
        elif route == "/mm/import":
            self._handle_mm_import(h, body)
        elif route == "/rpc/relay_generations":
            # Prefill side of the alternate PD response topology: forward
            # the decode peer's token batch to the master synchronously so
            # the continue map (cancellation feedback) flows back through
            # the same exchange.
            from xllm_service_tpu.api.protocol import output_from_json

            if self._master is None:
                h.send_error_json(503, "no master connection to relay to")
                return
            try:
                outs = [output_from_json(j) for j in body.get("gens", [])]
            except Exception as e:
                h.send_error_json(400, f"bad generations payload: {e}")
                return
            try:
                cont = self._master.push_generations(outs)
            except Exception as e:
                h.send_error_json(502, f"master push failed: {e}")
                return
            h.send_json({"ok": True, "cont": cont})
        elif route == "/flip":
            # Dynamic PD-ratio role flip (SURVEY §7 hard part 4): the
            # master's registry changed this instance's serving role; now
            # the ENGINE learns it too (round-1 weak item 8 — reference
            # never notifies, instance_mgr.cpp:759-807). MIX engines serve
            # both roles with identical compiled shapes (bucketed prefill +
            # fixed decode batch + persistent jit cache), so no
            # recompilation is needed — the role re-points heartbeat
            # metadata and is observable on /metrics.
            role = str(body.get("role", ""))
            if role not in ("PREFILL", "DECODE"):
                h.send_error_json(400, f"bad role {role!r}")
                return
            # current_type is the SERVING role; meta.type stays the
            # DECLARED type (MIX) — clobbering it would make a lease-blip
            # re-register permanently strip flip eligibility.
            self.meta.current_type = InstanceType.parse(role)
            setattr(self.engine, "serving_role", role)
            logger.info("instance %s now serving role %s", self.name, role)
            h.send_json({"ok": True, "role": role})
        elif route == "/cancel":
            srid = body.get("service_request_id", "")
            with self._srid_mu:
                rids = self._srid_map.pop(srid, None) or []
            for rid in rids:
                self.engine.cancel(rid)
            h.send_json({"ok": True, "cancelled": bool(rids)})
        else:
            h.send_error_json(404, f"no route {route}")

    # ------------------------------------------------------------------ #
    # PD disaggregation
    # ------------------------------------------------------------------ #

    def _make_push_callback(
        self,
        srid: str,
        detoks: Optional[Dict[int, IncrementalDetokenizer]] = None,
    ):
        if detoks is None:
            detoks = {}

        def callback(out: RequestOutput) -> bool:
            out.service_request_id = srid
            self._detokenize(out, detoks)
            if out.finished:
                with self._srid_mu:
                    self._srid_map.pop(srid, None)
                # A prefill_only request that finishes on its first token
                # (EOS / max_tokens=1 / reject / cancel) never runs its
                # handoff — reap the ack event here or it leaks forever.
                with self._push_acked_mu:
                    self._push_acked.pop(srid, None)
            self._push_q.put(out)
            return True

        return callback

    def _resolve_instance_addr(self, name: str) -> str:
        addr = self._peer_addrs.get(name)
        if addr:
            return addr
        meta = self._master.instance_info(name) if self._master else None
        if meta is None:
            return ""
        self._peer_addrs[name] = meta.http_address
        return meta.http_address

    def _make_handoff_sender(
        self,
        srid: str,
        decode_name: str,
        body: Dict,
        detoks: Optional[Dict[int, IncrementalDetokenizer]] = None,
        seed: Optional[int] = None,
        respond_via_self: bool = False,
    ):
        from xllm_service_tpu.common.types import Status, StatusCode

        sampling_fields = {
            k: body[k]
            for k in (
                "max_tokens", "max_completion_tokens", "temperature",
                "top_p", "top_k", "seed", "logprobs", "top_logprobs",
                "ignore_eos", "presence_penalty", "frequency_penalty",
            )
            if k in body
        }
        if seed is not None:
            # Forward the RESOLVED seed (possibly drawn at random for an
            # unseeded request) so the decode peer continues the same
            # RNG stream instead of drawing its own.
            sampling_fields["seed"] = seed

        def transfer(handoff) -> None:
            # Runs on the transfer thread (never the engine thread): waits
            # for the master to ack the first-token push, then POSTs the KV
            # payload to the decode peer. The engine already released the
            # sequence's slot and blocks before enqueueing this job, so a
            # slow master/peer delays only this handoff, not the engine.
            #
            # TOCTOU guard: send() kept the KV device-resident because a
            # local peer existed at enqueue time; if that peer deregistered
            # since, copy to host NOW — before the ack wait below — so a
            # device export never sits pinned in HBM through it. With the
            # pull plane enabled, device-residency through the ack wait is
            # the point (the peer pulls from device memory), so the copy
            # is skipped.
            if (
                handoff.kv is not None
                and not isinstance(handoff.kv, np.ndarray)
                and self._local_peer(decode_name) is None
                and self._kv_transfer is None
            ):
                handoff = dataclasses.replace(
                    handoff, kv=np.asarray(handoff.kv)
                )
            with self._push_acked_mu:
                acked = self._push_acked.get(srid)
            err = ""
            # Cross-instance ordering: the first token must be acked by the
            # master before the decode peer can start pushing, or a client
            # could see token 2 before token 1. The event stays in the dict
            # until AFTER the wait — popping first would race the ack.
            if acked is not None and not acked.wait(60.0):
                err = "first-token push never acked by master"
            with self._push_acked_mu:
                self._push_acked.pop(srid, None)
            if not err:
                extra = {
                    "service_request_id": srid,
                    "sampling": sampling_fields,
                }
                if respond_via_self:
                    # Alternate topology: decode relays its generations
                    # back through this (prefill) instance.
                    extra["respond_addr"] = self.address
                # Detokenizer carry-over: the decode peer continues from
                # this side's exact byte/char position.
                d0 = (detoks or {}).get(0)
                if d0 is not None:
                    ids, emitted = d0.export_state()
                    extra["detok_ids"] = ids
                    extra["detok_emitted"] = emitted
                peer = self._local_peer(decode_name)
                if peer is not None:
                    # Colocated peer: direct in-process import, no
                    # serialization (ICI-path analog).
                    try:
                        peer._admit_import(handoff, extra)
                    except Exception as e:
                        err = f"local decode peer import failed: {e}"
                else:
                    addr = self._resolve_instance_addr(decode_name)
                    if not addr:
                        err = f"decode instance {decode_name} unknown"
                    else:
                        err = self._post_handoff(addr, handoff, extra)
            if not err:
                # Handoff complete: this instance is done with the request
                # (the decode peer owns cancellation from here).
                with self._srid_mu:
                    self._srid_map.pop(srid, None)
            if err:
                logger.error("handoff for %s failed: %s", srid, err)
                out = RequestOutput(
                    request_id=handoff.request_id,
                    service_request_id=srid,
                    status=Status(StatusCode.UNAVAILABLE, err),
                    finished=True,
                )
                with self._srid_mu:
                    self._srid_map.pop(srid, None)
                self._push_q.put(out)

        def send(handoff) -> None:
            # Engine-thread side. The KV export arrives as a DEVICE array;
            # it may only stay device-resident if a colocated peer will
            # take it directly (in-process import) or the pull plane will
            # serve it (the decode peer pulls from device memory) — on the
            # bytes path it would otherwise sit pinned in HBM through the
            # queue + up-to-60s ack wait while the engine has already
            # freed and re-budgeted those blocks (round-2 review finding).
            # Copy to host here for the bytes path; a peer that
            # (de)registers between enqueue and transfer still works —
            # both import paths accept either array kind.
            if (
                handoff.kv is not None
                and self._local_peer(decode_name) is None
                and self._kv_transfer is None
            ):
                handoff = dataclasses.replace(
                    handoff, kv=np.asarray(handoff.kv)
                )
            self._transfer_q.put(lambda: transfer(handoff))

        return send

    def _post_handoff(self, addr: str, handoff, extra: Dict[str, Any]) -> str:
        """POST one handoff to a cross-process decode peer; returns "" on
        success, an error string otherwise.

        With the pull plane up and a device-resident payload, the KV is
        OFFERED on this process's transfer server and the POST carries
        only {addr, uuid, shape, dtype}; the peer pulls device-to-device
        before acking (runtime/transfer.py). A peer that rejects the pull
        header (no transfer server / pull failure) gets ONE retry on the
        bytes plane. Host (np) payloads always ride the bytes plane."""
        use_pull = (
            self._kv_transfer is not None
            and handoff.kv is not None
            and not isinstance(handoff.kv, np.ndarray)
            and addr not in self._peer_no_pull
        )
        if use_pull:
            kv_dev = handoff.kv
            uuid = self._kv_transfer.offer([kv_dev])
            header = dict(extra)
            header["kv_pull"] = {
                "addr": self._kv_transfer.address,
                "uuid": uuid,
                "shape": [int(s) for s in kv_dev.shape],
                "dtype": str(kv_dev.dtype),
            }
            try:
                payload = handoff_to_bytes(
                    dataclasses.replace(handoff, kv=None), header
                )
                code, resp = post_bytes(addr, "/kv/import", payload)
            except Exception as e:
                # The peer may STILL be pulling (e.g. our request timed
                # out while its pull was in flight) — an immediate
                # retract could free the buffer under it.
                self._kv_transfer.retract_later(uuid)
                return f"decode peer unreachable: {e}"
            # A response means the peer finished (or never started) its
            # pull — the offer's keepalive can drop now.
            self._kv_transfer.retract(uuid)
            if code == 200:
                return ""
            logger.warning(
                "pull-plane handoff rejected by %s (%s); using the bytes "
                "plane for this peer from now on", addr, resp,
            )
            # Capability cache: a peer without a transfer server rejects
            # EVERY pull header — don't pay the failing round trip per
            # handoff forever.
            self._peer_no_pull.add(addr)
            handoff = dataclasses.replace(handoff, kv=np.asarray(kv_dev))
        try:
            payload = handoff_to_bytes(handoff, extra)
            code, resp = post_bytes(addr, "/kv/import", payload)
            if code != 200:
                return f"decode peer rejected handoff: {resp}"
        except Exception as e:
            return f"decode peer unreachable: {e}"
        return ""

    def _local_peer(self, decode_name: str) -> Optional["InstanceServer"]:
        """The colocated in-process peer eligible for direct (device-
        resident) KV handoff, or None. BOTH sides must opt in, and both
        must belong to the same master (name collisions across stacks in
        one process must not cross-deliver KV)."""
        if not self.cfg.enable_local_kv_transfer:
            return None
        with _LOCAL_MU:
            peer = _LOCAL_INSTANCES.get(decode_name)
        if peer is None or peer is self:
            return None
        if not peer.cfg.enable_local_kv_transfer or getattr(
            peer._master, "_addr", None
        ) != getattr(self._master, "_addr", ""):
            return None
        return peer

    def _handle_embeddings(self, h: QuietHandler, body: Dict[str, Any]) -> None:
        """Engine-side /v1/embeddings: token id lists in (the service
        tokenizes, same injection contract as generation forwarding),
        mean-pooled normalized hidden-state vectors out. The reference
        rejects this endpoint (service.cpp:441-442) — implementing it
        exceeds parity."""
        token_lists = body.get("token_ids")
        if not isinstance(token_lists, list) or not token_lists or not all(
            isinstance(t, list) and t for t in token_lists
        ):
            h.send_error_json(
                400,
                "token_ids (non-empty list of non-empty id lists) required "
                "— raw text inputs are tokenized by the master service",
            )
            return
        limit = self.cfg.max_seq_len
        too_long = max(len(t) for t in token_lists)
        if too_long > limit:
            h.send_error_json(
                400,
                f"input of {too_long} tokens exceeds max_seq_len {limit}",
            )
            return
        try:
            vecs = self.engine.executor.embed_tokens(token_lists)
        except Exception as e:
            h.send_error_json(500, f"embedding failed: {e}")
            return
        n_tok = sum(len(t) for t in token_lists)
        h.send_json(
            {
                "object": "list",
                "model": body.get("model") or self.cfg.model,
                "data": [
                    {
                        "object": "embedding",
                        "index": i,
                        "embedding": [float(x) for x in vecs[i]],
                    }
                    for i in range(len(token_lists))
                ],
                "usage": {"prompt_tokens": n_tok, "total_tokens": n_tok},
            }
        )

    def _handle_kv_import(self, h: QuietHandler) -> None:
        try:
            n = int(h.headers.get("Content-Length", 0))
            data = h.rfile.read(n)
            handoff, header = handoff_from_bytes(data)
        except Exception as e:
            h.send_error_json(400, f"bad handoff payload: {e}")
            return
        if "kv_pull" in header:
            # Pull plane: the body carried no KV bytes — pull the payload
            # straight from the prefill peer's device memory into ours,
            # BEFORE acking (so the sender's offer lifetime is bounded by
            # this round-trip and pull failures surface in its response).
            if self._kv_transfer is None:
                h.send_error_json(
                    400, "kv_pull offered but this instance has no "
                    "transfer server (enable_kv_transfer_server)",
                )
                return
            p = header["kv_pull"]
            try:
                try:
                    dt = np.dtype(p["dtype"])
                except TypeError:
                    import ml_dtypes

                    dt = np.dtype(getattr(ml_dtypes, p["dtype"]))
                kv = self._kv_transfer.pull_single(
                    p["addr"], int(p["uuid"]), p["shape"], dt
                )
            except Exception as e:
                h.send_error_json(400, f"kv pull failed: {e}")
                return
            handoff = dataclasses.replace(handoff, kv=kv)
        rid = self._admit_import(handoff, header)
        h.send_json({"ok": True, "request_id": rid})

    def _admit_import(self, handoff, header: Dict[str, Any]) -> str:
        """Decode-side admission of a handed-off sequence — shared by the
        HTTP /kv/import route and the in-process direct path (colocated
        peers skip serialization entirely; the single-host analog of the
        ICI device-to-device KV transfer)."""
        from xllm_service_tpu.runtime.engine import EngineRequest

        srid = header.get("service_request_id", "")
        sampling = sampling_from_body(header.get("sampling", {}), self.cfg)
        rid = generate_uuid(16)
        with self._srid_mu:
            self._srid_map.setdefault(srid, []).append(rid)
        relay_addr = header.get("respond_addr", "")
        if relay_addr:
            self._relay_addrs[srid] = relay_addr
        detoks: Dict[int, IncrementalDetokenizer] = {}
        if "detok_ids" in header:
            detoks[0] = IncrementalDetokenizer.from_state(
                self.tokenizer, header["detok_ids"],
                header.get("detok_emitted", 0),
            )
        self.engine.import_sequence(
            EngineRequest(
                request_id=rid,
                prompt_token_ids=handoff.token_ids[:-1],
                sampling=sampling,
                callback=self._make_push_callback(srid, detoks),
            ),
            handoff,
        )
        return rid

    # ------------------------------------------------------------------ #
    # EPD multimodal (encoder stage + embedding import)
    # ------------------------------------------------------------------ #

    def _handle_encode(self, h: QuietHandler, body: Dict[str, Any]) -> None:
        """ENCODE-instance entry: media parts in, embeddings pushed to the
        prefill peer's /mm/import, ack out (three-stage EPD routing)."""
        import base64

        import numpy as np

        if not hasattr(self.engine, "encode"):
            h.send_error_json(501, "this instance has no encoder engine")
            return
        srid = body.get("service_request_id", "")
        parts = body.get("parts") or []
        positions = body.get("positions") or []
        target = body.get("target", "")
        if not parts or not target:
            h.send_error_json(400, "parts and target are required")
            return
        vcfg = self.engine.executor.cfg
        images = []
        for p in parts:
            shape = p.get("shape") or []
            if (
                len(shape) != 3
                or shape[0] != vcfg.image_size
                or shape[1] != vcfg.image_size
                or shape[2] != 3
            ):
                h.send_error_json(
                    400,
                    f"media shape {shape} != encoder input "
                    f"[{vcfg.image_size}, {vcfg.image_size}, 3]",
                )
                return
            try:
                arr = np.frombuffer(
                    base64.b64decode(p["data"]), np.float32
                ).reshape(shape)
            except Exception as e:
                h.send_error_json(400, f"bad media payload: {e}")
                return
            images.append(arr)
        embeds = self.engine.encode(np.stack(images))  # [B, T, D]
        flat = np.ascontiguousarray(embeds.reshape(-1, embeds.shape[-1]))
        if positions and len(positions) != flat.shape[0]:
            h.send_error_json(
                400,
                f"{len(positions)} placeholder positions but the encoder "
                f"produced {flat.shape[0]} media tokens "
                f"({embeds.shape[1]} per part — set mm_tokens_per_media)",
            )
            return
        try:
            code, resp = post_json(
                target,
                "/mm/import",
                {
                    "service_request_id": srid,
                    "embeds": base64.b64encode(flat.tobytes()).decode(),
                    "count": int(flat.shape[0]),
                    "dim": int(flat.shape[1]),
                    "positions": list(positions),
                },
                timeout=30.0,
            )
        except Exception as e:
            h.send_error_json(502, f"prefill peer unreachable: {e}")
            return
        if code != 200:
            h.send_error_json(502, f"prefill peer rejected embeddings: {resp}")
            return
        h.send_json({"ok": True, "media_tokens": int(flat.shape[0])})

    _MM_IMPORT_TTL_S = 120.0

    def _handle_mm_import(self, h: QuietHandler, body: Dict[str, Any]) -> None:
        import base64

        import numpy as np

        srid = body.get("service_request_id", "")
        try:
            count = int(body["count"])
            dim = int(body["dim"])
            embeds = np.frombuffer(
                base64.b64decode(body["embeds"]), np.float32
            ).reshape(count, dim)
            positions = [int(p) for p in body.get("positions", [])]
        except Exception as e:
            h.send_error_json(400, f"bad embeddings payload: {e}")
            return
        now = time.monotonic()
        with self._mm_mu:
            # Reap orphans (a push landing after its waiter timed out, or a
            # master that died between /encode and the forward): without a
            # TTL every such request pins its embedding array forever.
            stale = [
                s for s, (_, _, ts) in self._mm_imports.items()
                if now - ts > self._MM_IMPORT_TTL_S
            ]
            for s in stale:
                self._mm_imports.pop(s, None)
                self._mm_events.pop(s, None)
            self._mm_imports[srid] = (embeds, positions, now)
            ev = self._mm_events.setdefault(srid, threading.Event())
        ev.set()
        h.send_json({"ok": True})

    def _pop_mm_import(self, srid: str, timeout: float):
        with self._mm_mu:
            ev = self._mm_events.setdefault(srid, threading.Event())
        if not ev.wait(timeout):
            with self._mm_mu:
                self._mm_events.pop(srid, None)
            return None
        with self._mm_mu:
            self._mm_events.pop(srid, None)
            entry = self._mm_imports.pop(srid, None)
            return entry[:2] if entry is not None else None

    # ------------------------------------------------------------------ #
    # n>1 / best_of fan-out
    # ------------------------------------------------------------------ #

    def _serve_fanout_forwarded(
        self,
        srid: str,
        token_ids: List[int],
        sampling: SamplingParams,
        n: int,
        best_of: int,
    ) -> None:
        """Run n (or best_of) sequences as independent engine requests and
        push INDEXED deltas under one service_request_id. The prompt's KV
        blocks are shared through the prefix cache. best_of buffers all
        children and pushes only the top-n (by mean logprob) at the end."""
        from xllm_service_tpu.common.types import Usage
        from xllm_service_tpu.runtime.engine import EngineRequest

        total = best_of or n
        detoks: Dict[int, IncrementalDetokenizer] = {}
        agg_mu = threading.Lock()
        state = {
            "remaining": total,
            "generated": [0] * total,
            "logprob_sum": [0.0] * total,
            "buffered": {} if best_of else None,  # index -> merged SequenceOutput
            "aborted": False,
        }
        want_logprobs = sampling.logprobs

        def make_cb(i: int):
            def cb(out: RequestOutput) -> bool:
                out.service_request_id = srid
                for s in out.outputs:
                    s.index = i
                    for lp in s.logprobs:
                        state["logprob_sum"][i] += lp.data.logprob
                with agg_mu:
                    if state["aborted"]:
                        return False
                    if out.usage:
                        state["generated"][i] = out.usage.num_generated_tokens
                    last = False
                    if out.finished:
                        state["remaining"] -= 1
                        last = state["remaining"] == 0
                if not out.status.ok() and not out.cancelled:
                    # Child error (reject/engine failure): surface it ONCE,
                    # cancel the siblings, drop the request.
                    with agg_mu:
                        state["aborted"] = True
                    with self._srid_mu:
                        others = self._srid_map.pop(srid, None) or []
                    for other in others:
                        self.engine.cancel(other)
                    out.finished = True
                    self._push_q.put(out)
                    return False
                if state["buffered"] is not None:
                    # best_of: hold everything until all children finish.
                    with agg_mu:
                        accumulate_sequences(state["buffered"], out)
                    if last:
                        self._finish_best_of(
                            srid, state, token_ids, n, want_logprobs, detoks
                        )
                    return True
                # n>1 streaming/accumulating path: push indexed deltas; only
                # the LAST child's finish carries finished + merged usage
                # (per-seq finish_reason still reaches the client).
                self._detokenize(out, detoks)
                if out.finished and not last:
                    out.finished = False
                    out.usage = None
                elif out.finished and last:
                    out.usage = Usage(
                        num_prompt_tokens=len(token_ids),
                        num_generated_tokens=sum(state["generated"]),
                    )
                    with self._srid_mu:
                        self._srid_map.pop(srid, None)
                self._push_q.put(out)
                return True

            return cb

        # Register the rids BEFORE submitting: a fast-finishing child pops
        # the srid entry, and a late registration would resurrect it (leak)
        # or let a /cancel in the window find nothing to cancel.
        rids = [generate_uuid(16) for _ in range(total)]
        with self._srid_mu:
            self._srid_map.setdefault(srid, []).extend(rids)
        for i, rid in enumerate(rids):
            self.engine.add_request(
                EngineRequest(
                    request_id=rid,
                    prompt_token_ids=list(token_ids),
                    sampling=self._child_sampling(
                        sampling, i, need_logprobs=bool(best_of)
                    ),
                    callback=make_cb(i),
                )
            )

    def _finish_best_of(
        self,
        srid: str,
        state: Dict[str, Any],
        token_ids: List[int],
        n: int,
        want_logprobs: bool,
        detoks: Dict[int, IncrementalDetokenizer],
    ) -> None:
        """All best_of children done: rank by mean logprob, re-index the
        top n as choices 0..n-1, push ONE final output."""
        from xllm_service_tpu.common.types import Usage

        merged = state["buffered"]
        order = sorted(
            merged,
            key=lambda i: (
                state["logprob_sum"][i] / max(len(merged[i].token_ids), 1)
            ),
            reverse=True,
        )
        winners = []
        for new_idx, old_idx in enumerate(order[:n]):
            s = merged[old_idx]
            s.index = new_idx
            if not want_logprobs:
                s.logprobs = []
            winners.append(s)
        final = RequestOutput(
            request_id=srid,
            service_request_id=srid,
            outputs=winners,
            usage=Usage(
                num_prompt_tokens=len(token_ids),
                num_generated_tokens=sum(state["generated"]),
            ),
            finished=True,
        )
        self._detokenize(final, detoks)
        with self._srid_mu:
            self._srid_map.pop(srid, None)
        self._push_q.put(final)

    # ------------------------------------------------------------------ #
    def _prompt_tokens(self, body: Dict[str, Any], chat: bool) -> List[int]:
        # Forwarded traffic arrives pre-tokenized (the injection contract,
        # service.cpp:334-341) — never re-tokenize.
        if body.get("token_ids"):
            return [int(t) for t in body["token_ids"]]
        if chat:
            prompt = self.chat_template.apply(
                parse_messages(body.get("messages", [])), body.get("tools")
            )
        else:
            prompt, token_ids, err = parse_prompt_field(body.get("prompt", ""))
            if err:
                raise ValueError(err)
            if token_ids:
                return token_ids
        return self.tokenizer.encode(prompt)

    @staticmethod
    def _n_sequences(body: Dict[str, Any], chat: bool) -> Tuple[int, int, str]:
        """Parse (n, best_of, error). best_of is the completions-only
        over-generation count (>= n, select top-n by logprob); chat has no
        best_of. Errors mirror OpenAI validation."""
        try:
            n = max(int(body.get("n") or 1), 1)
        except (TypeError, ValueError):
            return 1, 0, "invalid n"
        best_of = 0
        if not chat and body.get("best_of") is not None:
            try:
                best_of = int(body["best_of"])
            except (TypeError, ValueError):
                return n, 0, "invalid best_of"
            if best_of < n:
                return n, best_of, "best_of must be >= n"
            if body.get("stream"):
                return n, best_of, "best_of is not supported with streaming"
        return n, best_of, ""

    @staticmethod
    def _child_sampling(sampling: SamplingParams, i: int, need_logprobs: bool):
        """Per-sequence sampling params: distinct RNG stream per choice
        (i=0 keeps the request seed so n=1 behavior is unchanged)."""
        import dataclasses

        seed = (sampling.seed + 0x9E3779B9 * i) & 0xFFFFFFFF
        return dataclasses.replace(
            sampling,
            seed=seed,
            logprobs=sampling.logprobs or need_logprobs,
        )

    def _serve(self, h: QuietHandler, body: Dict[str, Any], chat: bool) -> None:
        from xllm_service_tpu.runtime.engine import EngineRequest

        srid = body.get("service_request_id", "")
        try:
            token_ids = self._prompt_tokens(body, chat)
        except (ValueError, TypeError) as e:
            h.send_error_json(400, str(e))
            return
        if not token_ids:
            h.send_error_json(400, "empty prompt")
            return
        n, best_of, n_err = self._n_sequences(body, chat)
        if n_err:
            h.send_error_json(400, n_err)
            return
        sampling = sampling_from_body(body, self.cfg)

        if srid and self._master is not None and (n > 1 or best_of > 1):
            # Fan-out mode: PD split is skipped for multi-sequence requests
            # (a per-child handoff would need sub-request ids on the wire);
            # this instance serves all sequences and pushes indexed deltas.
            self._serve_fanout_forwarded(srid, token_ids, sampling, n, best_of)
            h.send_json({"ok": True, "service_request_id": srid})
            return
        rid = generate_uuid(16)

        if srid and self._master is not None:
            # Forwarded mode: ack now, stream back over /rpc/generations.
            mm_embeds = mm_positions = None
            if body.get("mm_positions"):
                # EPD: the encoder stage pushed this request's media
                # embeddings to /mm/import (usually already landed — the
                # master dispatches the encoder first).
                mm = self._pop_mm_import(srid, timeout=60.0)
                if mm is None:
                    h.send_error_json(503, "media embeddings never arrived")
                    return
                mm_embeds, mm_positions = mm
                if len(mm_positions) != len(body["mm_positions"]):
                    # Encoder and service disagree on media-token count —
                    # reject rather than pair mismatched arrays (an
                    # embeds/positions desync would crash the engine step).
                    h.send_error_json(
                        502,
                        f"encoder produced {len(mm_positions)} media tokens "
                        f"but the request has "
                        f"{len(body['mm_positions'])} placeholders",
                    )
                    return
            with self._srid_mu:
                self._srid_map.setdefault(srid, []).append(rid)
            detoks: Dict[int, IncrementalDetokenizer] = {}
            callback = self._make_push_callback(srid, detoks)
            routing = body.get("routing") or {}
            decode_name = routing.get("decode_name", "")
            if mm_embeds is not None:
                # Media requests serve colocated: the recomputed tail on a
                # decode peer would need the embeddings too.
                decode_name = ""
            if decode_name and decode_name != self.name:
                # PD disaggregation: this instance is the prefill side —
                # emit the first token, then migrate KV to the decode peer
                # (reference topology: rpc_service/service.h:61-71).
                with self._push_acked_mu:
                    self._push_acked[srid] = threading.Event()
                self.engine.add_request(
                    EngineRequest(
                        request_id=rid,
                        prompt_token_ids=token_ids,
                        sampling=sampling,
                        callback=callback,
                        prefill_only=True,
                        handoff=self._make_handoff_sender(
                            srid, decode_name, body, detoks,
                            seed=sampling.seed,
                            respond_via_self=(
                                routing.get("decode_response_to_service", True)
                                is False
                            ),
                        ),
                    )
                )
            else:
                self.engine.add_request(
                    EngineRequest(
                        request_id=rid,
                        prompt_token_ids=token_ids,
                        sampling=sampling,
                        callback=callback,
                        mm_embeds=mm_embeds,
                        mm_positions=mm_positions,
                    )
                )
            h.send_json({"ok": True, "service_request_id": srid, "request_id": rid})
            return

        # Direct mode: this instance is the whole stack for one request.
        self._serve_direct(h, body, chat, token_ids, sampling, rid, n, best_of)

    def _serve_direct(
        self,
        h: QuietHandler,
        body: Dict[str, Any],
        chat: bool,
        token_ids: List[int],
        sampling: SamplingParams,
        rid: str,
        n: int = 1,
        best_of: int = 0,
    ) -> None:
        from xllm_service_tpu.runtime.engine import EngineRequest

        total = best_of or n

        req = ServiceRequest(
            service_request_id=("chatcmpl-" if chat else "cmpl-") + rid,
            model=body.get("model", self.cfg.model),
            stream=bool(body.get("stream", False)),
            include_usage=bool(
                (body.get("stream_options") or {}).get("include_usage", False)
            ),
            token_ids=token_ids,
        )
        if chat:
            req.messages = parse_messages(body.get("messages", []))
        else:
            p = body.get("prompt", "")
            req.prompt = p if isinstance(p, str) else "".join(p)

        done = threading.Event()
        acc: List[RequestOutput] = []
        sse: Optional[SseWriter] = None
        # Per-choice: each choice's first chat chunk must carry the
        # assistant role (OpenAI stream semantics), not just the globally
        # first chunk.
        first_sent: Dict[int, bool] = {}
        agg_mu = threading.Lock()
        remaining = [total]
        lp_sums = [0.0] * total
        gen_counts = [0] * total

        detoks: Dict[int, IncrementalDetokenizer] = {}
        if req.stream:
            sse = SseWriter(h)

            class _Stream:
                def write(_, payload):
                    return sse.send(payload)

                def write_done(_):
                    ok = sse.send_done()
                    done.set()
                    return ok

            stream = _Stream()

            def make_callback(i: int):
                def callback(out: RequestOutput) -> bool:
                    if not out.status.ok() and not out.cancelled:
                        # Engine-side failure: surface it, don't end as a
                        # clean empty stream.
                        sse.send(
                            {"error": {"message": out.status.message,
                                       "code": int(out.status.code)}}
                        )
                        sse.close()
                        done.set()
                        return False
                    for s in out.outputs:
                        s.index = i
                        gen_counts[i] += len(s.token_ids)
                    with agg_mu:
                        last = True
                        if out.finished:
                            remaining[0] -= 1
                            last = remaining[0] == 0
                        if out.finished and not last:
                            # Suppress the per-child [DONE]; keep the
                            # choice's finish_reason chunk.
                            out.finished = False
                            out.usage = None
                        elif out.finished and out.usage and total > 1:
                            from xllm_service_tpu.common.types import Usage

                            out.usage = Usage(
                                num_prompt_tokens=len(token_ids),
                                num_generated_tokens=sum(gen_counts),
                            )
                    self._detokenize(out, detoks)
                    ok = self._responses.send_delta_to_client(
                        stream, req, out, first_sent.get(i, False)
                    )
                    first_sent[i] = True
                    if out.finished or not ok:
                        # All sequences finished, or the client
                        # disconnected — the exchange is over.
                        done.set()
                    return ok

                return callback
        else:

            def make_callback(i: int):
                def callback(out: RequestOutput) -> bool:
                    for s in out.outputs:
                        s.index = i
                        for lp in s.logprobs:
                            lp_sums[i] += lp.data.logprob
                    if not best_of:
                        self._detokenize(out, detoks)
                    with agg_mu:
                        acc.append(out)
                        if out.finished:
                            remaining[0] -= 1
                            if remaining[0] == 0:
                                done.set()
                    return True

                return callback

        rids = []
        for i in range(total):
            child_rid = rid if i == 0 else generate_uuid(16)
            rids.append(child_rid)
            self.engine.add_request(
                EngineRequest(
                    request_id=child_rid,
                    prompt_token_ids=list(token_ids),
                    sampling=self._child_sampling(
                        sampling, i, need_logprobs=bool(best_of)
                    ),
                    callback=make_callback(i),
                )
            )
        if not done.wait(600.0):
            for child_rid in rids:
                self.engine.cancel(child_rid)
            if sse is None:
                # Only a never-started exchange can still carry an error
                # response; an open SSE stream must not get a second head.
                h.send_error_json(504, "generation timeout")
            else:
                sse.close()
                h.close_connection = True
            return
        if not req.stream:
            if best_of:
                self._respond_best_of(
                    h, req, acc, lp_sums, n, sampling.logprobs, detoks
                )
            else:
                self._respond_accumulated(h, req, acc)

    def _respond_best_of(
        self,
        h: QuietHandler,
        req: ServiceRequest,
        acc: List[RequestOutput],
        lp_sums: List[float],
        n: int,
        want_logprobs: bool,
        detoks: Dict[int, IncrementalDetokenizer],
    ) -> None:
        """Rank best_of children by mean logprob, return the top n as
        choices 0..n-1 (completions API best_of semantics)."""
        from xllm_service_tpu.common.types import Usage

        if any(not o.status.ok() and not o.cancelled for o in acc):
            self._respond_accumulated(h, req, acc)  # error path
            return
        merged: Dict[int, Any] = {}
        for out in acc:
            accumulate_sequences(merged, out)
        order = sorted(
            merged,
            key=lambda i: lp_sums[i] / max(len(merged[i].token_ids), 1),
            reverse=True,
        )
        winners = []
        total_generated = sum(len(s.token_ids) for s in merged.values())
        for new_idx, old_idx in enumerate(order[:n]):
            s = merged[old_idx]
            s.index = new_idx
            if not want_logprobs:
                s.logprobs = []
            winners.append(s)
        final = RequestOutput(
            request_id=req.service_request_id,
            service_request_id=req.service_request_id,
            outputs=winners,
            usage=Usage(
                num_prompt_tokens=len(req.token_ids),
                num_generated_tokens=total_generated,
            ),
            finished=True,
        )
        self._detokenize(final, detoks)

        class _Once:
            def finish(_, payload):
                h.send_json(payload)
                return True

            def finish_with_error(_, code, msg):
                h.send_error_json(500, msg)
                return True

        self._responses.send_result_to_client(_Once(), req, final)

    def _respond_accumulated(
        self, h: QuietHandler, req: ServiceRequest, acc: List[RequestOutput]
    ) -> None:
        # With n>1 children interleaving, an errored child's output can sit
        # anywhere in acc — scan, don't just check the tail.
        err = next(
            (o for o in acc if not o.status.ok() and not o.cancelled), None
        )
        if err is not None:
            h.send_error_json(
                429 if err.status.code == StatusCode.RESOURCE_EXHAUSTED else 500,
                err.status.message,
            )
            return
        merged: Dict[int, Any] = {}
        usage = None
        for out in acc:
            accumulate_sequences(merged, out)
            if out.usage:
                usage = out.usage
        if usage is not None and len(merged) > 1:
            # n>1: per-child usage only counts its own tokens — report the
            # request-level total.
            from xllm_service_tpu.common.types import Usage

            usage = Usage(
                num_prompt_tokens=usage.num_prompt_tokens,
                num_generated_tokens=sum(
                    len(s.token_ids) for s in merged.values()
                ),
            )
        final = RequestOutput(
            request_id=req.service_request_id,
            service_request_id=req.service_request_id,
            outputs=sorted(merged.values(), key=lambda s: s.index),
            usage=usage,
            finished=True,
        )

        class _Once:
            def finish(_, payload):
                h.send_json(payload)
                return True

            def finish_with_error(_, code, msg):
                h.send_error_json(500, msg)
                return True

        self._responses.send_result_to_client(_Once(), req, final)

    def _detokenize(
        self, out: RequestOutput, detoks: Dict[int, IncrementalDetokenizer]
    ) -> None:
        """Per-request incremental detokenization: characters spanning token
        boundaries are held back until complete (detoks carries one state
        per sequence index for the request's lifetime)."""
        for s in out.outputs:
            if s.token_ids and not s.text:
                d = detoks.get(s.index)
                if d is None:
                    d = detoks[s.index] = IncrementalDetokenizer(self.tokenizer)
                s.text = d.push(s.token_ids)
                if out.finished:
                    s.text += d.flush()
            for lp in s.logprobs:
                if not lp.data.token:
                    lp.data.token = self.tokenizer.id_to_token(lp.data.token_id)


def main(argv=None) -> None:
    import argparse

    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser("xllm-service-tpu instance")
    parser.add_argument("--model", default="llama3-tiny")
    parser.add_argument("--master-rpc-addr", default="")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--tokenizer-path", default="")
    parser.add_argument("--instance-type", default="MIX")
    parser.add_argument("--checkpoint-path", default="")
    parser.add_argument("--dtype", default="bfloat16")
    parser.add_argument("--block-size", type=int, default=128)
    parser.add_argument("--num-blocks", type=int, default=0)
    parser.add_argument("--max-running-requests", type=int, default=16)
    parser.add_argument("--max-seq-len", type=int, default=2048)
    parser.add_argument(
        "--prefill-buckets", default="128,256,512,1024,2048",
        help="comma-separated prefill padding buckets",
    )
    parser.add_argument(
        "--kv-cache-dtype", default="auto", choices=["auto", "int8"],
        help="int8 halves decode HBM traffic and doubles pool capacity",
    )
    parser.add_argument("--dp-size", type=int, default=1)
    parser.add_argument("--tp-size", type=int, default=1)
    parser.add_argument("--ep-size", type=int, default=1)
    parser.add_argument("--sp-size", type=int, default=1)
    parser.add_argument(
        "--sp-prefill-threshold", type=int, default=0,
        help="uncached-suffix length that routes prefill to the sp ring",
    )
    parser.add_argument(
        "--max-prefill-tokens", type=int, default=8192,
        help="strict per-step prefill budget (long prompts chunk across "
        "steps with decode interleaved)",
    )
    parser.add_argument(
        "--compilation-cache-dir", default="",
        help="persistent XLA jit cache (restarts skip the per-shape compiles)",
    )
    args = parser.parse_args(argv)
    # Restore standard JAX env semantics: some environments force a
    # platform at interpreter start (sitecustomize), overriding
    # JAX_PLATFORMS; an explicit env var wins here.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    cfg = EngineConfig(
        model=args.model,
        checkpoint_path=args.checkpoint_path,
        instance_type=args.instance_type,
        dtype=args.dtype,
        block_size=args.block_size,
        num_blocks=args.num_blocks,
        max_running_requests=args.max_running_requests,
        max_seq_len=args.max_seq_len,
        prefill_buckets=[int(b) for b in args.prefill_buckets.split(",")],
        kv_cache_dtype=args.kv_cache_dtype,
        dp_size=args.dp_size,
        tp_size=args.tp_size,
        ep_size=args.ep_size,
        sp_size=args.sp_size,
        sp_prefill_threshold=args.sp_prefill_threshold,
        max_prefill_tokens=args.max_prefill_tokens,
        compilation_cache_dir=args.compilation_cache_dir,
    )
    srv = InstanceServer(
        cfg,
        master_rpc_addr=args.master_rpc_addr,
        host=args.host,
        port=args.port,
        tokenizer_path=args.tokenizer_path,
    )
    srv.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
