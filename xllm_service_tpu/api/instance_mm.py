"""Multimodal (EPD) and embeddings endpoints of the instance server.

Split from api/instance.py (round-3 de-monolith): the ENCODE-stage
/encode entry, the prefill-side /mm/import landing + wait, and the
/v1/embeddings handler. Mixed into InstanceServer; `self` is the server.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict

import numpy as np

from xllm_service_tpu.api.http_utils import HttpJsonApi, post_json

class MultimodalMixin:
    # Landed-but-unclaimed media embeddings are reaped after this TTL.
    _MM_IMPORT_TTL_S = 120.0

    def _handle_embeddings(self, h: HttpJsonApi, body: Dict[str, Any]) -> None:
        """Engine-side /v1/embeddings: token id lists in (the service
        tokenizes, same injection contract as generation forwarding),
        mean-pooled normalized hidden-state vectors out. The reference
        rejects this endpoint (service.cpp:441-442) — implementing it
        exceeds parity."""
        token_lists = body.get("token_ids")
        if not isinstance(token_lists, list) or not token_lists or not all(
            isinstance(t, list) and t for t in token_lists
        ):
            h.send_error_json(
                400,
                "token_ids (non-empty list of non-empty id lists) required "
                "— raw text inputs are tokenized by the master service",
            )
            return
        limit = self.cfg.max_seq_len
        too_long = max(len(t) for t in token_lists)
        if too_long > limit:
            h.send_error_json(
                400,
                f"input of {too_long} tokens exceeds max_seq_len {limit}",
            )
            return
        try:
            vecs = self.engine.executor.embed_tokens(token_lists)
        except Exception as e:
            h.send_error_json(500, f"embedding failed: {e}")
            return
        n_tok = sum(len(t) for t in token_lists)
        h.send_json(
            {
                "object": "list",
                "model": body.get("model") or self.cfg.model,
                "data": [
                    {
                        "object": "embedding",
                        "index": i,
                        "embedding": [float(x) for x in vecs[i]],
                    }
                    for i in range(len(token_lists))
                ],
                "usage": {"prompt_tokens": n_tok, "total_tokens": n_tok},
            }
        )

    def _handle_encode(self, h: HttpJsonApi, body: Dict[str, Any]) -> None:
        """ENCODE-instance entry: media parts in, embeddings pushed to the
        prefill peer's /mm/import, ack out (three-stage EPD routing)."""
        import base64

        if not hasattr(self.engine, "encode"):
            h.send_error_json(501, "this instance has no encoder engine")
            return
        srid = body.get("service_request_id", "")
        parts = body.get("parts") or []
        positions = body.get("positions") or []
        target = body.get("target", "")
        if not parts or not target:
            h.send_error_json(400, "parts and target are required")
            return
        vcfg = getattr(self.engine.executor, "cfg", None)  # vision
        acfg = getattr(
            getattr(self.engine, "audio_executor", None), "cfg", None
        )
        decoded = []  # (kind, arr) in part order; kind: img|video|audio
        for p in parts:
            shape = p.get("shape") or []
            if len(shape) == 2:
                # Audio: [num_mel_bins, mel_frames] log-mel features.
                if acfg is None:
                    h.send_error_json(
                        501, "this encoder instance hosts no audio tower"
                    )
                    return
                if shape != [acfg.num_mel_bins, acfg.mel_frames]:
                    h.send_error_json(
                        400,
                        f"audio shape {shape} != encoder input "
                        f"[{acfg.num_mel_bins}, {acfg.mel_frames}]",
                    )
                    return
                kind = "audio"
            elif len(shape) in (3, 4):
                if vcfg is None:
                    h.send_error_json(
                        501, "this encoder instance hosts no vision tower"
                    )
                    return
                S = vcfg.image_size
                is_video = len(shape) == 4
                spatial = shape[1:] if is_video else shape
                if spatial != [S, S, 3] or (
                    is_video and (shape[0] < 2 or shape[0] % 2)
                ):
                    h.send_error_json(
                        400,
                        f"media shape {shape} != encoder input "
                        f"[{S}, {S}, 3] (or [T even, {S}, {S}, 3] for "
                        "video)",
                    )
                    return
                if is_video and (
                    not hasattr(self.engine, "encode_video")
                    or getattr(vcfg, "arch", "")
                    not in ("qwen2vl", "qwen25vl")
                ):
                    # Checked HERE, not at jit-trace time inside the
                    # encode call — a raise escaping the handler tears
                    # down the connection instead of sending this 501
                    # (review finding, r5).
                    h.send_error_json(
                        501,
                        f"this encoder ({getattr(vcfg, 'arch', '?')}) "
                        "has no video path (qwen2vl/qwen25vl towers "
                        "only)",
                    )
                    return
                kind = "video" if is_video else "img"
            else:
                h.send_error_json(400, f"bad media shape {shape}")
                return
            try:
                arr = np.frombuffer(
                    base64.b64decode(p["data"]), np.float32
                ).reshape(shape)
            except Exception as e:
                h.send_error_json(400, f"bad media payload: {e}")
                return
            decoded.append((kind, arr))
        # Contiguous same-kind stills/audio batch through one encode
        # call; videos encode per part (token count varies with frames).
        chunks = []
        batch: list = []
        batch_kind = ""

        def flush():
            nonlocal batch_kind
            if batch:
                fn = (
                    self.engine.encode_audio if batch_kind == "audio"
                    else self.engine.encode
                )
                out = fn(np.stack(batch))  # [B, tokens, D]
                chunks.extend(out[i] for i in range(out.shape[0]))
                batch.clear()
            batch_kind = ""

        for kind, arr in decoded:
            if kind == "video":
                flush()
                chunks.append(self.engine.encode_video(arr))  # [N, D]
            else:
                if batch_kind not in ("", kind):
                    flush()
                batch_kind = kind
                batch.append(arr)
        flush()
        flat = np.ascontiguousarray(
            np.concatenate([np.asarray(c).reshape(-1, c.shape[-1])
                            for c in chunks])
        )
        if positions and len(positions) != flat.shape[0]:
            per_part = [
                int(np.asarray(c).reshape(-1, flat.shape[-1]).shape[0])
                for c in chunks
            ]
            h.send_error_json(
                400,
                f"{len(positions)} placeholder positions but the encoder "
                f"produced {flat.shape[0]} media tokens "
                f"(per part: {per_part} — check mm_tokens_per_media and "
                "the video frame counts)",
            )
            return
        try:
            code, resp = post_json(
                target,
                "/mm/import",
                {
                    "service_request_id": srid,
                    "embeds": base64.b64encode(flat.tobytes()).decode(),
                    "count": int(flat.shape[0]),
                    "dim": int(flat.shape[1]),
                    "positions": list(positions),
                },
                timeout=30.0,
            )
        except Exception as e:
            h.send_error_json(502, f"prefill peer unreachable: {e}")
            return
        if code != 200:
            h.send_error_json(502, f"prefill peer rejected embeddings: {resp}")
            return
        h.send_json({"ok": True, "media_tokens": int(flat.shape[0])})

    def _handle_mm_import(self, h: HttpJsonApi, body: Dict[str, Any]) -> None:
        import base64

        srid = body.get("service_request_id", "")
        try:
            count = int(body["count"])
            dim = int(body["dim"])
            embeds = np.frombuffer(
                base64.b64decode(body["embeds"]), np.float32
            ).reshape(count, dim)
            positions = [int(p) for p in body.get("positions", [])]
        except Exception as e:
            h.send_error_json(400, f"bad embeddings payload: {e}")
            return
        now = time.monotonic()
        with self._mm_mu:
            # Reap orphans (a push landing after its waiter timed out, or a
            # master that died between /encode and the forward): without a
            # TTL every such request pins its embedding array forever.
            stale = [
                s for s, (_, _, ts) in self._mm_imports.items()
                if now - ts > self._MM_IMPORT_TTL_S
            ]
            for s in stale:
                self._mm_imports.pop(s, None)
                self._mm_events.pop(s, None)
            self._mm_imports[srid] = (embeds, positions, now)
            ev = self._mm_events.setdefault(srid, threading.Event())
        ev.set()
        h.send_json({"ok": True})

    def _pop_mm_import(self, srid: str, timeout: float):
        with self._mm_mu:
            ev = self._mm_events.setdefault(srid, threading.Event())
        if not ev.wait(timeout):
            with self._mm_mu:
                self._mm_events.pop(srid, None)
            return None
        with self._mm_mu:
            self._mm_events.pop(srid, None)
            entry = self._mm_imports.pop(srid, None)
            return entry[:2] if entry is not None else None
