"""Multimodal (EPD) and embeddings endpoints of the instance server.

Split from api/instance.py (round-3 de-monolith): the ENCODE-stage
/encode entry, the prefill-side /mm/import landing + wait, and the
/v1/embeddings handler. Mixed into InstanceServer; `self` is the server.

Encoder fabric (docs/EPD.md): with `XLLM_ENCODER_FABRIC` on, the
monolithic `/mm/import` push grows a per-item streaming session modeled
on PR 5's `/kv/import` protocol —

    /mm/open   {srid, items}            session open (epoch-fenced)
    /mm/chunk  {srid, item, positions,  one media item's embedding rows,
                count, dim, embeds}     landed as it finishes encoding
    /mm/commit {srid, count}            all items delivered
    /mm/abort  {srid, reason}           streaming failed; the MONOLITHIC
                                        /mm/import push follows (fallback)

— so the prefill peer admits the text request immediately and its engine
prefills text chunks WHILE embeddings stream in, adopting landed items
at chunk boundaries (runtime/engine.py mm_stream gating). Chunk sends
ride the instance's dedicated bounded stream lane (`_stream_q`); a
saturated lane or any send failure aborts the session and degrades to
the monolithic push — never to an error.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from xllm_service_tpu.api.http_utils import HttpJsonApi, post_json
from xllm_service_tpu.common import faults

logger = logging.getLogger("xllm_service_tpu.api.instance")


def _encoder_fabric_enabled(cfg) -> bool:
    """Instance-side escape hatch, read per request so it can flip on a
    live instance (mirrors _pd_streaming_enabled in instance_kv.py).
    One implementation fleet-wide: master and instance must agree on
    the hatch semantics or the wire protocol splits."""
    from xllm_service_tpu.cluster.encoder_fabric import (
        encoder_fabric_enabled,
    )

    return encoder_fabric_enabled(cfg)


class MMStreamHandle:
    """Prefill-side assembly of one request's streamed media embeddings.

    Created at forwarded-request admission (the master forwards the text
    request BEFORE dispatching the encoder when the fabric is on); fed by
    `/mm/chunk` landings — or by a monolithic `/mm/import` push, which is
    both the legacy path and the abort fallback — and consumed by the
    engine at every prefill chunk boundary (`ready_upto`/`assembled`).
    An abort is ADVISORY: the encoder falls back to the monolithic push,
    so only the deadline fails a request whose stream died."""

    def __init__(
        self,
        srid: str,
        expected_positions: List[int],
        deadline_s: float = 180.0,
        on_update=None,
        on_complete=None,
    ):
        self.srid = srid
        self._expected = sorted(int(p) for p in expected_positions)
        self._expected_set = set(self._expected)
        self._mu = threading.Lock()
        self._covered: set = set()
        self._items: List[Tuple[List[int], np.ndarray]] = []
        self.created_ts = time.monotonic()
        self.admitted_ts: Optional[float] = None
        self.complete_ts: Optional[float] = None
        self._deadline = self.created_ts + max(float(deadline_s), 1.0)
        self._failed_msg = ""
        self._complete = False
        self._on_update = on_update
        self._on_complete = on_complete

    def land(self, positions: List[int], embeds: np.ndarray) -> None:
        """One item's rows (positions pair 1:1 with embedding rows).
        Idempotent: a fully re-landed item (master re-dispatch, abort
        fallback after partial streaming) is dropped silently."""
        done = None
        with self._mu:
            if self._complete:
                return
            pos = [int(p) for p in positions]
            if set(pos) <= self._covered:
                return  # idempotent re-land
            if (
                len(pos) != int(embeds.shape[0])
                or not set(pos) <= self._expected_set
            ):
                # Encoder and service disagree on media-token layout —
                # fail rather than pair mismatched arrays (an embeds/
                # positions desync would crash the engine step).
                self._failed_msg = (
                    f"media chunk desync: {len(pos)} positions vs "
                    f"{int(embeds.shape[0])} rows (or positions outside "
                    "the request's placeholders)"
                )
            else:
                emb = np.asarray(embeds, np.float32)
                fresh = [
                    i for i, p in enumerate(pos) if p not in self._covered
                ]
                if len(fresh) != len(pos):
                    # Partial overlap — a monolithic fallback landing
                    # after SOME items already streamed. Keep only the
                    # uncovered rows: appending wholesale would put the
                    # overlapped positions into assembled() twice, and
                    # duplicate mm_positions desync the mrope span/grid
                    # walk (and inflate the executor's media bucket).
                    pos = [pos[i] for i in fresh]
                    emb = emb[fresh]
                self._items.append((pos, emb))
                self._covered.update(pos)
                if self._covered == self._expected_set:
                    self._complete = True
                    self.complete_ts = time.monotonic()
                    done = self._on_complete
        if done is not None:
            try:
                done(self)
            except Exception:
                pass
        if self._on_update is not None:
            self._on_update()

    def fail(self, msg: str) -> None:
        with self._mu:
            if not self._complete and not self._failed_msg:
                self._failed_msg = msg
        if self._on_update is not None:
            self._on_update()

    def note_admitted(self) -> None:
        if self.admitted_ts is None:
            self.admitted_ts = time.monotonic()

    # ---------------------------------------------------- engine facing

    def ready_upto(self, pos_end: int) -> bool:
        """All expected placeholder positions strictly below `pos_end`
        are covered by landed items (the engine asks per prefill chunk)."""
        with self._mu:
            if self._complete:
                return True
            for p in self._expected:
                if p >= pos_end:
                    break
                if p not in self._covered:
                    return False
            return True

    def complete(self) -> bool:
        with self._mu:
            return self._complete

    def failed(self) -> str:
        with self._mu:
            return self._failed_msg

    def expired(self) -> bool:
        with self._mu:
            return not self._complete and time.monotonic() > self._deadline

    def assembled(self) -> Tuple[Optional[np.ndarray], Optional[np.ndarray]]:
        """(embeds [N, D], positions [N]) over landed items, sorted by
        position — what the current prefill chunk may scatter (the
        executor drops positions outside the chunk)."""
        with self._mu:
            if not self._items:
                return None, None
            pos = np.concatenate(
                [np.asarray(p, np.int64) for p, _ in self._items]
            )
            emb = np.concatenate([e for _, e in self._items])
        order = np.argsort(pos, kind="stable")
        return emb[order], pos[order]


class MultimodalMixin:
    # Landed-but-unclaimed media embeddings are reaped after this TTL.
    _MM_IMPORT_TTL_S = 120.0

    def _init_mm(self) -> None:  # graftlint: init-only
        """Multimodal state + instruments (called from InstanceServer
        __init__ after self.metrics exists)."""
        # srid -> (embeds, positions, arrival_ts); legacy monolithic
        # landing table, waited on by _pop_mm_import.
        self._mm_imports: Dict[str, Tuple[Any, List[int], float]] = {}  # guarded by: self._mm_mu
        self._mm_events: Dict[str, threading.Event] = {}  # guarded by: self._mm_mu
        self._mm_mu = threading.Lock()
        # Streamed-handoff state (encoder fabric): srid -> live handle,
        # plus chunks that arrived before the forwarded request did
        # (item_idx, positions, embeds, arrival_ts).
        self._mm_streams: Dict[str, MMStreamHandle] = {}
        self._mm_early: Dict[
            str, List[Tuple[int, List[int], np.ndarray, float]]
        ] = {}
        self._m_mm_reaped = self.metrics.counter(
            "xllm_mm_import_reaped_total",
            "Landed-but-unclaimed media embeddings reaped after the "
            "import TTL (their waiter timed out or its master died "
            "between /encode and the forward)",
        )
        self._m_mm_wait = self.metrics.histogram(
            "xllm_mm_import_wait_ms",
            "Time a forwarded media request waited for its embeddings "
            "(legacy blocking wait, or open->complete on a streamed "
            "session)",
        )
        self._m_mm_sessions = self.metrics.counter(
            "xllm_mm_stream_sessions_total",
            "Encoder->prefill streaming sessions opened (encoder side)",
        )
        self._m_mm_chunks = self.metrics.counter(
            "xllm_mm_stream_chunks_total",
            "Per-item embedding chunks sent on streaming sessions "
            "(encoder side)",
        )
        self._m_mm_chunks_landed = self.metrics.counter(
            "xllm_mm_stream_chunks_landed_total",
            "Per-item embedding chunks landed by /mm/chunk (prefill side)",
        )
        self._m_mm_aborts = self.metrics.counter(
            "xllm_mm_stream_aborts_total",
            "Streaming sessions aborted to the monolithic /mm/import "
            "fallback (encoder side)",
        )
        # Stage-E overlap: fraction of the embedding wait that ran AFTER
        # the text request was already admitted to the engine (prefilling
        # text) — the pipelining the streamed handoff exists to create.
        # Own lock: the on_complete hook may fire from a handler that
        # holds _mm_mu.
        self._mm_overlap_mu = threading.Lock()
        self._mm_overlap_num = 0.0
        self._mm_overlap_den = 0.0
        self.metrics.gauge(
            "xllm_mm_stream_overlap_frac",
            "Fraction of streamed-session embedding wait overlapped with "
            "an already-admitted text prefill (1 = fully hidden)",
        ).set_function(
            lambda: self._mm_overlap_num / max(self._mm_overlap_den, 1e-9)
        )

    def _mm_note_complete(self, handle: MMStreamHandle) -> None:
        """on_complete hook: wait + overlap accounting for one session."""
        now = handle.complete_ts or time.monotonic()
        wait = max(now - handle.created_ts, 0.0)
        self._m_mm_wait.observe(wait * 1000.0)
        if handle.admitted_ts is not None:
            with self._mm_overlap_mu:
                self._mm_overlap_num += max(now - handle.admitted_ts, 0.0)
                self._mm_overlap_den += max(wait, 1e-9)

    def _handle_embeddings(self, h: HttpJsonApi, body: Dict[str, Any]) -> None:
        """Engine-side /v1/embeddings: token id lists in (the service
        tokenizes, same injection contract as generation forwarding),
        mean-pooled normalized hidden-state vectors out. The reference
        rejects this endpoint (service.cpp:441-442) — implementing it
        exceeds parity."""
        token_lists = body.get("token_ids")
        if not isinstance(token_lists, list) or not token_lists or not all(
            isinstance(t, list) and t for t in token_lists
        ):
            h.send_error_json(
                400,
                "token_ids (non-empty list of non-empty id lists) required "
                "— raw text inputs are tokenized by the master service",
            )
            return
        limit = self.cfg.max_seq_len
        too_long = max(len(t) for t in token_lists)
        if too_long > limit:
            h.send_error_json(
                400,
                f"input of {too_long} tokens exceeds max_seq_len {limit}",
            )
            return
        try:
            vecs = self.engine.executor.embed_tokens(token_lists)
        except Exception as e:
            h.send_error_json(500, f"embedding failed: {e}")
            return
        n_tok = sum(len(t) for t in token_lists)
        h.send_json(
            {
                "object": "list",
                "model": body.get("model") or self.cfg.model,
                "data": [
                    {
                        "object": "embedding",
                        "index": i,
                        "embedding": [float(x) for x in vecs[i]],
                    }
                    for i in range(len(token_lists))
                ],
                "usage": {"prompt_tokens": n_tok, "total_tokens": n_tok},
            }
        )

    def _handle_encode(self, h: HttpJsonApi, body: Dict[str, Any]) -> None:
        """ENCODE-instance entry: media parts in, embeddings pushed to the
        prefill peer's /mm/import, ack out (three-stage EPD routing)."""
        import base64

        if not hasattr(self.engine, "encode"):
            h.send_error_json(501, "this instance has no encoder engine")
            return
        srid = body.get("service_request_id", "")
        parts = body.get("parts") or []
        positions = body.get("positions") or []
        target = body.get("target", "")
        if not parts or not target:
            h.send_error_json(400, "parts and target are required")
            return
        vcfg = getattr(self.engine.executor, "cfg", None)  # vision
        acfg = getattr(
            getattr(self.engine, "audio_executor", None), "cfg", None
        )
        decoded = []  # (kind, arr) in part order; kind: img|video|audio
        for p in parts:
            shape = p.get("shape") or []
            if len(shape) == 2:
                # Audio: [num_mel_bins, mel_frames] log-mel features.
                if acfg is None:
                    h.send_error_json(
                        501, "this encoder instance hosts no audio tower"
                    )
                    return
                if shape != [acfg.num_mel_bins, acfg.mel_frames]:
                    h.send_error_json(
                        400,
                        f"audio shape {shape} != encoder input "
                        f"[{acfg.num_mel_bins}, {acfg.mel_frames}]",
                    )
                    return
                kind = "audio"
            elif len(shape) in (3, 4):
                if vcfg is None:
                    h.send_error_json(
                        501, "this encoder instance hosts no vision tower"
                    )
                    return
                S = vcfg.image_size
                is_video = len(shape) == 4
                spatial = shape[1:] if is_video else shape
                if spatial != [S, S, 3] or (
                    is_video and (shape[0] < 2 or shape[0] % 2)
                ):
                    h.send_error_json(
                        400,
                        f"media shape {shape} != encoder input "
                        f"[{S}, {S}, 3] (or [T even, {S}, {S}, 3] for "
                        "video)",
                    )
                    return
                if is_video and (
                    not hasattr(self.engine, "encode_video")
                    or getattr(vcfg, "arch", "")
                    not in ("qwen2vl", "qwen25vl")
                ):
                    # Checked HERE, not at jit-trace time inside the
                    # encode call — a raise escaping the handler tears
                    # down the connection instead of sending this 501
                    # (review finding, r5).
                    h.send_error_json(
                        501,
                        f"this encoder ({getattr(vcfg, 'arch', '?')}) "
                        "has no video path (qwen2vl/qwen25vl towers "
                        "only)",
                    )
                    return
                kind = "video" if is_video else "img"
            else:
                h.send_error_json(400, f"bad media shape {shape}")
                return
            try:
                arr = np.frombuffer(
                    base64.b64decode(p["data"]), np.float32
                ).reshape(shape)
            except Exception as e:
                h.send_error_json(400, f"bad media payload: {e}")
                return
            decoded.append((kind, arr))

        if (
            _encoder_fabric_enabled(getattr(self, "cfg", None))
            and hasattr(self.engine, "encode_media_submit")
            and positions
        ):
            if self._encode_fabric(h, body, decoded, parts):
                return
            # Fabric path declined (unpredictable token layout) — the
            # legacy synchronous path below handles it, errors included.

        # Legacy synchronous path (and the XLLM_ENCODER_FABRIC=0 hatch):
        # contiguous same-kind stills/audio batch through one encode
        # call; videos encode per part (token count varies with frames).
        # Outputs map back through EXPLICIT source indices — flush-order
        # bookkeeping must never reorder embeddings when kinds interleave
        # (audio<->image), or every item after the first boundary binds
        # to the wrong placeholder span.
        chunks: list = [None] * len(decoded)
        batch: list = []
        batch_idx: list = []
        batch_kind = ""

        def flush():
            nonlocal batch_kind
            if batch:
                fn = (
                    self.engine.encode_audio if batch_kind == "audio"
                    else self.engine.encode
                )
                out = fn(np.stack(batch))  # [B, tokens, D]
                for j, src in enumerate(batch_idx):
                    chunks[src] = out[j]
                batch.clear()
                batch_idx.clear()
            batch_kind = ""

        for i, (kind, arr) in enumerate(decoded):
            if kind == "video":
                flush()
                chunks[i] = self.engine.encode_video(arr)  # [N, D]
            else:
                if batch_kind not in ("", kind):
                    flush()
                batch_kind = kind
                batch.append(arr)
                batch_idx.append(i)
        flush()
        flat = np.ascontiguousarray(
            np.concatenate([np.asarray(c).reshape(-1, c.shape[-1])
                            for c in chunks])
        )
        if positions and len(positions) != flat.shape[0]:
            per_part = [
                int(np.asarray(c).reshape(-1, flat.shape[-1]).shape[0])
                for c in chunks
            ]
            h.send_error_json(
                400,
                f"{len(positions)} placeholder positions but the encoder "
                f"produced {flat.shape[0]} media tokens "
                f"(per part: {per_part} — check mm_tokens_per_media and "
                "the video frame counts)",
            )
            return
        try:
            code, resp = post_json(
                target,
                "/mm/import",
                {
                    "service_request_id": srid,
                    "embeds": base64.b64encode(flat.tobytes()).decode(),
                    "count": int(flat.shape[0]),
                    "dim": int(flat.shape[1]),
                    "positions": list(positions),
                },
                timeout=30.0,
            )
        except Exception as e:
            h.send_error_json(502, f"prefill peer unreachable: {e}")
            return
        if code != 200:
            h.send_error_json(502, f"prefill peer rejected embeddings: {resp}")
            return
        h.send_json({"ok": True, "media_tokens": int(flat.shape[0])})

    # ------------------------------------------------------------------ #
    # encoder fabric: cached + batched encode, streamed handoff session
    # ------------------------------------------------------------------ #

    def _mm_expected_counts(self, decoded) -> Optional[List[int]]:
        """Predicted media-token count per decoded item — the same layout
        the service computed placeholders from (scheduler._expand_media),
        so per-item position segments are known BEFORE any tower runs.
        None when a count is unpredictable (unknown tower geometry):
        the caller then declines to stream and the legacy path serves."""
        vcfg = getattr(self.engine.executor, "cfg", None)
        counts: List[int] = []
        for kind, arr in decoded:
            if kind == "audio":
                from xllm_service_tpu.models.audio import audio_out_tokens

                counts.append(audio_out_tokens(int(arr.shape[1])))
            elif kind == "img":
                if vcfg is None:
                    return None
                counts.append(int(vcfg.out_tokens))
            else:  # video: out_tokens per temporal slice
                if vcfg is None:
                    return None
                tps = max(getattr(vcfg, "temporal_patch_size", 2), 1)
                counts.append(int(vcfg.out_tokens) * (int(arr.shape[0]) // tps))
        return counts

    def _encode_fabric(self, h: HttpJsonApi, body, decoded, parts) -> bool:
        """Fabric serve of one /encode: per-item cache/batcher resolution
        (EncoderEngine.encode_media_submit) + a streamed per-item handoff
        session to the prefill peer. Returns False — caller falls back to
        the legacy synchronous path — only when the per-item token layout
        cannot be predicted; once streaming starts, every failure degrades
        INSIDE this method (abort -> monolithic /mm/import push), and the
        response is always sent here."""
        import base64

        from xllm_service_tpu.service.image_processor import (
            media_content_hash,
        )

        srid = body.get("service_request_id", "")
        target = body.get("target", "")
        positions = [int(p) for p in body.get("positions") or []]
        counts = self._mm_expected_counts(decoded)
        if counts is None or sum(counts) != len(positions):
            return False  # legacy path reports layout errors post-encode
        segments: List[List[int]] = []
        off = 0
        for c in counts:
            segments.append(positions[off:off + c])
            off += c

        # Submit EVERY item before waiting on any: a multi-item request
        # batches against itself, and cache hits resolve instantly.
        pendings = []
        for (kind, arr), part in zip(decoded, parts):
            hx = part.get("hash") if isinstance(part, dict) else None
            try:
                key = bytes.fromhex(hx) if hx else None
            except ValueError:
                key = None
            if key is None:
                key = bytes.fromhex(media_content_hash(
                    kind, list(arr.shape), part.get("data", "")
                ))
            pendings.append(self.engine.encode_media_submit(kind, arr, key))

        # Session open: a refused/unreachable peer means no streaming —
        # the monolithic fallback below still delivers.
        epoch = body.get("master_epoch", 0)
        streaming = True
        mm_open: Dict[str, Any] = {
            "service_request_id": srid,
            "items": len(decoded),
            "master_epoch": epoch,
        }
        if isinstance(body.get("trace"), dict):
            # Trace context crosses the encoder->prefill stream plane so
            # the peer's embed landing joins the request's timeline.
            mm_open["trace"] = body["trace"]
        try:
            code, _ = post_json(target, "/mm/open", mm_open, timeout=10.0)
            streaming = code == 200
        except Exception:
            streaming = False
        if streaming:
            self._m_mm_sessions.inc()

        # Sender-side drain state: chunk posts run on the dedicated
        # bounded stream lane (_stream_q) — a stuck peer saturates only
        # that lane and the session degrades to the monolithic push.
        mu = threading.Lock()
        cv = threading.Condition(mu)
        state = {"pending": 0, "failed": ""}

        def _chunk_done(err: str = "") -> None:
            with cv:
                state["pending"] -= 1
                if err and not state["failed"]:
                    state["failed"] = err
                cv.notify_all()

        def _send_chunk(idx: int, seg: List[int], rows: np.ndarray) -> None:
            try:
                faults.point(
                    "mm_handoff.send",
                    instance=self.name, srid=srid, item=idx, peer=target,
                )
                code, resp = post_json(
                    target, "/mm/chunk",
                    {
                        "service_request_id": srid,
                        "item": idx,
                        "positions": seg,
                        "count": int(rows.shape[0]),
                        "dim": int(rows.shape[1]),
                        "embeds": base64.b64encode(
                            np.ascontiguousarray(rows).tobytes()
                        ).decode(),
                    },
                    timeout=30.0,
                )
                _chunk_done("" if code == 200 else f"peer returned {code}: {resp}")
            except Exception as e:  # noqa: BLE001
                _chunk_done(str(e))

        outs: List[Optional[np.ndarray]] = [None] * len(decoded)
        encode_err: Optional[str] = None
        for i, p in enumerate(pendings):
            try:
                out = p.result(timeout=300.0)
            except BaseException as e:  # noqa: BLE001
                encode_err = f"encode failed: {e}"
                break
            rows = np.asarray(out, np.float32).reshape(-1, out.shape[-1])
            outs[i] = rows
            if rows.shape[0] != counts[i]:
                # Predicted layout diverged from the tower — stop
                # streaming; the monolithic fallback's strict count check
                # reports it exactly like the legacy path.
                streaming = False
            if streaming and not state["failed"]:
                with cv:
                    state["pending"] += 1
                try:
                    self._stream_q.put_nowait(
                        lambda i=i, seg=segments[i], rows=rows: (
                            _send_chunk(i, seg, rows)
                        )
                    )
                    self._m_mm_chunks.inc()
                except queue.Full:
                    _chunk_done("stream lane saturated")

        self._span(
            srid, "encoder_batch",
            items=len(decoded), target=target,
            error=encode_err or None,
        )
        if encode_err is not None:
            if streaming:
                try:
                    post_json(
                        target, "/mm/abort",
                        {"service_request_id": srid, "reason": encode_err},
                        timeout=5.0,
                    )
                except Exception:
                    pass
            h.send_error_json(500, encode_err)
            return True

        aborted = False
        if streaming:
            with cv:
                deadline = time.monotonic() + 120.0
                while state["pending"] > 0 and not state["failed"]:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        state["failed"] = "chunk delivery timed out"
                        break
                    cv.wait(timeout=left)
                aborted = bool(state["failed"])
        else:
            aborted = True

        total = int(sum(r.shape[0] for r in outs))
        if not aborted:
            try:
                code, _ = post_json(
                    target, "/mm/commit",
                    {"service_request_id": srid, "count": total},
                    timeout=10.0,
                )
                aborted = code != 200
            except Exception:
                aborted = True
        if aborted:
            # Abort -> monolithic fallback: everything is encoded, so the
            # full push both completes a half-fed stream handle on the
            # peer (idempotent re-lands) and serves the legacy waiter.
            self._m_mm_aborts.inc()
            try:
                post_json(
                    target, "/mm/abort",
                    {"service_request_id": srid,
                     "reason": state["failed"] or "stream fallback"},
                    timeout=5.0,
                )
            except Exception:
                pass
            flat = np.ascontiguousarray(np.concatenate(outs))
            if positions and len(positions) != flat.shape[0]:
                h.send_error_json(
                    400,
                    f"{len(positions)} placeholder positions but the "
                    f"encoder produced {flat.shape[0]} media tokens",
                )
                return True
            try:
                code, resp = post_json(
                    target, "/mm/import",
                    {
                        "service_request_id": srid,
                        "embeds": base64.b64encode(flat.tobytes()).decode(),
                        "count": int(flat.shape[0]),
                        "dim": int(flat.shape[1]),
                        "positions": list(positions),
                    },
                    timeout=30.0,
                )
            except Exception as e:
                h.send_error_json(502, f"prefill peer unreachable: {e}")
                return True
            if code != 200:
                h.send_error_json(
                    502, f"prefill peer rejected embeddings: {resp}"
                )
                return True
        h.send_json({
            "ok": True,
            "media_tokens": total,
            "streamed": not aborted,
        })
        return True

    def _mm_reap_locked(self, now: float) -> int:
        """Drop landed-but-unclaimed embedding state past the import TTL
        (caller holds _mm_mu): monolithic imports whose waiter timed out
        or whose master died between /encode and the forward, early
        chunks whose forward never came, and stream handles that are
        complete/expired with nobody left to claim them. Returns the
        number of REQUESTS reaped (instrumented + logged by callers)."""
        reaped = 0
        stale = [
            s for s, (_, _, ts) in self._mm_imports.items()
            if now - ts > self._MM_IMPORT_TTL_S
        ]
        for s in stale:
            self._mm_imports.pop(s, None)
            self._mm_events.pop(s, None)
            reaped += 1
        for s, chunks in list(self._mm_early.items()):
            if chunks and now - chunks[0][3] > self._MM_IMPORT_TTL_S:
                del self._mm_early[s]
                reaped += 1
        for s, handle in list(self._mm_streams.items()):
            if now - handle.created_ts > self._MM_IMPORT_TTL_S and (
                handle.complete() or handle.expired()
            ):
                # The engine holds its own reference; dropping the table
                # entry only stops NEW chunk landings from finding it.
                del self._mm_streams[s]
                if not handle.complete():
                    reaped += 1
        return reaped

    def _mm_note_reaped(self, n: int) -> None:
        if n:
            self._m_mm_reaped.inc(n)
            logger.warning(
                "instance %s reaped %d unclaimed media-embedding "
                "import(s) past the %.0fs TTL",
                self.name, n, self._MM_IMPORT_TTL_S,
            )

    def _handle_mm_import(self, h: HttpJsonApi, body: Dict[str, Any]) -> None:
        import base64

        srid = body.get("service_request_id", "")
        try:
            count = int(body["count"])
            dim = int(body["dim"])
            embeds = np.frombuffer(
                base64.b64decode(body["embeds"]), np.float32
            ).reshape(count, dim)
            positions = [int(p) for p in body.get("positions", [])]
        except Exception as e:
            h.send_error_json(400, f"bad embeddings payload: {e}")
            return
        now = time.monotonic()
        ev = handle = None
        with self._mm_mu:
            # Reap orphans (a push landing after its waiter timed out, or a
            # master that died between /encode and the forward): without a
            # TTL every such request pins its embedding array forever.
            reaped = self._mm_reap_locked(now)
            handle = self._mm_streams.get(srid)
            if handle is None:
                self._mm_imports[srid] = (embeds, positions, now)
                ev = self._mm_events.setdefault(srid, threading.Event())
        self._mm_note_reaped(reaped)
        if handle is not None:
            # A live stream handle claims the monolithic push directly:
            # this is both the abort fallback (idempotent re-lands of
            # already-streamed items) and a fabric-off encoder feeding a
            # fabric-on prefill.
            handle.land(positions, embeds)
        else:
            ev.set()
        h.send_json({"ok": True})

    def _pop_mm_import(self, srid: str, timeout: float):
        t0 = time.monotonic()
        with self._mm_mu:
            ev = self._mm_events.setdefault(srid, threading.Event())
        ok = ev.wait(timeout)
        self._m_mm_wait.observe((time.monotonic() - t0) * 1000.0)
        if not ok:
            with self._mm_mu:
                self._mm_events.pop(srid, None)
            return None
        with self._mm_mu:
            self._mm_events.pop(srid, None)
            entry = self._mm_imports.pop(srid, None)
            return entry[:2] if entry is not None else None

    # ------------------------------------------------------------------ #
    # streamed handoff, prefill side (/mm/open|chunk|commit|abort)
    # ------------------------------------------------------------------ #

    def _mm_stream_attach(
        self, srid: str, expected_positions: List[int]
    ) -> MMStreamHandle:
        """Create (or return) the stream handle for one forwarded media
        request, folding in chunks — or a whole monolithic import — that
        landed before the forward arrived (the master dispatches the
        encoder CONCURRENTLY with the forward when the fabric is on)."""
        early: List[Tuple[List[int], np.ndarray, float]] = []
        mono = None
        with self._mm_mu:
            handle = self._mm_streams.get(srid)
            if handle is None:
                handle = MMStreamHandle(
                    srid,
                    expected_positions,
                    deadline_s=getattr(
                        self.cfg, "mm_stream_deadline_s", 180.0
                    ),
                    on_update=self._engine_wake,
                    on_complete=self._mm_note_complete,
                )
                self._mm_streams[srid] = handle
                early = self._mm_early.pop(srid, [])
                mono = self._mm_imports.pop(srid, None)
                self._mm_events.pop(srid, None)
        for _item, pos, emb, _ts in early:
            handle.land(pos, emb)
        if mono is not None:
            handle.land(mono[1], mono[0])
        return handle

    def _mm_stream_discard(self, srid: str) -> None:
        with self._mm_mu:
            self._mm_streams.pop(srid, None)

    def _engine_wake(self) -> None:
        """Stream landing -> engine work event: a media request parked at
        a chunk boundary re-checks coverage without the 50ms poll."""
        wake = getattr(self.engine, "wake", None)
        if wake is not None:
            try:
                wake()
            except Exception:
                pass

    def _handle_mm_open(self, h: HttpJsonApi, body: Dict[str, Any]) -> None:
        srid = body.get("service_request_id", "")
        if not srid:
            h.send_error_json(400, "service_request_id required")
            return
        # The handle itself is created by the forwarded request (only it
        # knows the placeholder layout); open proves the peer reachable
        # and un-fenced before the encoder starts streaming.
        h.send_json({"ok": True})

    def _handle_mm_chunk(self, h: HttpJsonApi, body: Dict[str, Any]) -> None:
        import base64

        srid = body.get("service_request_id", "")
        try:
            faults.point(
                "mm_handoff.recv",
                instance=self.name, srid=srid, item=body.get("item", -1),
            )
        except faults.FaultInjected as fi:
            h.send_error_json(503, str(fi))
            return
        try:
            count = int(body["count"])
            dim = int(body["dim"])
            embeds = np.frombuffer(
                base64.b64decode(body["embeds"]), np.float32
            ).reshape(count, dim)
            positions = [int(p) for p in body.get("positions", [])]
        except Exception as e:
            h.send_error_json(400, f"bad chunk payload: {e}")
            return
        now = time.monotonic()
        handle = None
        stashed = True
        with self._mm_mu:
            reaped = self._mm_reap_locked(now)
            handle = self._mm_streams.get(srid)
            if handle is None:
                # Chunk beat the forwarded request here: stash until the
                # serving thread attaches (bounded per srid; TTL-reaped).
                stash = self._mm_early.setdefault(srid, [])
                if len(stash) < 64:
                    stash.append((
                        int(body.get("item", len(stash))),
                        positions, embeds, now,
                    ))
                else:
                    stashed = False
        self._mm_note_reaped(reaped)
        if handle is not None:
            handle.land(positions, embeds)
        elif not stashed:
            # Acking a dropped chunk would let the encoder commit a
            # session that can never complete — fail it so the sender
            # aborts to the monolithic /mm/import fallback.
            h.send_error_json(503, "early-chunk stash full")
            return
        self._m_mm_chunks_landed.inc()
        h.send_json({"ok": True})

    def _handle_mm_commit(self, h: HttpJsonApi, body: Dict[str, Any]) -> None:
        srid = body.get("service_request_id", "")
        ev = None
        with self._mm_mu:
            handle = self._mm_streams.get(srid)
            if handle is None:
                early = self._mm_early.pop(srid, [])
                if early:
                    # No stream handle will ever attach (this prefill
                    # runs the legacy blocking path — hatch mismatch
                    # across instances, or the forward died): assemble
                    # the committed items into a monolithic import so a
                    # blocked `_pop_mm_import` waiter still gets served.
                    early.sort(key=lambda t: t[0])
                    positions = [p for _i, ps, _e, _t in early for p in ps]
                    embeds = np.concatenate([e for _i, _p, e, _t in early])
                    self._mm_imports[srid] = (
                        embeds, positions, time.monotonic()
                    )
                    ev = self._mm_events.setdefault(
                        srid, threading.Event()
                    )
        if ev is not None:
            ev.set()
            h.send_json({"ok": True, "assembled": True})
            return
        if handle is not None and not handle.complete():
            # Every chunk was acked before the encoder committed, so an
            # incomplete handle here means landings were lost — fail fast
            # rather than hold the engine to the deadline. The encoder's
            # commit failure path then pushes the monolithic fallback
            # (which un-fails nothing: the engine already rejected).
            handle.fail("mm commit before full item coverage")
            h.send_error_json(409, "commit before full item coverage")
            return
        h.send_json({"ok": True})

    def _handle_mm_abort(self, h: HttpJsonApi, body: Dict[str, Any]) -> None:
        # Advisory: the encoder degrades to the monolithic /mm/import
        # push, which completes the handle; only the deadline kills it.
        h.send_json({"ok": True, "aborted": True})
