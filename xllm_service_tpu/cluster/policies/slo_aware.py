"""SLO-aware routing with dynamic PD-ratio flipping.

Reference: loadbalance_policy/slo_aware_policy.cpp:26-39 delegating to
InstanceMgr::select_instance_pair_on_slo (instance_mgr.cpp:656-757);
targets from --target_ttft / --target_tpot (global_gflags.cpp:102-112).
The policy predicts TTFT/TPOT per candidate from each instance's fitted
profiling curves, dispatches to the first instance meeting targets, spills
prefill work onto idle decode instances, and flips MIX instance roles to
rebalance the prefill:decode ratio under sustained pressure.
"""

from __future__ import annotations

from typing import Sequence

from xllm_service_tpu.cluster.instance_mgr import InstanceMgr
from xllm_service_tpu.cluster.policies.base import LoadBalancePolicy
from xllm_service_tpu.common.types import Routing


class SloAwarePolicy(LoadBalancePolicy):
    def __init__(
        self,
        instance_mgr: InstanceMgr,
        target_ttft_ms: float = 1000.0,
        target_tpot_ms: float = 50.0,
    ) -> None:
        self._instance_mgr = instance_mgr
        self.target_ttft_ms = target_ttft_ms
        self.target_tpot_ms = target_tpot_ms

    def select_instances_pair(
        self, token_ids: Sequence[int], scores=None
    ) -> Routing:
        return self._instance_mgr.select_instance_pair_on_slo(
            len(token_ids), self.target_ttft_ms, self.target_tpot_ms
        )
