"""Cache-aware routing (CAR): prefix-cache-affinity pair selection.

Reference: loadbalance_policy/cache_aware_routing.{h,cpp} — the "KV Cache
aware routing" release feature. Per candidate:

    score = matched_blocks / total_blocks
          - gpu_cache_usage_perc
          - waiting_requests / max_waiting_requests        (cost_function :59-85)

with DRAM/SSD matches discounted (they require a tier fetch before reuse).
Deliberate divergence: the reference computes the first and third terms with
*integer* division, truncating both to 0 for every partial value
(cache_aware_routing.cpp:73-78) — scoring degenerates to cache-usage only.
Here all terms are float, so the feature works as designed.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from xllm_service_tpu.cluster.global_kvcache_mgr import GlobalKVCacheMgr
from xllm_service_tpu.cluster.instance_mgr import InstanceMgr
from xllm_service_tpu.cluster.policies.base import LoadBalancePolicy
from xllm_service_tpu.common.types import LoadMetrics, OverlapScores, Routing

# Tier weights for matched blocks: HBM reuse is free, DRAM needs a
# host->device copy, SSD a disk read first.
_TIER_WEIGHTS = (("hbm_scores", 1.0), ("dram_scores", 0.5), ("ssd_scores", 0.25))


class CacheAwareRouting(LoadBalancePolicy):
    def __init__(
        self,
        instance_mgr: InstanceMgr,
        kvcache_mgr: GlobalKVCacheMgr,
        fabric=None,
    ) -> None:
        self._instance_mgr = instance_mgr
        self._kvcache_mgr = kvcache_mgr
        # Prefix KV fabric (cluster/prefix_fabric.py): when present, the
        # affinity term scores EFFECTIVE matched blocks after a peer
        # fetch (local overlap + fetchable-from-the-best-holder blocks
        # discounted by fetch cost) instead of raw local overlap — a
        # loaded holder can lose to a lightly loaded cheap-fetch peer on
        # the merits instead of by accident.
        self._fabric = fabric

    def _score(
        self,
        name: str,
        scores: OverlapScores,
        load: Dict[str, LoadMetrics],
        max_waiting: int,
    ) -> float:
        if self._fabric is not None:
            matched = self._fabric.effective_matched(name, scores)
        else:
            matched = 0.0
            for attr, w in _TIER_WEIGHTS:
                matched += getattr(scores, attr).get(name, 0) * w
        affinity = matched / scores.total_blocks if scores.total_blocks else 0.0
        m = load.get(name, LoadMetrics())
        waiting = m.waiting_requests_num / max_waiting if max_waiting else 0.0
        return affinity - m.gpu_cache_usage_perc - waiting

    def _pick(
        self,
        candidates: List[str],
        scores: OverlapScores,
        load: Dict[str, LoadMetrics],
        max_waiting: int,
    ) -> str:
        if not candidates:
            return ""
        best, best_score = candidates[0], float("-inf")
        for name in candidates:
            s = self._score(name, scores, load, max_waiting)
            if s > best_score:
                best, best_score = name, s
        return best

    def select_instances_pair(
        self, token_ids: Sequence[int], scores=None
    ) -> Routing:
        if scores is None:
            scores = self._kvcache_mgr.match(token_ids)
        load = self._instance_mgr.get_load_metrics()
        max_waiting = max(
            (m.waiting_requests_num for m in load.values()), default=0
        )
        # Health-filtered candidates: the breaker's ejected instances are
        # excluded; suspect ones only surface when nothing healthier exists.
        prefill = self._pick(
            self._instance_mgr.routable_prefill_instances(),
            scores, load, max_waiting,
        )
        decode = self._pick(
            self._instance_mgr.routable_decode_instances(),
            scores, load, max_waiting,
        )
        if not prefill and not decode:
            return self._instance_mgr.get_next_instance_pair()
        return Routing(
            prefill_name=prefill or decode, decode_name=decode or prefill
        )
