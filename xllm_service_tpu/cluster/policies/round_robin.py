"""Round-robin pair selection (reference: loadbalance_policy/round_robin.cpp:20-22,
delegating to InstanceMgr::get_next_instance_pair)."""

from __future__ import annotations

from typing import Sequence

from xllm_service_tpu.cluster.instance_mgr import InstanceMgr
from xllm_service_tpu.cluster.policies.base import LoadBalancePolicy
from xllm_service_tpu.common.types import Routing


class RoundRobinPolicy(LoadBalancePolicy):
    def __init__(self, instance_mgr: InstanceMgr) -> None:
        self._instance_mgr = instance_mgr

    def select_instances_pair(
        self, token_ids: Sequence[int], scores=None
    ) -> Routing:
        return self._instance_mgr.get_next_instance_pair()
