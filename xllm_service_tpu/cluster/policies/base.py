"""Policy interface + factory.

Reference: loadbalance_policy.h:25-36 (`select_instances_pair(request)`)
and the flag-driven construction in the scheduler ctor
(scheduler.cpp:50-57, --load_balance_policy = RR | CAR | SLO_AWARE).
"""

from __future__ import annotations

from typing import Sequence

from xllm_service_tpu.common.types import Routing


class LoadBalancePolicy:
    def select_instances_pair(
        self, token_ids: Sequence[int], scores=None
    ) -> Routing:
        """Choose the (prefill, decode) pair for one request given its
        pre-tokenized prompt. `scores` is an optional precomputed
        GlobalKVCacheMgr.match() result — the scheduler computes it once
        per request and shares it with the fabric's fetch planner, so
        cache-aware policies must not re-hash the prompt when given it
        (non-cache policies ignore it)."""
        raise NotImplementedError


def make_policy(
    name: str,
    instance_mgr,
    kvcache_mgr=None,
    target_ttft_ms: float = 1000.0,
    target_tpot_ms: float = 50.0,
    fabric=None,
) -> LoadBalancePolicy:
    from xllm_service_tpu.cluster.policies.cache_aware import CacheAwareRouting
    from xllm_service_tpu.cluster.policies.round_robin import RoundRobinPolicy
    from xllm_service_tpu.cluster.policies.slo_aware import SloAwarePolicy

    key = name.upper()
    if key in ("RR", "ROUND_ROBIN"):
        return RoundRobinPolicy(instance_mgr)
    if key in ("CAR", "CACHE_AWARE"):
        if kvcache_mgr is None:
            raise ValueError("CAR policy requires a GlobalKVCacheMgr")
        return CacheAwareRouting(instance_mgr, kvcache_mgr, fabric=fabric)
    if key == "SLO_AWARE":
        return SloAwarePolicy(instance_mgr, target_ttft_ms, target_tpot_ms)
    raise ValueError(f"unknown load_balance_policy {name!r}")
