"""Load-balance policies (reference: xllm_service/scheduler/loadbalance_policy/)."""

from xllm_service_tpu.cluster.policies.base import LoadBalancePolicy, make_policy
from xllm_service_tpu.cluster.policies.cache_aware import CacheAwareRouting
from xllm_service_tpu.cluster.policies.round_robin import RoundRobinPolicy
from xllm_service_tpu.cluster.policies.slo_aware import SloAwarePolicy

__all__ = [
    "LoadBalancePolicy",
    "make_policy",
    "CacheAwareRouting",
    "RoundRobinPolicy",
    "SloAwarePolicy",
]
