"""Fleet-wide prefix KV fabric: the cluster plane that MOVES cached blocks.

`GlobalKVCacheMgr` knows where every committed prefix block lives, and
`CacheAwareRouting` steers requests toward holders — but until this module
a hit was only usable if routing happened to land the request on the one
instance holding the blocks. The fabric turns the per-instance caches into
one content-addressed store (ROADMAP item 3; P/D-Serve, arXiv 2408.08147,
is the at-scale reference for weighing global reuse against load):

  * **Peer prefix fetch** — at dispatch the master attaches a `kv_fabric`
    hint ({holder, addr, blocks}) when the fleet-wide best match beats the
    routed instance's own; the instance pulls the missing blocks from the
    holder over `POST /kv/fetch` (api/instance_fabric.py) and lands them
    content-addressed, OVERLAPPED with chunked prefill of the uncovered
    tail (the engine re-matches at every chunk boundary —
    InferenceEngine._extend_midchunk_match). Any failure, timeout, or
    fault-injection hit degrades to plain recompute — never to an error.
  * **Coordinated multi-tier eviction** — before an instance drops the
    LAST fleet replica of a block from its coldest tier, it asks the
    master (`/rpc/fabric/evict_offer` -> `evict_decisions` here) whether
    to re-home the block on an under-utilized peer's host tier or let it
    die with an index retraction. Hot shared prefixes survive local
    pressure; cold ones die fleet-wide.
  * **Hit-aware admission** — `CacheAwareRouting` scores candidates by
    `effective_matched` (local matched + fetchable-from-a-peer blocks
    discounted by fetch cost) instead of raw overlap, so routing can
    prefer a loaded holder or a cheap-fetch peer on the merits.

Escape hatch: `XLLM_PREFIX_FABRIC=1|0` overrides the config flags either
way, read per call so it can flip on a live cluster. Wire protocol +
fallback matrix: docs/KV_CACHE.md.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, List, Optional, Sequence

from xllm_service_tpu.common.types import OverlapScores

logger = logging.getLogger(__name__)

# Tier weights for matched blocks (shared with CacheAwareRouting): HBM
# reuse is free, DRAM needs a host->device copy, SSD a disk read first.
TIER_WEIGHTS = (("hbm_scores", 1.0), ("dram_scores", 0.5), ("ssd_scores", 0.25))

# A block fetched from a peer is worth this fraction of a local HBM hit in
# the routing score: the fetch pays one control round-trip + a bulk copy,
# recompute pays a full forward pass — cheaper, but not free.
FETCH_DISCOUNT = 0.6

# Don't plan a fetch for less than this many blocks (the control round-trip
# would cost more than the recompute it saves).
MIN_FETCH_BLOCKS = 1

# Eviction re-homing only targets peers with KV headroom: offering blocks
# to a peer above this usage would just trigger ITS evictions.
PEER_USAGE_CEILING = 0.85


def fabric_enabled(cfg=None) -> bool:
    """The escape hatch: XLLM_PREFIX_FABRIC=1|0 overrides the config flag
    either way. Read per call so the hatch can flip on a live process."""
    env = os.environ.get("XLLM_PREFIX_FABRIC", "")
    if env == "1":
        return True
    if env == "0":
        return False
    return bool(getattr(cfg, "enable_prefix_fabric", True))


def weighted_matched(scores: OverlapScores, name: str) -> float:
    """Tier-weighted matched-block score for one instance."""
    total = 0.0
    for attr, w in TIER_WEIGHTS:
        total += getattr(scores, attr).get(name, 0) * w
    return total


class PrefixFabric:
    """Master-side fabric coordinator: fetch planning, fetch-cost-adjusted
    routing scores, and multi-tier eviction decisions. Owned by the
    Scheduler; consulted by `schedule()` (hint), `CacheAwareRouting`
    (scores), and the `/rpc/fabric/evict_offer` RPC (decisions)."""

    def __init__(
        self, config, instance_mgr, kvcache_mgr, metrics=None,
        span_hook=None,
    ):
        self._config = config
        self._instance_mgr = instance_mgr
        self._kvcache_mgr = kvcache_mgr
        self._mu = threading.Lock()
        # Distributed tracing: span_hook(srid, stage, **fields) — the
        # master's ring-buffer emit, so fetch-plan decisions land on the
        # same merged timeline the /trace collector assembles.
        self._span_hook = span_hook
        # Fleet-wide prefix hit accounting from the router's vantage: per
        # scheduled request, the fleet-best matched block count over the
        # prompt's total hashable blocks. This is the number the fabric
        # exists to RAISE (routing + fetch turn fleet-visible blocks into
        # served blocks).
        self.fleet_matched_blocks = 0
        self.fleet_total_blocks = 0
        self.plans = 0
        self.evict_sends = 0
        self.evict_drops = 0
        if metrics is not None:
            metrics.gauge(
                "xllm_fleet_prefix_hit_rate",
                "Fleet-wide prefix hit rate at the router: best matched "
                "blocks any instance holds over total prompt blocks, "
                "across scheduled requests",
            ).set_function(
                lambda: self.fleet_matched_blocks
                / max(self.fleet_total_blocks, 1)
            )

    def enabled(self) -> bool:
        return fabric_enabled(self._config)

    # ------------------------------------------------------------- routing

    def _holder_usable(self, name: str) -> bool:
        """A fetch/score holder must still exist and not be ejected (a
        breaker-ejected peer would time the fetch out on every request).
        Ejection/deregistration also prunes its index locations — this
        check covers the heartbeat of staleness in between."""
        from xllm_service_tpu.cluster.instance_mgr import HealthState

        if self._instance_mgr.get_instance(name) is None:
            return False
        return self._instance_mgr.health_state(name) != HealthState.EJECTED

    def effective_matched(self, name: str, scores: OverlapScores) -> float:
        """Matched blocks AFTER a fabric fetch: the candidate's own
        tier-weighted overlap plus what the best usable peer could ship,
        discounted by fetch cost. With the fabric disabled this is exactly
        the raw local overlap."""
        local = weighted_matched(scores, name)
        if not self.enabled():
            return local
        best_other = 0.0
        for other in self._candidate_names(scores):
            if other == name:
                continue
            w = weighted_matched(scores, other)
            if w > best_other and self._holder_usable(other):
                best_other = w
        return local + max(best_other - local, 0.0) * FETCH_DISCOUNT

    @staticmethod
    def _candidate_names(scores: OverlapScores):
        names = set()
        for attr, _ in TIER_WEIGHTS:
            names.update(getattr(scores, attr))
        return names

    # ------------------------------------------------------ fetch planning

    def plan_fetch(
        self,
        token_ids: Sequence[int],
        routed: str,
        scores: Optional[OverlapScores] = None,
        srid: str = "",
    ) -> Optional[Dict]:
        """The `kv_fabric` dispatch hint for one routed request: the best
        usable peer holding more matched blocks than the routed instance,
        or None when routing already landed on (one of) the best holders.
        Also feeds the fleet-wide hit-rate gauge — every scheduled request
        counts, hint or not."""
        if scores is None:
            scores = self._kvcache_mgr.match(token_ids)

        def blocks_held(name: str) -> int:
            # Tiers are DISJOINT per instance (record_updated_kvcaches
            # moves a hash between sets) — a holder's matched count is
            # the SUM across tiers, not the max.
            return sum(
                getattr(scores, attr).get(name, 0) for attr, _ in TIER_WEIGHTS
            )

        best_name, best_w = "", 0.0
        best_blocks = 0
        for name in self._candidate_names(scores):
            w = weighted_matched(scores, name)
            if w > best_w:
                best_name, best_w = name, w
                best_blocks = blocks_held(name)
        with self._mu:
            self.fleet_total_blocks += scores.total_blocks
            self.fleet_matched_blocks += best_blocks
        if not self.enabled():
            return None
        routed_w = weighted_matched(scores, routed)
        routed_blocks = blocks_held(routed)
        if (
            not best_name
            or best_name == routed
            or best_w <= routed_w
            or best_blocks - routed_blocks < MIN_FETCH_BLOCKS
            or not self._holder_usable(best_name)
        ):
            return None
        meta = self._instance_mgr.get_instance(best_name)
        if meta is None:
            return None
        with self._mu:
            self.plans += 1
        if self._span_hook is not None:
            self._span_hook(
                srid, "fabric_plan",
                holder=best_name, blocks=int(best_blocks),
                routed=routed,
            )
        return {
            "holder": best_name,
            "addr": meta.http_address,
            # Fleet-best matched block count: the requester fetches the
            # hash range between its own local match and this bound.
            "blocks": int(best_blocks),
            "total_blocks": int(scores.total_blocks),
        }

    # ------------------------------------------- coordinated eviction tier

    def evict_decisions(
        self, instance: str, hashes: List[bytes]
    ) -> List[Dict]:
        """Per-hash verdicts for an instance about to drop blocks from its
        coldest tier (the `/rpc/fabric/evict_offer` RPC):

          * another instance still holds the block on ANY tier -> "drop"
            (a replica survives; the offerer's removal is just an index
            retraction carried by its next heartbeat);
          * this is the last fleet replica AND an under-utilized peer
            exists -> "send" + {peer, addr} (the offerer POSTs the block
            to the peer's /kv/import; the peer's heartbeat re-indexes it);
          * last replica but no peer has headroom -> "drop" (the block
            dies fleet-wide — it was cold everywhere).
        """
        peer_name, peer_addr = "", ""
        if self.enabled():
            peer_name, peer_addr = self._pick_evict_peer(instance)
        out: List[Dict] = []
        for h in hashes:
            loc = self._kvcache_mgr.lookup(h)
            others = (
                (loc.hbm_instance_set | loc.dram_instance_set
                 | loc.ssd_instance_set) - {instance}
            )
            if others or not peer_name:
                out.append({"action": "drop"})
                with self._mu:
                    self.evict_drops += 1
            else:
                out.append(
                    {"action": "send", "peer": peer_name, "addr": peer_addr}
                )
                with self._mu:
                    self.evict_sends += 1
        return out

    def _pick_evict_peer(self, exclude: str):
        """Least-KV-loaded routable peer with headroom, or ("", "")."""
        load = self._instance_mgr.get_load_metrics()
        candidates = [
            n
            for n in set(
                self._instance_mgr.routable_prefill_instances()
                + self._instance_mgr.routable_decode_instances()
            )
            if n != exclude
        ]
        best, best_usage = "", PEER_USAGE_CEILING
        for n in candidates:
            m = load.get(n)
            usage = m.gpu_cache_usage_perc if m is not None else 0.0
            if usage < best_usage:
                best, best_usage = n, usage
        if not best:
            return "", ""
        meta = self._instance_mgr.get_instance(best)
        if meta is None:
            return "", ""
        return best, meta.http_address
