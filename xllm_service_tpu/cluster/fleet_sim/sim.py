"""Discrete-event fleet simulator driving the REAL master stack.

The fidelity bet (and what separates this from a queueing model): the
object under test is the ACTUAL `Scheduler` — real routing policies,
real prefix index and fetch planner, real breaker and redispatch/resume
machinery, real goodput controller and admission front door — and only
the ENGINES are simulated. Each simulated instance is a registration
record in a real `MemoryStore` plus a two-event service model
(prefill-done at TTFT, decode-done at TTFT + (n-1)*TPOT, both inflated
by instance load and straggler factors). Requests enter through
`scheduler.schedule()` / `record_new_request()` exactly as the HTTP
tier submits them, and tokens return through
`scheduler.handle_generation()` exactly as /rpc/generations pushes
them — so attempt-versioned wire fencing, mid-stream token replay, and
lane-ordered delivery all run for real at 10k+ concurrent streams.

Three clocks, deliberately separate:
  * the SIM clock (`self.now`) — advances event-to-event; injected into
    the scheduler's control plane (instance health, goodput EWMAs,
    admission buckets) via the `Scheduler(clock=...)` seam;
  * the STORE clock — frozen at 0, so the election lease never expires
    under a GIL stall and the single simulated master stays master
    (kills are explicit store deletes, not lease timeouts);
  * wall time — only the real master loop (idled at a huge interval)
    and the lane worker threads see it; the sim calls
    `scheduler.run_master_upkeep()` itself at simulated heartbeat
    cadence.

Instance death is a store DELETE: the real watch fires the real removal
listeners, which redispatch or token-replay-resume every affected
stream — the simulator only stops producing events for the dead
generation and lets wire-id fencing reject the stale ones.

Hatch: XLLM_FLEET_SIM_CAPACITY (per-instance concurrency knee for the
service-time model, default 16; docs/ARCHITECTURE.md).
"""

from __future__ import annotations

import heapq
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from xllm_service_tpu.cluster.fleet_sim.traces import TraceSpec
from xllm_service_tpu.cluster.instance_mgr import instance_key
from xllm_service_tpu.common import faults
from xllm_service_tpu.common.config import ServiceConfig
from xllm_service_tpu.common.hashing import prefix_block_hashes
from xllm_service_tpu.common.types import (
    FinishReason,
    InstanceMetaInfo,
    InstanceType,
    KvCacheEvent,
    LoadMetrics,
    RequestOutput,
    SequenceOutput,
    Status,
    StatusCode,
    Usage,
)
from xllm_service_tpu.coordination.store import MemoryStore
from xllm_service_tpu.service.request import ServiceRequest
from xllm_service_tpu.service.scheduler import Scheduler

# Service-time model: per-request TTFT/TPOT scale linearly past the
# instance's concurrency knee — the simplest model that produces real
# queueing collapse under overload (which is the phenomenon the
# admission A/B and the scenario guards measure).
BASE_TTFT_S = 0.2
BASE_TPOT_S = 0.03
# Prefix-cache hit: prefill shrinks to this fraction when the routed
# instance already holds the request's shared-prefix block.
PREFIX_HIT_TTFT_FRAC = 0.3


def _capacity() -> int:
    try:
        return max(1, int(os.environ.get("XLLM_FLEET_SIM_CAPACITY", "16")))
    except ValueError:
        return 16


class _SimInstance:
    __slots__ = (
        "index", "name", "key", "meta", "alive", "registered",
        "generation", "inflight", "straggler", "groups", "pending_stored",
    )

    def __init__(self, index: int, meta: InstanceMetaInfo) -> None:
        self.index = index
        self.name = meta.name
        self.key = instance_key(meta)
        self.meta = meta
        self.alive = True
        self.registered = False
        self.generation = 0
        self.inflight = 0
        self.straggler = 1.0
        self.groups: set = set()          # prefix groups served (sim model)
        self.pending_stored: set = set()  # block hashes for next heartbeat


class _SimStream:
    """Client-stream stub implementing the ResponseHandler interface
    (write/write_done/finish/finish_with_error). Terminal transitions
    report once into the sim's completion accounting."""

    __slots__ = ("_on_terminal", "_terminal", "error_code")

    def __init__(self, on_terminal: Callable[["_SimStream"], None]) -> None:
        self._on_terminal = on_terminal
        self._terminal = False
        self.error_code: Optional[StatusCode] = None

    def _finish(self) -> None:
        if not self._terminal:
            self._terminal = True
            self._on_terminal(self)

    def write(self, payload) -> bool:
        return True

    def write_done(self) -> bool:
        self._finish()
        return True

    def finish(self, payload) -> bool:
        self._finish()
        return True

    def finish_with_error(self, code, message) -> bool:
        self.error_code = code
        self._finish()
        return True


@dataclass
class SimReport:
    scenario: str = ""
    num_instances: int = 0
    submitted: int = 0
    completed: int = 0
    shed: int = 0
    failed: int = 0
    unrecovered: int = 0
    peak_concurrent: int = 0
    p50_ttft_s: float = 0.0
    p99_ttft_s: float = 0.0
    goodput_tok_s: float = 0.0       # SLO-met generated tokens / sim second
    total_tok_s: float = 0.0         # all generated tokens / sim second
    slo_ttft_s: float = 0.0
    sheds_by_reason: Dict[str, int] = field(default_factory=dict)
    redispatches: int = 0
    resumes: int = 0
    reshape_flips: int = 0
    wanted_instances: Dict[str, int] = field(default_factory=dict)
    sim_duration_s: float = 0.0
    wall_s: float = 0.0
    events: int = 0

    def to_json(self) -> Dict[str, object]:
        return dict(self.__dict__)


class FleetSim:
    """One simulated fleet run (see module docstring). Single-use: build,
    `run(trace)`, read the report, `close()`."""

    def __init__(
        self,
        num_instances: int = 50,
        seed: int = 0,
        policy: str = "",
        admission: bool = False,
        heartbeat_s: float = 3.0,
        slo_ttft_s: float = 30.0,
        config: Optional[ServiceConfig] = None,
        drain_timeout_s: float = 10.0,
    ) -> None:
        self.num_instances = num_instances
        self.seed = seed
        self.heartbeat_s = heartbeat_s
        self.slo_ttft_s = slo_ttft_s
        # No-progress bound on the post-event completion tail: streams
        # still outstanding past it (e.g. their service events were
        # chaos-dropped) report as unrecovered instead of hanging the run.
        self.drain_timeout_s = drain_timeout_s
        self.now = 0.0
        self._rng = random.Random(seed ^ 0x5EED)
        self._events: List = []   # (t, seq, fn) heap
        self._eseq = 0
        self._emu = threading.Lock()
        self._policy = policy

        cfg = config or ServiceConfig()
        cfg.load_balance_policy = policy or cfg.load_balance_policy
        # The real master loop idles on a huge interval; the sim drives
        # run_master_upkeep() itself at simulated heartbeat cadence.
        cfg.heartbeat_interval_s = 3600.0
        cfg.num_ordered_output_streams = 32
        cfg.enable_admission_control = admission
        # acquire() must NEVER park the sim thread in a real wait.
        cfg.admission_queue_timeout_s = 0.0
        self.config = cfg

        # Store clock frozen at 0: the election lease cannot expire, so
        # the simulated master never flaps; instance death is an explicit
        # DELETE, exactly like an etcd lease revoke.
        self.store = MemoryStore(clock=lambda: 0.0)
        self.scheduler = Scheduler(
            cfg, store=self.store, identity="fleet-sim",
            clock=lambda: self.now,
        )
        self._await_master()

        self.instances: Dict[str, _SimInstance] = {}
        self._by_index: List[_SimInstance] = []
        for i in range(num_instances):
            meta = InstanceMetaInfo(
                name=f"sim-{i:03d}",
                rpc_address=f"sim-{i:03d}:1",
                http_address=f"sim-{i:03d}:2",
                model_name="sim-model",
                type=InstanceType.MIX,
                ttft_profiling_data=[
                    (64, BASE_TTFT_S * 1e3), (256, BASE_TTFT_S * 1e3),
                    (1024, BASE_TTFT_S * 1e3),
                ],
                tpot_profiling_data=[
                    (1, 10, BASE_TPOT_S * 1e3), (4, 40, BASE_TPOT_S * 1e3),
                    (8, 100, BASE_TPOT_S * 1e3),
                ],
            )
            inst = _SimInstance(i, meta)
            self.instances[inst.name] = inst
            self._by_index.append(inst)
            self._register(inst)
        self._await_registered()

        # Completion accounting (touched from lane threads).
        self._amu = threading.Lock()
        self.submitted = 0
        self.terminal = 0
        self.failed = 0
        self.shed = 0
        self.inflight_streams = 0
        self.peak_concurrent = 0
        self.ttfts: List[float] = []          # sim-time TTFT per stream
        self._t_submit: Dict[str, float] = {}
        self._slo_tokens = 0
        self._all_tokens = 0

    # ------------------------------------------------------------------ #
    # construction helpers
    # ------------------------------------------------------------------ #

    def _await_master(self, timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.scheduler.master_state == "active":
                return
            time.sleep(0.005)
        raise RuntimeError(
            f"sim master never reconciled "
            f"(state={self.scheduler.master_state})"
        )

    def _register(self, inst: _SimInstance) -> None:
        self.store.set(inst.key, inst.meta.serialize())
        inst.registered = True
        inst.alive = True

    def _await_registered(self, timeout: float = 10.0) -> None:
        mgr = self.scheduler.instance_mgr
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(mgr.list_instances()) >= self.num_instances:
                return
            time.sleep(0.005)
        raise RuntimeError(
            f"only {len(mgr.list_instances())}/{self.num_instances} "
            "instances registered"
        )

    # ------------------------------------------------------------------ #
    # event loop
    # ------------------------------------------------------------------ #

    def _push(self, t: float, fn: Callable[[], None]) -> None:
        with self._emu:
            self._eseq += 1
            heapq.heappush(self._events, (t, self._eseq, fn))

    def _pop(self):
        with self._emu:
            if not self._events:
                return None
            return heapq.heappop(self._events)

    def run(self, trace: TraceSpec) -> SimReport:
        """Execute one scenario to completion and return its report."""
        wall0 = time.monotonic()
        for spec in trace.requests:
            self._push(spec.t, self._make_arrival(spec))
        for act in trace.actions:
            if act.kind == "drain":
                self._push(act.t, self._make_drain(act.instance))
            elif act.kind == "rejoin":
                self._push(act.t, self._make_rejoin(act.instance))
        for idx, factor in trace.straggler_factors.items():
            self._by_index[idx].straggler = factor
        self._push(self.heartbeat_s, self._heartbeat_tick)

        events = 0
        while True:
            item = self._pop()
            if item is None:
                # Heap drained; lane threads may still be delivering the
                # tail — nothing left can create sim work except them.
                if self._drain_lanes():
                    break
                continue
            t, _, fn = item
            self.now = max(self.now, t)
            events += 1
            # Deterministic chaos seam (ONE site): a dropped tick loses
            # this event — the stream it served must be recovered by the
            # real machinery or counted unrecovered, never hang the sim.
            try:
                faults.point("fleet_sim.tick", t=f"{t:.3f}")
            except faults.FaultInjected:
                continue
            fn()

        report = self._report(trace, events)
        report.wall_s = time.monotonic() - wall0
        return report

    def _drain_lanes(self, timeout: Optional[float] = None) -> bool:
        """True when every submitted stream reached a terminal state (or
        no further progress happens within `timeout` real seconds)."""
        if timeout is None:
            timeout = self.drain_timeout_s
        deadline = time.monotonic() + timeout
        last = -1
        while time.monotonic() < deadline:
            with self._amu:
                done = self.terminal + self.shed
                outstanding = self.submitted - done
            with self._emu:
                if self._events:
                    return False  # a lane callback scheduled new work
            if outstanding <= 0:
                return True
            if done != last:
                last = done
                deadline = time.monotonic() + timeout
            time.sleep(0.01)
        return True

    # ------------------------------------------------------------------ #
    # request lifecycle
    # ------------------------------------------------------------------ #

    def _tokens_for(self, spec) -> List[int]:
        if spec.prefix_group >= 0:
            # Shared 1-block (block_size tokens) prefix per group, unique
            # tail — the REAL chained hashing scores these as hits.
            bs = self.config.block_size
            tail = max(spec.prompt_len, bs + 32) - bs
            return [7000 + spec.prefix_group] * bs + [
                self._rng.randint(1, 4096) for _ in range(tail)
            ]
        return [self._rng.randint(1, 4096) for _ in range(spec.prompt_len)]

    def _make_arrival(self, spec) -> Callable[[], None]:
        def arrive() -> None:
            with self._amu:
                self.submitted += 1
                n = self.submitted - (self.terminal + self.shed)
            srid = f"sim-r{self.submitted}"
            req = ServiceRequest(
                service_request_id=srid,
                model="sim-model",
                stream=True,
                max_tokens=spec.gen_len,
                token_ids=self._tokens_for(spec),
                tenant=spec.tenant,
            )
            status = self.scheduler.schedule(req)
            if not status.ok():
                with self._amu:
                    if status.code == StatusCode.RESOURCE_EXHAUSTED:
                        self.shed += 1
                    else:
                        # No routable instance etc: a front-door failure,
                        # terminal for accounting.
                        self.terminal += 1
                        self.failed += 1
                return
            with self._amu:
                self.inflight_streams += 1
                if self.inflight_streams > self.peak_concurrent:
                    self.peak_concurrent = self.inflight_streams
                self._t_submit[srid] = self.now
            stream = _SimStream(lambda s, r=req: self._on_terminal(r, s))
            dispatch = self.scheduler.record_new_request(
                req, stream, None, self._make_dispatch(req, spec),
            )
            try:
                dispatch()
            except Exception:
                if not self.scheduler.redispatch_request(srid):
                    self.scheduler.fail_request(
                        srid, StatusCode.UNAVAILABLE,
                        "sim dispatch failed with no fallback",
                    )
        return arrive

    def _make_dispatch(self, req: ServiceRequest, spec) -> Callable[[], None]:
        def dispatch() -> None:
            name = req.routing.prefill_name
            inst = self.instances.get(name)
            if inst is None or not inst.alive or not inst.registered:
                raise ConnectionError(f"sim instance {name} is down")
            wire = req.wire_srid or req.service_request_id
            gen = inst.generation
            inst.inflight += 1
            cap = _capacity()
            load = 1.0 + inst.inflight / cap
            ttft = BASE_TTFT_S * load * inst.straggler
            if spec.prefix_group >= 0 and spec.prefix_group in inst.groups:
                ttft *= PREFIX_HIT_TTFT_FRAC
            tpot = BASE_TPOT_S * load * inst.straggler
            n_rest = max(spec.gen_len - 1, 0)
            t_first = self.now + ttft
            self._push(t_first, lambda: self._prefill_done(
                req, spec, inst, wire, gen,
            ))
            self._push(t_first + n_rest * tpot, lambda: self._decode_done(
                req, spec, inst, wire, gen,
            ))
        return dispatch

    def _prefill_done(self, req, spec, inst, wire, gen) -> None:
        if not inst.alive or inst.generation != gen:
            return  # dead attempt; recovery machinery owns the stream
        if spec.prefix_group >= 0:
            inst.groups.add(spec.prefix_group)
            bs = self.config.block_size
            inst.pending_stored.update(prefix_block_hashes(
                req.token_ids[:bs], bs, self.config.murmur_hash3_seed,
            ))
        srid = req.service_request_id
        # Sim-time TTFT: recorded once, at the FIRST attempt that delivers.
        with self._amu:
            t0 = self._t_submit.pop(srid, None)
        if t0 is not None:
            ttft = self.now - t0
            with self._amu:
                self.ttfts.append(ttft)
                if ttft <= self.slo_ttft_s:
                    self._slo_tokens += spec.gen_len
                self._all_tokens += spec.gen_len
        self.scheduler.handle_generation(RequestOutput(
            request_id=srid,
            service_request_id=wire,
            status=Status(StatusCode.OK),
            outputs=[SequenceOutput(index=0, text="t", token_ids=[11])],
            finished=False,
        ))

    def _decode_done(self, req, spec, inst, wire, gen) -> None:
        if inst.generation == gen and inst.inflight > 0:
            inst.inflight -= 1
        if not inst.alive or inst.generation != gen:
            return
        n_rest = max(spec.gen_len - 1, 0)
        self.scheduler.handle_generation(RequestOutput(
            request_id=req.service_request_id,
            service_request_id=wire,
            status=Status(StatusCode.OK),
            outputs=[SequenceOutput(
                index=0, text="d" * n_rest, token_ids=[13] * n_rest,
                finish_reason=FinishReason.LENGTH,
            )],
            usage=Usage(
                num_prompt_tokens=len(req.token_ids),
                num_generated_tokens=spec.gen_len,
            ),
            finished=True,
        ))

    def _on_terminal(self, req: ServiceRequest, stream: _SimStream) -> None:
        with self._amu:
            self.terminal += 1
            self.inflight_streams -= 1
            if stream.error_code is not None:
                self.failed += 1
            self._t_submit.pop(req.service_request_id, None)

    # ------------------------------------------------------------------ #
    # fleet actions + heartbeats
    # ------------------------------------------------------------------ #

    def _make_drain(self, idx: int) -> Callable[[], None]:
        def drain() -> None:
            inst = self._by_index[idx]
            if not inst.registered:
                return
            inst.registered = False
            # Generation bump: events produced by attempts routed to the
            # pre-restart incarnation die with it (wire fencing rejects
            # them anyway; this also keeps the inflight gauge honest).
            inst.generation += 1
            inst.inflight = 0
            inst.alive = False
            # The real watch fires the real removal listeners: every
            # affected stream redispatches (pre-token) or token-replay
            # resumes (mid-stream) onto survivors.
            self.store.remove(inst.key)
        return drain

    def _make_rejoin(self, idx: int) -> Callable[[], None]:
        def rejoin() -> None:
            inst = self._by_index[idx]
            if inst.registered:
                return
            inst.generation += 1
            inst.groups.clear()
            inst.pending_stored.clear()
            self._register(inst)
        return rejoin

    def _heartbeat_tick(self) -> None:
        cap = _capacity()
        for inst in self._by_index:
            if not (inst.alive and inst.registered):
                continue
            stored = inst.pending_stored
            inst.pending_stored = set()
            self.scheduler.handle_instance_heartbeat(
                inst.name,
                load_metrics=LoadMetrics(
                    waiting_requests_num=max(inst.inflight - cap, 0),
                    gpu_cache_usage_perc=min(inst.inflight / cap, 1.0),
                ),
                cache_event=(
                    KvCacheEvent(stored_cache=stored) if stored else None
                ),
            )
        self.scheduler.run_master_upkeep()
        # Repush only while OTHER events remain: once arrivals and service
        # completions drain, the tail is lane-thread delivery (wall time,
        # no upkeep needed) — repushing on outstanding>0 would race the
        # lane threads and spin the sim clock forward for nothing.
        with self._emu:
            more = len(self._events) > 0
        if more:
            self._push(self.now + self.heartbeat_s, self._heartbeat_tick)

    # ------------------------------------------------------------------ #
    # reporting / teardown
    # ------------------------------------------------------------------ #

    @staticmethod
    def _pct(sorted_vals: List[float], q: float) -> float:
        if not sorted_vals:
            return 0.0
        i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1)))
        return sorted_vals[i]

    def _report(self, trace: TraceSpec, events: int) -> SimReport:
        sched = self.scheduler
        with self._amu:
            ttfts = sorted(self.ttfts)
            submitted = self.submitted
            terminal = self.terminal
            shed = self.shed
            failed = self.failed
            peak = self.peak_concurrent
            slo_tokens = self._slo_tokens
            all_tokens = self._all_tokens
        duration = max(self.now, trace.duration_s)
        return SimReport(
            scenario=trace.name,
            num_instances=self.num_instances,
            submitted=submitted,
            completed=terminal - failed,
            shed=shed,
            failed=failed,
            unrecovered=max(submitted - terminal - shed, 0),
            peak_concurrent=peak,
            p50_ttft_s=self._pct(ttfts, 0.50),
            p99_ttft_s=self._pct(ttfts, 0.99),
            goodput_tok_s=slo_tokens / duration,
            total_tok_s=all_tokens / duration,
            slo_ttft_s=self.slo_ttft_s,
            sheds_by_reason=dict(sched.admission.sheds),
            redispatches=sched.total_redispatches,
            resumes=sched.total_resumes,
            reshape_flips=sched.goodput.reshape_flips,
            wanted_instances=sched.goodput.wanted_instances(),
            sim_duration_s=duration,
            events=events,
        )

    def close(self) -> None:
        self.scheduler.stop(drain_timeout_s=0.0)
        self.store.close()
