"""Fleet-scale discrete-event simulation harness (docs/FAULT_TOLERANCE.md,
BASELINE.md round 19).

`FleetSim` drives the REAL master stack — the actual `Scheduler` object
with its real routing policies, prefix fabric, breaker, election,
goodput controller, and admission front door — against simulated
instances on a simulated clock, so 50+ instances and 10k+ concurrent
streams run in seconds of wall time. `traces` generates the scenario
request mixes (diurnal / burst / Zipf-prefix / straggler /
rolling-restart) P/D-Serve (arxiv 2408.08147) names as the
fleet-scale failure surfaces; `bench_fleet.py` wraps each in an exit-3
guard.
"""

from xllm_service_tpu.cluster.fleet_sim.sim import FleetSim, SimReport
from xllm_service_tpu.cluster.fleet_sim.traces import (
    FleetAction,
    SimRequestSpec,
    TraceSpec,
    make_trace,
    SCENARIOS,
)

__all__ = [
    "FleetSim",
    "SimReport",
    "FleetAction",
    "SimRequestSpec",
    "TraceSpec",
    "make_trace",
    "SCENARIOS",
]
