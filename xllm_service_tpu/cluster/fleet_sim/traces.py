"""Trace generators for the fleet simulator.

Each generator returns a `TraceSpec`: a deterministic (seeded) arrival
sequence plus a schedule of fleet actions (drain/kill/rejoin) and
per-instance straggler factors. The five scenarios are the fleet-scale
storms P/D-Serve (arxiv 2408.08147) calls out — the ones a 512-stream
bench against 4 instances can never surface:

  diurnal          sinusoidal arrival rate (the day/night swing); the
                   peak must clear 10k concurrent streams
  burst            flat baseline with a 10x arrival spike mid-trace
  zipf_prefix      Zipf-skewed shared prompt prefixes (hot system
                   prompts) — exercises the prefix index + CAR routing
  straggler        uniform load with a few instances serving 6x slow —
                   the p99 killer
  rolling_restart  drain -> kill -> rejoin every instance in sequence
                   while traffic flows; zero streams may drop

Prompt/output lengths are drawn per request; prefix groups share a
block-aligned token prefix so the REAL chained murmur3 block hashing
(common/hashing.py) scores them as cache hits once an instance has
served the group.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Dict, List


@dataclass
class SimRequestSpec:
    t: float                 # arrival, sim seconds from trace start
    tenant: str
    prompt_len: int
    gen_len: int
    prefix_group: int = -1   # -1 = unique prompt, else shared-prefix id


@dataclass
class FleetAction:
    t: float
    kind: str                # "drain" | "rejoin"
    instance: int            # instance index


@dataclass
class TraceSpec:
    name: str
    duration_s: float
    requests: List[SimRequestSpec]
    actions: List[FleetAction] = field(default_factory=list)
    straggler_factors: Dict[int, float] = field(default_factory=dict)
    # Routing policy the scenario exercises (zipf wants CAR).
    policy: str = "RR"


# Tenant mix shared by every scenario: a couple of heavy tenants and a
# tail, so per-tenant admission has real shares to arbitrate.
_TENANTS = (
    ("tenant-a", 0.4),
    ("tenant-b", 0.3),
    ("tenant-c", 0.2),
    ("tenant-d", 0.1),
)


def _pick_tenant(rng: random.Random) -> str:
    x = rng.random()
    acc = 0.0
    for name, w in _TENANTS:
        acc += w
        if x < acc:
            return name
    return _TENANTS[-1][0]


def _lens(rng: random.Random) -> "tuple[int, int]":
    prompt = rng.randint(64, 256)
    gen = rng.randint(16, 96)
    return prompt, gen


def _requests_from_weights(
    num_requests: int, duration_s: float, weights: List[float],
    rng: random.Random, prefix_zipf: float = 0.0, num_groups: int = 0,
) -> List[SimRequestSpec]:
    """Place `num_requests` arrivals over `duration_s` proportionally to
    per-bin `weights` (the rate shape), jittered uniformly inside each
    bin. With prefix_zipf > 0, each request joins prefix group
    ~Zipf(s=prefix_zipf) over `num_groups` groups."""
    total_w = sum(weights) or 1.0
    bin_s = duration_s / len(weights)
    # Zipf CDF over groups (precomputed; group 0 hottest).
    zipf_cdf: List[float] = []
    if prefix_zipf > 0 and num_groups > 0:
        masses = [1.0 / (k ** prefix_zipf) for k in range(1, num_groups + 1)]
        z = sum(masses)
        acc = 0.0
        for m in masses:
            acc += m / z
            zipf_cdf.append(acc)
    out: List[SimRequestSpec] = []
    remaining = num_requests
    for i, w in enumerate(weights):
        in_bin = (
            remaining if i == len(weights) - 1
            else int(round(num_requests * w / total_w))
        )
        in_bin = min(in_bin, remaining)
        remaining -= in_bin
        for _ in range(in_bin):
            t = bin_s * i + rng.random() * bin_s
            prompt, gen = _lens(rng)
            group = -1
            if zipf_cdf:
                x = rng.random()
                for g, c in enumerate(zipf_cdf):
                    if x < c:
                        group = g
                        break
                else:
                    group = num_groups - 1
            out.append(SimRequestSpec(
                t=t, tenant=_pick_tenant(rng),
                prompt_len=prompt, gen_len=gen, prefix_group=group,
            ))
    out.sort(key=lambda r: r.t)
    return out


def diurnal(num_requests: int, duration_s: float, num_instances: int,
            seed: int) -> TraceSpec:
    """Sinusoidal arrival rate: trough at the edges, peak mid-trace at
    ~5x the trough — the compressed day/night swing."""
    rng = random.Random(seed)
    bins = 48
    weights = [
        1.0 + 4.0 * 0.5 * (1.0 - math.cos(2.0 * math.pi * i / bins))
        for i in range(bins)
    ]
    return TraceSpec(
        "diurnal", duration_s,
        _requests_from_weights(num_requests, duration_s, weights, rng),
    )


def burst(num_requests: int, duration_s: float, num_instances: int,
          seed: int) -> TraceSpec:
    """Flat baseline with a 10x spike in the middle fifth of the trace
    (a retry storm / product launch)."""
    rng = random.Random(seed)
    bins = 40
    lo, hi = int(bins * 0.4), int(bins * 0.6)
    weights = [10.0 if lo <= i < hi else 1.0 for i in range(bins)]
    return TraceSpec(
        "burst", duration_s,
        _requests_from_weights(num_requests, duration_s, weights, rng),
    )


def zipf_prefix(num_requests: int, duration_s: float, num_instances: int,
                seed: int) -> TraceSpec:
    """Uniform arrivals, Zipf(1.1)-skewed shared prompt prefixes over 32
    groups: a handful of hot system prompts dominate, so cache-aware
    routing + the prefix index earn their keep (policy=CAR)."""
    rng = random.Random(seed)
    return TraceSpec(
        "zipf_prefix", duration_s,
        _requests_from_weights(
            num_requests, duration_s, [1.0] * 32, rng,
            prefix_zipf=1.1, num_groups=32,
        ),
        policy="CAR",
    )


def straggler(num_requests: int, duration_s: float, num_instances: int,
              seed: int) -> TraceSpec:
    """Uniform arrivals; ~6% of instances serve 6x slow (thermal
    throttling, a bad host, a noisy neighbor)."""
    rng = random.Random(seed)
    n_slow = max(1, num_instances // 16)
    slow = rng.sample(range(num_instances), n_slow)
    return TraceSpec(
        "straggler", duration_s,
        _requests_from_weights(num_requests, duration_s, [1.0] * 32, rng),
        straggler_factors={i: 6.0 for i in slow},
    )


def rolling_restart(num_requests: int, duration_s: float,
                    num_instances: int, seed: int) -> TraceSpec:
    """Uniform arrivals while EVERY instance is drained (deregistered —
    its inflight work transparently redispatches/resumes), then rejoined
    after a grace period, in sequence across the middle 60% of the
    trace. The guard: zero unrecovered streams fleet-wide."""
    rng = random.Random(seed)
    actions: List[FleetAction] = []
    window_start = duration_s * 0.2
    window = duration_s * 0.6
    step = window / num_instances
    grace = step * 0.5
    for i in range(num_instances):
        t = window_start + i * step
        actions.append(FleetAction(t=t, kind="drain", instance=i))
        actions.append(FleetAction(t=t + grace, kind="rejoin", instance=i))
    return TraceSpec(
        "rolling_restart", duration_s,
        _requests_from_weights(num_requests, duration_s, [1.0] * 32, rng),
        actions=actions,
    )


SCENARIOS = {
    "diurnal": diurnal,
    "burst": burst,
    "zipf_prefix": zipf_prefix,
    "straggler": straggler,
    "rolling_restart": rolling_restart,
}


def make_trace(name: str, num_requests: int, duration_s: float,
               num_instances: int, seed: int = 0) -> TraceSpec:
    try:
        gen = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; pick from {sorted(SCENARIOS)}"
        )
    return gen(num_requests, duration_s, num_instances, seed)
