"""Instance registry and lifecycle manager.

TPU-native redesign of the reference's InstanceMgr
(reference: xllm_service/scheduler/managers/instance_mgr.{h,cpp}):
store-prefix discovery with watch-driven register/remove
(instance_mgr.cpp:69-154, 355-526), role index vectors with O(1) swap-pop
maintenance, per-instance TimePredictor / RequestMetrics / LatencyMetrics /
LoadMetrics maps (instance_mgr.h:103-134), round-robin pair selection
(:170-186), SLO-aware pair selection with prefill spill (:656-757), and the
dynamic-PD-ratio role flips (:759-807).

Differences from the reference, on purpose:
  * no brpc channel cache — instance addresses are handed to the API tier
    which keeps its own HTTP connections;
  * heartbeat-staleness pruning is real (the reference plumbs
    --detect_disconnected_instance_interval but never reads it);
  * an ENCODE role index exists for EPD multimodal three-stage routing.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from xllm_service_tpu.cluster.time_predictor import TimePredictor
from xllm_service_tpu.common.types import (
    InstanceMetaInfo,
    InstanceType,
    LatencyMetrics,
    LoadMetrics,
    RequestAction,
    RequestMetrics,
    Routing,
)
from xllm_service_tpu.coordination.store import (
    CoordinationStore,
    EventType,
    WatchEvent,
)

logger = logging.getLogger(__name__)


class HealthState:
    """Per-instance circuit-breaker states (string constants — they label
    metrics and JSON surfaces).

        healthy ──failures──▶ suspect ──more failures──▶ ejected
           ▲                     │                          │
           │◀──success/beat──────┘            /health probe ▼
           └──────────first success────────────────── probation

    healthy/probation route normally; suspect routes only when nothing
    healthier exists; ejected never routes and is re-admitted only
    through an active /health probe.
    """

    HEALTHY = "healthy"
    SUSPECT = "suspect"
    EJECTED = "ejected"
    PROBATION = "probation"


# Numeric encoding for the xllm_instance_health_state gauge.
HEALTH_STATE_VALUES: Dict[str, int] = {
    HealthState.HEALTHY: 0,
    HealthState.SUSPECT: 1,
    HealthState.EJECTED: 2,
    HealthState.PROBATION: 3,
}


class _Health:
    __slots__ = ("state", "consecutive_failures", "last_probe_mono")

    def __init__(self) -> None:
        self.state = HealthState.HEALTHY
        self.consecutive_failures = 0
        self.last_probe_mono = 0.0


# Store key prefixes (reference: instance_mgr.cpp:31-39; ENCODE is new).
INSTANCE_PREFIXES: Dict[InstanceType, str] = {
    InstanceType.DEFAULT: "XLLM:DEFAULT:",
    InstanceType.PREFILL: "XLLM:PREFILL:",
    InstanceType.DECODE: "XLLM:DECODE:",
    InstanceType.MIX: "XLLM:MIX:",
    InstanceType.ENCODE: "XLLM:ENCODE:",
}
LOADMETRICS_PREFIX = "XLLM:LOADMETRICS:"


def instance_key(meta: InstanceMetaInfo) -> str:
    return INSTANCE_PREFIXES[meta.type] + meta.name


class InstanceMgr:
    def __init__(
        self,
        store: CoordinationStore,
        is_master: Callable[[], bool],
        detect_disconnected_interval_s: float = 15.0,
        suspect_failures: int = 2,
        eject_failures: int = 4,
        probe_min_interval_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._store = store
        self._is_master = is_master
        # Injectable monotonic clock (the MemoryStore(clock=...) pattern):
        # heartbeat staleness, prune, and probe rate-limiting all advance
        # on THIS clock, so frozen-clock tests pin every expiry decision
        # and the fleet simulator runs liveness on simulated time.
        self._clock = clock
        self._stale_after_s = detect_disconnected_interval_s
        # Circuit breaker (docs/FAULT_TOLERANCE.md): consecutive
        # dispatch/cancel failures drive healthy -> suspect -> ejected;
        # heartbeat staleness past half the prune interval also suspects.
        self._suspect_failures = max(int(suspect_failures), 1)
        self._eject_failures = max(int(eject_failures), self._suspect_failures)
        self._probe_min_interval_s = probe_min_interval_s
        self._health: Dict[str, _Health] = {}
        # Installed by the Master: meta -> bool active /health probe used
        # to re-admit ejected instances (probation on success).
        self.health_prober: Optional[Callable[[InstanceMetaInfo], bool]] = None
        self.total_ejections = 0
        self.total_probe_recoveries = 0
        self._mu = threading.RLock()
        # Pending (name, attempt) role flips awaiting instance notification.
        self._flip_events: List[Tuple[str, int]] = []
        # Lifetime flip count (events drain; benches/metrics need totals).
        self.total_flips = 0

        self._instances: Dict[str, InstanceMetaInfo] = {}
        # Role indices: name lists with swap-pop removal (reference keeps
        # vectors + per-name positions, instance_mgr.h:109-118).
        self._prefill_index: List[str] = []
        self._decode_index: List[str] = []
        self._encode_index: List[str] = []
        self._mix_index: List[str] = []  # serving BOTH sides at once
        self._index_pos: Dict[str, int] = {}  # name -> position in its index

        self._predictors: Dict[str, TimePredictor] = {}
        self._request_metrics: Dict[str, RequestMetrics] = {}
        self._latency_metrics: Dict[str, LatencyMetrics] = {}
        self._load_metrics: Dict[str, LoadMetrics] = {}  # guarded by: self._mu
        self._heartbeat_ts: Dict[str, float] = {}
        # Last master-flush (epoch, counter) seen per instance: replicas
        # only refresh liveness on PUTs whose stamp advances. The epoch is
        # per-master-process randomness, NOT wall time — cross-host clock
        # comparison would let a skewed old master disable refreshes after
        # failover.
        self._load_flush_seq: Dict[str, Tuple[str, int]] = {}
        self._flush_epoch = uuid.uuid4().hex[:12]
        self._flush_counter = 0
        self._dirty_load: set = set()  # names needing master->store upload

        self._rr_prefill = 0
        self._rr_decode = 0
        self._rr_encode = 0

        # Removal listeners (scheduler re-dispatch, cache-index cleanup).
        # Called OUTSIDE the registry lock with the instance name.
        self._removal_listeners: List[Callable[[str], None]] = []
        # Health-transition listeners: fn(name, new_state), called OUTSIDE
        # the lock whenever the breaker changes an instance's state in
        # record_dispatch_failure (the only entry into EJECTED). The
        # scheduler uses this to prune an ejected instance's KV-index
        # locations so cache-aware routing stops scoring phantom hits.
        self._health_listeners: List[Callable[[str, str], None]] = []

        self._watch_ids: List[int] = []
        for prefix in INSTANCE_PREFIXES.values():
            self._watch_ids.append(
                self._store.add_watch(prefix, self._on_instance_watch)
            )
        # Non-masters learn load metrics via the store (reference adds the
        # LOADMETRICS watch only when not master, instance_mgr.cpp:58-67);
        # the handler itself no-ops on the master, so watching always is safe
        # across master failover.
        self._watch_ids.append(
            self._store.add_watch(LOADMETRICS_PREFIX, self._on_load_watch)
        )
        self._init_from_store()

    def close(self) -> None:
        for wid in self._watch_ids:
            self._store.remove_watch(wid)
        self._watch_ids.clear()

    # ------------------------------------------------------------------ #
    # registration / discovery
    # ------------------------------------------------------------------ #

    def _init_from_store(self) -> None:  # graftlint: init-only
        """Initial prefix scan (reference: InstanceMgr::init,
        instance_mgr.cpp:69-154)."""
        for itype, prefix in INSTANCE_PREFIXES.items():
            for key, raw in self._store.get_prefix(prefix).items():
                try:
                    meta = InstanceMetaInfo.deserialize(raw)
                except Exception:
                    logger.warning("bad instance record at %s", key)
                    continue
                meta.type = itype
                self._register(meta)
        for key, raw in self._store.get_prefix(LOADMETRICS_PREFIX).items():
            name = key[len(LOADMETRICS_PREFIX):]
            try:
                j = json.loads(raw)
                seq = j.pop("_flush_seq", None)
                j.pop("_flushed_at", None)
                if seq is not None:
                    self._load_flush_seq[name] = (str(seq[0]), int(seq[1]))
                self._load_metrics[name] = LoadMetrics.from_json(j)
            except Exception:
                pass

    def _register(self, meta: InstanceMetaInfo) -> None:
        with self._mu:
            existing = meta.name in self._instances
            if existing:
                # Metadata refresh: keep role placement, update payload.
                old = self._instances[meta.name]
                meta.current_type = old.current_type
                self._instances[meta.name] = meta
                self._predictors[meta.name] = TimePredictor(
                    meta.ttft_profiling_data, meta.tpot_profiling_data
                )
                self._heartbeat_ts[meta.name] = self._clock()
                return
            self._instances[meta.name] = meta
            self._predictors[meta.name] = TimePredictor(
                meta.ttft_profiling_data, meta.tpot_profiling_data
            )
            self._request_metrics[meta.name] = RequestMetrics()
            self._latency_metrics[meta.name] = LatencyMetrics()
            self._load_metrics.setdefault(meta.name, LoadMetrics())
            self._heartbeat_ts[meta.name] = self._clock()
            # A fresh registration starts with a clean breaker: the lease
            # write proves the instance is up NOW.
            self._health[meta.name] = _Health()
            role = self._initial_role(meta)
            meta.current_type = role
            self._push_index(meta.name, role)
            logger.info(
                "instance %s registered type=%s role=%s",
                meta.name, meta.type.name, role.name,
            )

    def _initial_role(self, meta: InstanceMetaInfo) -> InstanceType:
        """MIX placement rule: first MIX instance becomes DECODE, later ones
        PREFILL (reference: instance_mgr.cpp:110-127, 429-446); DEFAULT
        instances serve both sides and are indexed as prefill."""
        if meta.type == InstanceType.MIX:
            has_decode = bool(self._decode_index)
            return InstanceType.PREFILL if has_decode else InstanceType.DECODE
        if meta.type in (InstanceType.PREFILL, InstanceType.DECODE,
                         InstanceType.ENCODE):
            return meta.type
        return InstanceType.PREFILL  # DEFAULT

    def _index_for(self, role: InstanceType) -> List[str]:
        return {
            InstanceType.PREFILL: self._prefill_index,
            InstanceType.DECODE: self._decode_index,
            InstanceType.ENCODE: self._encode_index,
            InstanceType.MIX: self._mix_index,
        }[role]

    def _push_index(self, name: str, role: InstanceType) -> None:
        idx = self._index_for(role)
        self._index_pos[name] = len(idx)
        idx.append(name)

    def _pop_index(self, name: str, role: InstanceType) -> None:
        """Swap-pop removal keeping positions dense
        (reference: instance_mgr.cpp:455-523)."""
        idx = self._index_for(role)
        pos = self._index_pos.pop(name, None)
        if pos is None or pos >= len(idx) or idx[pos] != name:
            try:
                pos = idx.index(name)
            except ValueError:
                return
        last = idx.pop()
        if pos < len(idx):
            idx[pos] = last
            self._index_pos[last] = pos

    def add_removal_listener(self, fn: Callable[[str], None]) -> None:
        self._removal_listeners.append(fn)

    def add_health_listener(self, fn: Callable[[str, str], None]) -> None:
        self._health_listeners.append(fn)

    def _notify_health(self, name: str, state: str) -> None:
        for fn in self._health_listeners:
            try:
                fn(name, state)
            except Exception:
                logger.exception("health listener failed for %s", name)

    def _remove(self, name: str) -> None:
        with self._mu:
            meta = self._instances.pop(name, None)
            if meta is None:
                return
            self._pop_index(name, meta.current_type)
            self._predictors.pop(name, None)
            self._request_metrics.pop(name, None)
            self._latency_metrics.pop(name, None)
            self._load_metrics.pop(name, None)
            self._heartbeat_ts.pop(name, None)
            self._dirty_load.discard(name)
            self._health.pop(name, None)
            logger.info("instance %s removed", name)
        for fn in self._removal_listeners:
            try:
                fn(name)
            except Exception:
                logger.exception("removal listener failed for %s", name)
        if self._is_master():
            # Clean the replicated load-metrics record for departed
            # instances (reference marks names for LOADMETRICS cleanup).
            try:
                self._store.remove(LOADMETRICS_PREFIX + name)
            except Exception:
                pass

    def _on_instance_watch(self, events: List[WatchEvent]) -> None:
        """Watch-driven registry maintenance
        (reference: update_instance_metainfo, instance_mgr.cpp:355-526)."""
        for ev in events:
            prefix, itype = next(
                ((p, t) for t, p in INSTANCE_PREFIXES.items()
                 if ev.key.startswith(p)),
                (None, None),
            )
            if prefix is None:
                continue
            name = ev.key[len(prefix):]
            if ev.type == EventType.PUT:
                try:
                    meta = InstanceMetaInfo.deserialize(ev.value)
                except Exception:
                    logger.warning("bad instance PUT for %s", name)
                    continue
                meta.type = itype
                meta.name = meta.name or name
                self._register(meta)
            else:
                self._remove(name)

    def _on_load_watch(self, events: List[WatchEvent]) -> None:
        """Replicated load metrics for non-master replicas
        (reference: update_load_metrics, instance_mgr.cpp:528-569)."""
        if self._is_master():
            return
        with self._mu:
            for ev in events:
                name = ev.key[len(LOADMETRICS_PREFIX):]
                if ev.type == EventType.PUT:
                    try:
                        j = json.loads(ev.value)
                        seq = j.pop("_flush_seq", None)
                        j.pop("_flushed_at", None)  # legacy stamp
                        self._load_metrics[name] = LoadMetrics.from_json(j)
                        # A replicated metrics PUT proves the instance was
                        # alive at the master's flush — refresh liveness so a
                        # newly-promoted master does not mass-evict on its
                        # first prune_disconnected pass. Only a PUT whose
                        # flush sequence ADVANCES counts (same-epoch replays
                        # of stale data must not extend a dead instance's
                        # life); a new epoch — master failover — always
                        # counts, and unstamped records (older writers)
                        # refresh unconditionally.
                        prev = self._load_flush_seq.get(name)
                        fresh = True
                        if seq is not None:
                            epoch, counter = str(seq[0]), int(seq[1])
                            if prev is not None and prev[0] == epoch:
                                fresh = counter > prev[1]
                            self._load_flush_seq[name] = (epoch, counter)
                        if name in self._instances and fresh:
                            self._heartbeat_ts[name] = self._clock()
                    except Exception:
                        pass
                else:
                    self._load_metrics.pop(name, None)
                    self._load_flush_seq.pop(name, None)

    # ------------------------------------------------------------------ #
    # accessors
    # ------------------------------------------------------------------ #

    def get_instance(self, name: str) -> Optional[InstanceMetaInfo]:
        with self._mu:
            return self._instances.get(name)

    def list_instances(self) -> List[InstanceMetaInfo]:
        with self._mu:
            return list(self._instances.values())

    def counts(self) -> Tuple[int, int, int]:
        """(num_prefill, num_decode, num_encode) by current role."""
        with self._mu:
            return (
                len(self._prefill_index),
                len(self._decode_index),
                len(self._encode_index),
            )

    def role_census(self) -> Dict[str, int]:
        """Per-role instance counts by CURRENT serving role, including the
        MIX serving role (which `counts()` predates and must not grow —
        callers pattern-match its 3-tuple)."""
        with self._mu:
            return {
                "prefill": len(self._prefill_index),
                "decode": len(self._decode_index),
                "encode": len(self._encode_index),
                "mix": len(self._mix_index),
            }

    def prefill_instances(self) -> List[str]:
        with self._mu:
            return list(self._prefill_index)

    def decode_instances(self) -> List[str]:
        with self._mu:
            return list(self._decode_index)

    def encode_instances(self) -> List[str]:
        with self._mu:
            return list(self._encode_index)

    def mix_instances(self) -> List[str]:
        with self._mu:
            return list(self._mix_index)

    def get_time_predictor(self, name: str) -> Optional[TimePredictor]:
        with self._mu:
            return self._predictors.get(name)

    def get_request_metrics(self, name: str) -> Optional[RequestMetrics]:
        with self._mu:
            return self._request_metrics.get(name)

    def get_latency_metrics(self, name: str) -> Optional[LatencyMetrics]:
        with self._mu:
            return self._latency_metrics.get(name)

    # ------------------------------------------------------------------ #
    # health circuit breaker
    # ------------------------------------------------------------------ #

    def health_state(self, name: str) -> str:
        with self._mu:
            h = self._health.get(name)
            return h.state if h is not None else HealthState.HEALTHY

    def health_states(self) -> Dict[str, str]:
        with self._mu:
            return {n: h.state for n, h in self._health.items()}

    def record_dispatch_success(self, name: str) -> None:
        """A control-plane call to the instance succeeded: close the
        breaker (probation's first success graduates to healthy)."""
        with self._mu:
            h = self._health.get(name)
            if h is None:
                return
            h.consecutive_failures = 0
            if h.state != HealthState.HEALTHY:
                logger.info(
                    "instance %s breaker %s -> healthy", name, h.state
                )
                h.state = HealthState.HEALTHY

    def record_dispatch_failure(self, name: str) -> str:
        """One consecutive dispatch/cancel failure. Returns the resulting
        state. A failure during probation re-ejects immediately (the probe
        lied); otherwise the suspect/eject thresholds apply."""
        with self._mu:
            h = self._health.get(name)
            if h is None:
                return HealthState.HEALTHY
            h.consecutive_failures += 1
            prev = h.state
            if prev == HealthState.PROBATION:
                h.state = HealthState.EJECTED
            elif h.consecutive_failures >= self._eject_failures:
                h.state = HealthState.EJECTED
            elif h.consecutive_failures >= self._suspect_failures:
                if prev == HealthState.HEALTHY:
                    h.state = HealthState.SUSPECT
            state = h.state
            if state != prev:
                logger.warning(
                    "instance %s breaker %s -> %s (%d consecutive failures)",
                    name, prev, state, h.consecutive_failures,
                )
                if state == HealthState.EJECTED:
                    self.total_ejections += 1
                    h.last_probe_mono = 0.0  # probe as soon as possible
        if state != prev:
            self._notify_health(name, state)
        return state

    def _beat_observed(self, name: str) -> None:
        """A live heartbeat clears staleness-driven suspicion (failure-
        driven suspicion clears only through dispatch success)."""
        h = self._health.get(name)
        if (
            h is not None
            and h.state == HealthState.SUSPECT
            and h.consecutive_failures < self._suspect_failures
        ):
            h.state = HealthState.HEALTHY

    def mark_stale_suspects(self) -> List[str]:
        """Pre-prune staleness signal: an instance silent for half the
        prune interval turns suspect (routing avoids it) well before the
        prune backstop removes it."""
        now = self._clock()
        marked: List[str] = []
        with self._mu:
            for name, ts in self._heartbeat_ts.items():
                h = self._health.get(name)
                if (
                    h is not None
                    and h.state == HealthState.HEALTHY
                    and now - ts > self._stale_after_s * 0.5
                ):
                    h.state = HealthState.SUSPECT
                    marked.append(name)
        for name in marked:
            logger.warning("instance %s suspect: heartbeats stale", name)
        return marked

    def probe_unhealthy(self) -> int:
        """Active breaker drive: fire a /health probe (the installed
        health_prober) at each non-healthy instance at most once per
        probe_min_interval_s. A routing-avoided suspect would otherwise
        never see the traffic that could heal OR convict it — the probe
        supplies that evidence: suspect + probe ok -> healthy, suspect +
        probe failure -> one more consecutive failure (escalating to
        ejected); ejected + probe ok -> probation. Probes run on daemon
        threads so a dead endpoint's connect timeout never blocks the
        master loop. Returns the number of probes launched."""
        prober = self.health_prober
        if prober is None:
            return 0
        now = self._clock()
        due: List[InstanceMetaInfo] = []
        with self._mu:
            for name, h in self._health.items():
                if h.state not in (HealthState.EJECTED, HealthState.SUSPECT):
                    continue
                if now - h.last_probe_mono < self._probe_min_interval_s:
                    continue
                meta = self._instances.get(name)
                if meta is None:
                    continue
                h.last_probe_mono = now
                due.append(meta)
        for meta in due:
            threading.Thread(
                target=self._probe_one,
                args=(prober, meta),
                name=f"health-probe-{meta.name}",
                daemon=True,
            ).start()
        return len(due)

    def _probe_one(self, prober, meta: InstanceMetaInfo) -> None:
        try:
            ok = bool(prober(meta))
        except Exception:
            ok = False
        escalate = False
        with self._mu:
            h = self._health.get(meta.name)
            if h is None:
                return
            if h.state == HealthState.EJECTED and ok:
                h.state = HealthState.PROBATION
                h.consecutive_failures = 0
                self.total_probe_recoveries += 1
                logger.info(
                    "instance %s /health probe ok: ejected -> probation",
                    meta.name,
                )
            elif h.state == HealthState.SUSPECT:
                if ok:
                    h.state = HealthState.HEALTHY
                    h.consecutive_failures = 0
                    logger.info(
                        "instance %s /health probe ok: suspect -> healthy",
                        meta.name,
                    )
                else:
                    escalate = True
        if escalate:
            self.record_dispatch_failure(meta.name)

    def _routable(self, names: List[str]) -> List[str]:
        """Health filter under self._mu: healthy/probation first; suspect
        only as a last resort; ejected never."""
        good, fallback = [], []
        for n in names:
            h = self._health.get(n)
            state = h.state if h is not None else HealthState.HEALTHY
            if state in (HealthState.HEALTHY, HealthState.PROBATION):
                good.append(n)
            elif state == HealthState.SUSPECT:
                fallback.append(n)
        return good or fallback

    def routable_prefill_instances(self) -> List[str]:
        # MIX-serving instances take work on both sides.
        with self._mu:
            return self._routable(self._prefill_index + self._mix_index)

    def routable_decode_instances(self) -> List[str]:
        with self._mu:
            return self._routable(self._decode_index + self._mix_index)

    # ------------------------------------------------------------------ #
    # routing primitives
    # ------------------------------------------------------------------ #

    def get_next_instance_pair(self) -> Routing:
        """Round-robin prefill+decode pair
        (reference: instance_mgr.cpp:170-186). With no decode instances the
        prefill instance serves both roles (colocated deployment). The
        health breaker filters the candidate lists: ejected instances are
        never picked, suspect ones only when nothing healthier exists."""
        with self._mu:
            routing = Routing()
            prefill = self._routable(self._prefill_index + self._mix_index)
            decode = self._routable(self._decode_index + self._mix_index)
            if prefill:
                routing.prefill_name = prefill[
                    self._rr_prefill % len(prefill)
                ]
                self._rr_prefill += 1
            elif decode:
                routing.prefill_name = decode[
                    self._rr_decode % len(decode)
                ]
            if decode:
                routing.decode_name = decode[
                    self._rr_decode % len(decode)
                ]
                self._rr_decode += 1
            else:
                routing.decode_name = routing.prefill_name
            return routing

    def next_encode_instance(
        self, required=frozenset(), hit_scores=None, exclude=frozenset()
    ) -> str:
        """Pick an ENCODE instance whose advertised modalities cover
        `required` (e.g. {"image"} or {"audio"}). Encoders host ONE
        tower, so modality-blind rotation would 501 half the requests on
        mixed fleets (review finding, r5); instances that advertise
        nothing are legacy wildcards. `exclude` names candidates a
        caller already failed against (encode dispatch re-route).

        With `hit_scores` (encoder fabric, docs/EPD.md: per-instance
        cached-media-item counts from the master's embedding index) the
        pick is SCORED — live encoder queue depth from the last
        heartbeat, minus a bonus per cached item (a hit skips the tower
        dispatch entirely) — instead of round-robin; ties rotate so an
        idle fleet still spreads. Without it (fabric off / text fleets)
        the legacy round-robin is unchanged."""
        from xllm_service_tpu.cluster.encoder_fabric import HIT_WEIGHT

        required = set(required)
        exclude = set(exclude)
        with self._mu:
            candidates = [
                n for n in self._routable(self._encode_index)
                if n not in exclude
                and (
                    not required
                    or not (m := self._instances.get(n)) or not m.modalities
                    or required <= set(m.modalities)
                )
            ]
            if not candidates:
                return ""
            if hit_scores is None:
                name = candidates[self._rr_encode % len(candidates)]
                self._rr_encode += 1
                return name

            def score(n: str) -> float:
                load = self._load_metrics.get(n, LoadMetrics())
                return (
                    load.waiting_requests_num
                    - HIT_WEIGHT * hit_scores.get(n, 0)
                )

            rot = self._rr_encode % len(candidates)
            best = min(
                range(len(candidates)),
                key=lambda i: (
                    score(candidates[i]),
                    (i - rot) % len(candidates),
                ),
            )
            self._rr_encode += 1
            return candidates[best]

    def get_load_metrics(self) -> Dict[str, LoadMetrics]:
        """Snapshot for policy scoring (reference: instance_mgr.cpp:217-286).
        dataclasses.replace copies EVERY field — a positional rebuild
        silently zeroed fields added later (the MoE expert-hotness
        signal, ISSUE 15)."""
        import dataclasses

        with self._mu:
            return {
                n: dataclasses.replace(m)
                for n, m in self._load_metrics.items()
            }

    def least_loaded(self, candidates: List[str]) -> str:
        """Fallback selection by (waiting, cache usage) — the reference's
        least-loaded path inside get_load_metrics. Candidates the breaker
        has ejected are skipped."""
        with self._mu:
            candidates = self._routable(list(candidates))
            best, best_key = "", None
            for name in candidates:
                m = self._load_metrics.get(name, LoadMetrics())
                key = (m.waiting_requests_num, m.gpu_cache_usage_perc)
                if best_key is None or key < best_key:
                    best, best_key = name, key
            return best

    # ------------------------------------------------------------------ #
    # heartbeat-fed state
    # ------------------------------------------------------------------ #

    def record_load_metrics_update(self, name: str, metrics: LoadMetrics) -> None:
        with self._mu:
            if name not in self._instances:
                return
            self._load_metrics[name] = metrics
            self._heartbeat_ts[name] = self._clock()
            self._dirty_load.add(name)
            self._beat_observed(name)

    def update_latency_metrics(self, name: str, metrics: LatencyMetrics) -> None:
        with self._mu:
            if name in self._instances:
                self._latency_metrics[name] = metrics

    def upload_load_metrics(self) -> int:
        """Master-only flush of dirty load metrics to the store
        (reference: instance_mgr.cpp:299-317). Returns records written."""
        if not self._is_master():
            return 0
        with self._mu:
            dirty = {
                n: self._load_metrics[n].to_json()
                for n in self._dirty_load
                if n in self._load_metrics
            }
            self._dirty_load.clear()
        self._flush_counter += 1
        for name, j in dirty.items():
            # The flush sequence rides the record so replicas only refresh
            # liveness on PUTs carrying NEW data — a slow master re-flushing
            # stale metrics must not keep a dead instance alive.
            j["_flush_seq"] = [self._flush_epoch, self._flush_counter]
            self._store.set(LOADMETRICS_PREFIX + name, json.dumps(j))
        return len(dirty)

    def absorb_reconcile(
        self,
        name: str,
        load: Optional[LoadMetrics],
        manifest: List[Dict],
    ) -> None:
        """Takeover reconciliation (docs/FAULT_TOLERANCE.md): a freshly
        elected master rebuilds this instance's request charges from its
        /reconcile manifest instead of starting blind. Each in-flight
        entry re-creates the charge its original SCHEDULE/FINISH_PREFILL
        bookkeeping would have left: zero delivered tokens => queued
        prefill work, delivered tokens => an open decode slot. The
        heartbeat stamp refreshes too — the manifest IS a proof of life,
        and the first post-takeover prune must not evict a healthy
        instance whose beats went to the dead master."""
        with self._mu:
            if name not in self._instances:
                return
            if load is not None:
                self._load_metrics[name] = load
            self._heartbeat_ts[name] = self._clock()
            rm = RequestMetrics()
            pred = self._predictors.get(name)
            for ent in manifest:
                try:
                    delivered = int(ent.get("delivered_tokens", 0))
                    prompt_toks = int(ent.get("prompt_tokens", 0))
                except (TypeError, ValueError):
                    continue
                if delivered > 0:
                    rm.decode_request_num += 1
                else:
                    rm.prefill_request_num += 1
                    rm.prefill_token_num += prompt_toks
                    if pred is not None and pred.has_ttft_model:
                        rm.estimated_prefill_time += pred.predict_ttft(
                            prompt_toks
                        )
            self._request_metrics[name] = rm
            self._beat_observed(name)

    def prune_disconnected(self) -> List[str]:
        """Drop instances whose heartbeats stopped, master-side backstop to
        store-lease liveness. The reference declares this interval flag but
        never consumes it (master.cpp:193-194) — here it works."""
        now = self._clock()
        stale: List[str] = []
        with self._mu:
            for name, ts in list(self._heartbeat_ts.items()):
                if now - ts > self._stale_after_s:
                    stale.append(name)
        for name in stale:
            meta = self.get_instance(name)
            self._remove(name)
            if meta is not None and self._is_master():
                try:
                    self._store.remove(instance_key(meta))
                except Exception:
                    pass
        return stale

    # ------------------------------------------------------------------ #
    # request-metrics state machine
    # ------------------------------------------------------------------ #

    def update_request_metrics(
        self,
        routing: Routing,
        action: RequestAction,
        num_tokens: int = 0,
    ) -> None:
        """5-action per-instance bookkeeping
        (reference: instance_mgr.cpp:582-654):
        SCHEDULE        -> queued prefill work on the prefill instance;
        FINISH_PREFILL  -> prefill done, decode slot opens on decode instance;
        GENERATE        -> one decode token on the decode instance;
        FINISH_DECODE   -> decode slot closes;
        CANCEL          -> unwind a request cancelled BEFORE FINISH_PREFILL
                           (prefill counters only — its decode slot never
                           opened; post-prefill cancellation must use
                           FINISH_DECODE).
        """
        with self._mu:
            pm = self._request_metrics.get(routing.prefill_name)
            dm = self._request_metrics.get(routing.decode_name)
            if action == RequestAction.SCHEDULE:
                if pm is not None:
                    pm.prefill_request_num += 1
                    pm.prefill_token_num += num_tokens
                    pred = self._predictors.get(routing.prefill_name)
                    if pred is not None and pred.has_ttft_model:
                        pm.estimated_prefill_time += pred.predict_ttft(num_tokens)
            elif action == RequestAction.FINISH_PREFILL:
                if pm is not None:
                    pm.prefill_request_num = max(0, pm.prefill_request_num - 1)
                    pm.prefill_token_num = max(0, pm.prefill_token_num - num_tokens)
                    pred = self._predictors.get(routing.prefill_name)
                    if pred is not None and pred.has_ttft_model:
                        pm.estimated_prefill_time = max(
                            0.0,
                            pm.estimated_prefill_time - pred.predict_ttft(num_tokens),
                        )
                if dm is not None:
                    dm.decode_request_num += 1
            elif action == RequestAction.GENERATE:
                if dm is not None:
                    dm.decode_token_num += num_tokens or 1
            elif action == RequestAction.FINISH_DECODE:
                if dm is not None:
                    dm.decode_request_num = max(0, dm.decode_request_num - 1)
            elif action == RequestAction.CANCEL:
                if pm is not None and pm.prefill_request_num > 0:
                    pm.prefill_request_num -= 1
                    pm.prefill_token_num = max(0, pm.prefill_token_num - num_tokens)
                    pred = self._predictors.get(routing.prefill_name)
                    if pred is not None and pred.has_ttft_model:
                        pm.estimated_prefill_time = max(
                            0.0,
                            pm.estimated_prefill_time
                            - pred.predict_ttft(num_tokens),
                        )

    # ------------------------------------------------------------------ #
    # SLO-aware selection + dynamic PD ratio
    # ------------------------------------------------------------------ #

    def select_instance_pair_on_slo(
        self,
        prompt_len: int,
        target_ttft_ms: float,
        target_tpot_ms: float,
    ) -> Routing:
        """SLA-driven pair choice (reference: instance_mgr.cpp:656-757):
        walk prefill candidates predicting TTFT = queued-work + own-prefill
        and take the first within target; if none fits, *spill* onto an idle
        decode instance acting as prefill; if decode is overwhelmed
        (no candidate under target TPOT) flip a prefill instance to decode.
        Falls back to round-robin when predictors are absent.
        """
        with self._mu:
            prefill_candidates = self._routable(
                self._prefill_index + self._mix_index
            )
            decode_candidates = self._routable(
                self._decode_index + self._mix_index
            )
            have_models = any(
                self._predictors.get(n) is not None
                and self._predictors[n].has_ttft_model
                for n in prefill_candidates
            ) or any(
                self._predictors.get(n) is not None
                and self._predictors[n].has_tpot_model
                for n in decode_candidates
            )
        if not have_models:
            # No instance published profiling curves: predictions are all
            # +inf, so fall back to round-robin instead of pinning the fleet
            # to candidates[0].
            return self.get_next_instance_pair()
        routing = Routing()

        # --- prefill side ---
        best_name, best_ttft = "", float("inf")
        for name in prefill_candidates:
            pred = self._predictors.get(name)
            rm = self._request_metrics.get(name)
            if pred is None or not pred.has_ttft_model or rm is None:
                continue
            est = rm.estimated_prefill_time + pred.predict_ttft(prompt_len)
            if est < best_ttft:
                best_name, best_ttft = name, est
            if est <= target_ttft_ms:
                best_name, best_ttft = name, est
                break
        if best_name and best_ttft > target_ttft_ms:
            # Spill: borrow the most idle decode instance for this prefill
            # (reference: spill branch of select_instance_pair_on_slo).
            idle_decode = ""
            with self._mu:
                for name in decode_candidates:
                    rm = self._request_metrics.get(name)
                    lm = self._load_metrics.get(name, LoadMetrics())
                    if (
                        rm is not None
                        and rm.decode_request_num == 0
                        and lm.waiting_requests_num == 0
                    ):
                        idle_decode = name
                        break
            if idle_decode:
                best_name = idle_decode
        routing.prefill_name = best_name or (
            prefill_candidates[0] if prefill_candidates else
            (decode_candidates[0] if decode_candidates else "")
        )

        # --- decode side ---
        best_decode, best_tpot = "", float("inf")
        for name in decode_candidates:
            pred = self._predictors.get(name)
            rm = self._request_metrics.get(name)
            if pred is None or not pred.has_tpot_model or rm is None:
                continue
            tpot = pred.predict_tpot(
                rm.decode_request_num + 1,
                rm.decode_token_num + prompt_len,
            )
            if tpot < best_tpot:
                best_decode, best_tpot = name, tpot
            if tpot <= target_tpot_ms:
                best_decode, best_tpot = name, tpot
                break
        if not best_decode:
            best_decode = decode_candidates[0] if decode_candidates else ""
        elif best_tpot > target_tpot_ms:
            # Decode pressure: grow the decode side by flipping a MIX
            # prefill instance (reference: flip trigger, :744-754).
            flipped = self.flip_prefill_to_decode()
            if flipped:
                best_decode = flipped
        routing.decode_name = best_decode or routing.prefill_name
        if not routing.prefill_name:
            routing.prefill_name = routing.decode_name
        return routing

    def _flippable(self, name: str) -> bool:
        meta = self._instances.get(name)
        return meta is not None and meta.type == InstanceType.MIX

    def flip_prefill_to_decode(self) -> str:
        """Move one idle MIX prefill instance to the decode side
        (reference: instance_mgr.cpp:759-783). Returns its name or ''."""
        with self._mu:
            for name in self._prefill_index:
                if not self._flippable(name):
                    continue
                rm = self._request_metrics.get(name)
                if rm is not None and rm.prefill_request_num > 0:
                    continue
                if len(self._prefill_index) <= 1:
                    return ""  # never empty the prefill side
                self._pop_index(name, InstanceType.PREFILL)
                self._push_index(name, InstanceType.DECODE)
                self._instances[name].current_type = InstanceType.DECODE
                self._flip_events.append((name, 1))
                self.total_flips += 1
                logger.info("flipped %s prefill->decode", name)
                return name
            return ""

    def flip_decode_to_prefill(self) -> str:
        """Opposite flip (reference: instance_mgr.cpp:785-807)."""
        with self._mu:
            for name in self._decode_index:
                if not self._flippable(name):
                    continue
                rm = self._request_metrics.get(name)
                if rm is not None and rm.decode_request_num > 0:
                    continue
                if len(self._decode_index) <= 1:
                    return ""  # never empty the decode side
                self._pop_index(name, InstanceType.DECODE)
                self._push_index(name, InstanceType.PREFILL)
                self._instances[name].current_type = InstanceType.PREFILL
                self._flip_events.append((name, 1))
                self.total_flips += 1
                logger.info("flipped %s decode->prefill", name)
                return name
            return ""

    @staticmethod
    def _side_coverage(role: InstanceType) -> Tuple[int, int]:
        """(prefill, decode) coverage contributed by one serving role."""
        if role == InstanceType.PREFILL:
            return (1, 0)
        if role == InstanceType.DECODE:
            return (0, 1)
        if role == InstanceType.MIX:
            return (1, 1)
        return (0, 0)

    def flip_role(
        self,
        name: str,
        target: InstanceType,
        force: bool = False,
    ) -> str:
        """Targeted role transition for the goodput controller, covering
        MIX serving transitions the paired primitives above cannot express.
        Only declared-MIX instances flip. Drain-aware: refuses while the
        instance still holds work on the side it is leaving, unless
        `force=True` (after a drain timeout; inflight streams keep running —
        the role only steers NEW routing, token replay recovers the rest).
        Never leaves either the prefill or decode side uncovered. Returns
        the name on success, '' otherwise."""
        if isinstance(target, str):
            target = InstanceType.parse(target)
        if target not in (
            InstanceType.PREFILL, InstanceType.DECODE, InstanceType.MIX,
        ):
            return ""
        with self._mu:
            meta = self._instances.get(name)
            if meta is None or meta.type != InstanceType.MIX:
                return ""
            cur = meta.current_type
            if cur == target or cur not in (
                InstanceType.PREFILL, InstanceType.DECODE, InstanceType.MIX,
            ):
                return ""
            if not force:
                rm = self._request_metrics.get(name)
                if rm is not None:
                    lose_p, lose_d = self._side_coverage(cur)
                    gain_p, gain_d = self._side_coverage(target)
                    if lose_p > gain_p and rm.prefill_request_num > 0:
                        return ""
                    if lose_d > gain_d and rm.decode_request_num > 0:
                        return ""
            p_cov = len(self._prefill_index) + len(self._mix_index)
            d_cov = len(self._decode_index) + len(self._mix_index)
            cp, cd = self._side_coverage(cur)
            tp, td = self._side_coverage(target)
            if p_cov - cp + tp < 1 or d_cov - cd + td < 1:
                return ""  # never empty a side
            self._pop_index(name, cur)
            self._push_index(name, target)
            meta.current_type = target
            self._flip_events.append((name, 1))
            self.total_flips += 1
            logger.info(
                "flipped %s %s->%s%s", name, cur.name, target.name,
                " (forced)" if force else "",
            )
            return name

    def take_flip_events(self):
        """Drain pending (instance, attempt) flip notifications — the
        master tells each flipped instance so the ENGINE learns its new
        role (round-1 weak item 8: the registry mutated but the instance
        never knew; the reference never notifies at all,
        instance_mgr.cpp:759-807). The role itself is NOT carried: the
        notifier reads the registry's current_type at send time, so
        delayed deliveries can't park an engine on a stale role."""
        with self._mu:
            out = list(self._flip_events)
            self._flip_events.clear()
            return out

    def requeue_flip(self, name: str, attempt: int) -> None:
        """Re-queue a failed flip notification for the next master tick."""
        with self._mu:
            if not any(n == name for n, _ in self._flip_events):
                self._flip_events.append((name, attempt))
