"""Latency predictors fitted from instance-published profiling curves.

The reference fits a degree-2 polynomial TTFT(prompt_len) and a linear model
TPOT(batch_size, total_tokens) per instance with Eigen's
colPivHouseholderQr (reference: common/time_predictor.{h,cpp}:25-93); engines
profile themselves and publish the sample curves in their registration
metadata (types.h:179-182). Here the fit is a numpy least-squares solve.
Deliberate divergence: the reference's `else` branch zeroes the *ttft*
coefficients when tpot data is missing (time_predictor.cpp:72-74, a bug);
we zero the right ones.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


class TimePredictor:
    def __init__(
        self,
        ttft_profiling_data: Sequence[Tuple[int, float]] = (),
        tpot_profiling_data: Sequence[Tuple[int, int, float]] = (),
    ) -> None:
        self._ttft_coef: Optional[np.ndarray] = None  # [c0, c1, c2]
        self._tpot_coef: Optional[np.ndarray] = None  # [c0, c_batch, c_tokens]
        if len(ttft_profiling_data) >= 3:
            x = np.array([p[0] for p in ttft_profiling_data], dtype=np.float64)
            y = np.array([p[1] for p in ttft_profiling_data], dtype=np.float64)
            A = np.stack([np.ones_like(x), x, x * x], axis=1)
            self._ttft_coef, *_ = np.linalg.lstsq(A, y, rcond=None)
        if len(tpot_profiling_data) >= 3:
            b = np.array([p[0] for p in tpot_profiling_data], dtype=np.float64)
            t = np.array([p[1] for p in tpot_profiling_data], dtype=np.float64)
            y = np.array([p[2] for p in tpot_profiling_data], dtype=np.float64)
            A = np.stack([np.ones_like(b), b, t], axis=1)
            self._tpot_coef, *_ = np.linalg.lstsq(A, y, rcond=None)

    @property
    def has_ttft_model(self) -> bool:
        return self._ttft_coef is not None

    @property
    def has_tpot_model(self) -> bool:
        return self._tpot_coef is not None

    def predict_ttft(self, prompt_len: int) -> float:
        """Milliseconds; +inf when no model (so SLO routing skips the
        instance rather than treating it as instantaneous)."""
        if self._ttft_coef is None:
            return float("inf")
        c = self._ttft_coef
        return float(c[0] + c[1] * prompt_len + c[2] * prompt_len * prompt_len)

    def predict_tpot(self, batch_size: int, total_tokens: int) -> float:
        if self._tpot_coef is None:
            return float("inf")
        c = self._tpot_coef
        return float(c[0] + c[1] * batch_size + c[2] * total_tokens)
