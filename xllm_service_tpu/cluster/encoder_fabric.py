"""Encoder fabric, master side: the fleet's media-embedding index.

Fourth cluster plane (after serving, PD KV handoff, and the prefix KV
fabric — ROADMAP item 4): the EPD paper (arXiv 2501.05460) scales
multimodal serving with independently-sized encoder instances,
cross-request encoder batching, and cached embeddings; P/D-Serve
(arXiv 2408.08147) is the reference for weighing cache affinity against
load. This module is the master's half:

  * **Embedding index** — media content hashes (16-byte blake2b keys,
    service/image_processor.media_content_hash) -> the set of ENCODE
    instances holding that item in their local embedding LRU. Fed by the
    SAME heartbeat KvCacheEvent delta plumbing the prefix index uses
    (EncoderEngine.take_cache_event); the scheduler routes encoder
    instances' deltas here instead of into GlobalKVCacheMgr.
  * **Hit-aware encoder routing** — `match()` scores each encoder by how
    many of a request's media items it already holds;
    `InstanceMgr.next_encode_instance` folds that into its live
    queue-depth score so re-sent media lands where its embeddings live
    (and skips the tower entirely).
  * **Hardening parity with the prefix fabric (docs/KV_CACHE.md)** — on
    breaker ejection the scheduler prunes the instance's embedding-index
    entries and arms a cache RESYNC: the next heartbeat after re-admission
    folds the encoder's full LRU snapshot (cache_snapshot_event) into a
    stored delta, rebuilding the index.

Escape hatch: `XLLM_ENCODER_FABRIC=1|0` overrides the config flags either
way, read per call so it can flip on a live cluster. Wire protocol +
fallback matrix: docs/EPD.md.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Iterable, List, Set

logger = logging.getLogger(__name__)

# One cached media item is worth this many queue slots in the encoder
# routing score: a hit skips the tower dispatch entirely, a queued request
# costs one dispatch — but a hit still pays admission + the handoff.
HIT_WEIGHT = 2.0


def encoder_fabric_enabled(cfg=None) -> bool:
    """The escape hatch: XLLM_ENCODER_FABRIC=1|0 overrides the config
    flag (ServiceConfig/EngineConfig.enable_encoder_fabric) either way.
    Read per call so the hatch can flip on a live process."""
    env = os.environ.get("XLLM_ENCODER_FABRIC", "")
    if env == "1":
        return True
    if env == "0":
        return False
    return bool(getattr(cfg, "enable_encoder_fabric", True))


class EncoderFabric:
    """Master-side embedding-index coordinator. Owned by the Scheduler;
    fed by `handle_instance_heartbeat` (ENCODE-role cache deltas),
    consulted by `schedule()` for hit-aware encoder routing, pruned by
    the breaker/removal listeners."""

    def __init__(self, config, instance_mgr, metrics=None, span_hook=None):
        self._config = config
        self._instance_mgr = instance_mgr
        self._mu = threading.Lock()
        # Distributed tracing: span_hook(srid, stage, **fields) — the
        # master's ring-buffer emit for hit-aware encoder-routing spans.
        self._span_hook = span_hook
        # media content hash -> encoder instance names holding it.
        self._index: Dict[bytes, Set[str]] = {}
        # Fleet-wide embedding hit accounting from the router's vantage:
        # per scheduled media request, items ANY encoder already holds
        # over total items. The number the fabric exists to raise.
        self.fleet_hit_items = 0
        self.fleet_total_items = 0
        if metrics is not None:
            metrics.gauge(
                "xllm_fleet_embed_hit_rate",
                "Fleet-wide media-embedding hit rate at the router: items "
                "some encoder already holds cached over total media items, "
                "across scheduled media requests",
            ).set_function(
                lambda: self.fleet_hit_items
                / max(self.fleet_total_items, 1)
            )

    def enabled(self) -> bool:
        return encoder_fabric_enabled(self._config)

    def __len__(self) -> int:
        with self._mu:
            return len(self._index)

    # -------------------------------------------------------- index feed

    def record_event(self, instance: str, event) -> None:
        """Fold one heartbeat KvCacheEvent from an ENCODE instance:
        stored = items inserted into its embedding LRU, removed = LRU
        evictions. The offload tiers don't exist for embeddings; a
        resync snapshot arrives as a plain stored set (idempotent)."""
        with self._mu:
            for h in event.stored_cache:
                self._index.setdefault(h, set()).add(instance)
            for h in getattr(event, "offload_cache", {}) or {}:
                self._index.setdefault(h, set()).add(instance)
            for h in event.removed_cache:
                holders = self._index.get(h)
                if holders is not None:
                    holders.discard(instance)
                    if not holders:
                        del self._index[h]

    def remove_instance(self, name: str) -> None:
        """Retract every location of one encoder (deregistration, lease
        expiry, or breaker ejection — the scheduler arms a resync so a
        re-admitted encoder's snapshot rebuilds what this drops)."""
        with self._mu:
            dead = []
            for h, holders in self._index.items():
                holders.discard(name)
                if not holders:
                    dead.append(h)
            for h in dead:
                del self._index[h]

    # ----------------------------------------------------------- routing

    def holders(self, media_hash: bytes) -> Set[str]:
        with self._mu:
            return set(self._index.get(media_hash, ()))

    def match(
        self, hashes: Iterable[bytes], srid: str = ""
    ) -> Dict[str, int]:
        """Per-encoder cached-item counts for one request's media list.
        Always feeds the fleet hit-rate gauge (fabric on or off, so an
        A/B hatch flip never flatlines it); the ROUTING consumer only
        uses the scores when the fabric is enabled."""
        hashes = list(hashes)
        scores: Dict[str, int] = {}
        hit_items = 0
        with self._mu:
            for h in hashes:
                holders = self._index.get(h)
                if not holders:
                    continue
                hit_items += 1
                for name in holders:
                    scores[name] = scores.get(name, 0) + 1
            self.fleet_total_items += len(hashes)
            self.fleet_hit_items += hit_items
        if self._span_hook is not None and hashes:
            self._span_hook(
                srid, "encoder_route",
                items=len(hashes), hit_items=hit_items,
                encoders=len(scores),
            )
        return scores

    @staticmethod
    def hashes_of(media_parts: List[dict]) -> List[bytes]:
        """The 16-byte content keys riding a request's media parts (empty
        entries — legacy callers without front-door hashing — drop out)."""
        out = []
        for p in media_parts or ():
            hx = p.get("hash") if isinstance(p, dict) else None
            if hx:
                try:
                    out.append(bytes.fromhex(hx))
                except ValueError:
                    pass
        return out
