"""Global prefix-cache location index ("KV Cache Pool").

TPU-native redesign of the reference's GlobalKVCacheMgr
(reference: xllm_service/scheduler/managers/global_kvcache_mgr.{h,cpp}):
maps chained murmur3 block hashes -> per-tier instance sets, fed by
heartbeat KvCacheEvents (:177-225), queried by cache-aware routing via a
block-aligned prefix walk (:73-131), replicated master->store under
`XLLM:CACHE:` (:227-247) and synced on non-masters via watches (:133-175).

On TPU the tiers are HBM (device pool), DRAM (host offload), SSD (local
NVMe). Deliberate fix vs the reference: DRAM/SSD matches attribute the score
to the instance actually holding the block (the reference dereferences
`hbm_instance_set.begin()` in those branches — UB when the HBM set is
empty, global_kvcache_mgr.cpp:108-125).
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Callable, Dict, List, Sequence, Set

from xllm_service_tpu.common.hashing import prefix_block_hashes
from xllm_service_tpu.common.types import (
    CacheLocations,
    KvCacheEvent,
    OverlapScores,
)
from xllm_service_tpu.coordination.store import (
    CoordinationStore,
    EventType,
    WatchEvent,
)

logger = logging.getLogger(__name__)

CACHE_PREFIX = "XLLM:CACHE:"


class GlobalKVCacheMgr:
    def __init__(
        self,
        store: CoordinationStore,
        is_master: Callable[[], bool],
        block_size: int = 128,
        murmur_hash3_seed: int = 1024,
    ) -> None:
        self._store = store
        self._is_master = is_master
        self._block_size = block_size
        self._seed = murmur_hash3_seed
        self._mu = threading.RLock()
        self._index: Dict[bytes, CacheLocations] = {}  # guarded by: self._mu
        self._dirty: Set[bytes] = set()    # changed since last upload
        self._deleted: Set[bytes] = set()  # emptied since last upload
        self._watch_id = self._store.add_watch(CACHE_PREFIX, self._on_watch)
        self._init_from_store()

    def close(self) -> None:
        self._store.remove_watch(self._watch_id)

    @property
    def block_size(self) -> int:
        return self._block_size

    def _init_from_store(self) -> None:  # graftlint: init-only
        for key, raw in self._store.get_prefix(CACHE_PREFIX).items():
            h = bytes.fromhex(key[len(CACHE_PREFIX):])
            try:
                self._index[h] = CacheLocations.from_json(json.loads(raw))
            except Exception:
                logger.warning("bad cache record at %s", key)

    def __len__(self) -> int:
        with self._mu:
            return len(self._index)

    def lookup(self, block_hash: bytes) -> CacheLocations:
        with self._mu:
            loc = self._index.get(block_hash)
            return (
                CacheLocations(
                    set(loc.hbm_instance_set),
                    set(loc.dram_instance_set),
                    set(loc.ssd_instance_set),
                )
                if loc is not None
                else CacheLocations()
            )

    # ------------------------------------------------------------------ #
    # match: the routing-side prefix walk
    # ------------------------------------------------------------------ #

    def match(self, token_ids: Sequence[int]) -> OverlapScores:
        """Per-instance matched-block counts over the longest cached prefix
        (reference: global_kvcache_mgr.cpp:73-131). Hashes every complete
        block of the prompt with the chained scheme — identical bytes to
        what engines commit — then walks until a block no instance holds."""
        hashes = prefix_block_hashes(token_ids, self._block_size, self._seed)
        scores = OverlapScores(total_blocks=len(hashes))
        with self._mu:
            for h in hashes:
                loc = self._index.get(h)
                if loc is None or loc.empty():
                    break
                for name in loc.hbm_instance_set:
                    scores.hbm_scores[name] = scores.hbm_scores.get(name, 0) + 1
                for name in loc.dram_instance_set:
                    scores.dram_scores[name] = scores.dram_scores.get(name, 0) + 1
                for name in loc.ssd_instance_set:
                    scores.ssd_scores[name] = scores.ssd_scores.get(name, 0) + 1
        return scores

    # ------------------------------------------------------------------ #
    # heartbeat ingestion
    # ------------------------------------------------------------------ #

    def record_updated_kvcaches(self, instance: str, event: KvCacheEvent) -> None:
        """Apply one instance's cache delta
        (reference: global_kvcache_mgr.cpp:177-225). `stored` puts the block
        in the instance's HBM set; `offload` moves it HBM->DRAM/SSD;
        `removed` clears the instance from every tier."""
        if event.empty():
            return
        with self._mu:
            for h in event.stored_cache:
                loc = self._index.setdefault(h, CacheLocations())
                loc.hbm_instance_set.add(instance)
                loc.dram_instance_set.discard(instance)
                loc.ssd_instance_set.discard(instance)
                self._dirty.add(h)
            for h, tier in event.offload_cache.items():
                loc = self._index.setdefault(h, CacheLocations())
                loc.hbm_instance_set.discard(instance)
                if tier == "ssd":
                    loc.dram_instance_set.discard(instance)
                    loc.ssd_instance_set.add(instance)
                else:
                    loc.dram_instance_set.add(instance)
                    loc.ssd_instance_set.discard(instance)
                self._dirty.add(h)
            for h in event.removed_cache:
                loc = self._index.get(h)
                if loc is None:
                    continue
                loc.hbm_instance_set.discard(instance)
                loc.dram_instance_set.discard(instance)
                loc.ssd_instance_set.discard(instance)
                if loc.empty():
                    del self._index[h]
                    self._deleted.add(h)
                    self._dirty.discard(h)
                else:
                    self._dirty.add(h)

    def absorb_instance_snapshot(
        self, instance: str, hashes: Sequence[bytes]
    ) -> None:
        """Takeover reconciliation: fold one instance's full committed-
        block snapshot (from its /reconcile manifest) into the index. The
        snapshot is authoritative for the HBM tier — blocks the index
        attributes to this instance that the instance no longer holds are
        dropped, so a standby's stale watch-synced view cannot survive
        the takeover (docs/FAULT_TOLERANCE.md, control plane)."""
        want = set(hashes)
        with self._mu:
            for h in list(self._index):
                loc = self._index[h]
                if h in want or instance not in loc.hbm_instance_set:
                    continue
                loc.hbm_instance_set.discard(instance)
                if loc.empty():
                    del self._index[h]
                    self._deleted.add(h)
                    self._dirty.discard(h)
                else:
                    self._dirty.add(h)
        if want:
            self.record_updated_kvcaches(
                instance, KvCacheEvent(stored_cache=want)
            )

    def remove_instance(self, instance: str) -> None:
        """Drop a departed instance from every location set."""
        with self._mu:
            for h in list(self._index):
                loc = self._index[h]
                before = (
                    instance in loc.hbm_instance_set
                    or instance in loc.dram_instance_set
                    or instance in loc.ssd_instance_set
                )
                if not before:
                    continue
                loc.hbm_instance_set.discard(instance)
                loc.dram_instance_set.discard(instance)
                loc.ssd_instance_set.discard(instance)
                if loc.empty():
                    del self._index[h]
                    self._deleted.add(h)
                    self._dirty.discard(h)
                else:
                    self._dirty.add(h)

    # ------------------------------------------------------------------ #
    # master <-> store replication
    # ------------------------------------------------------------------ #

    def upload_kvcache(self) -> int:
        """Master-only batch flush of dirty records
        (reference: global_kvcache_mgr.cpp:227-247). Returns writes+deletes."""
        if not self._is_master():
            return 0
        with self._mu:
            dirty = {h: self._index[h].to_json() for h in self._dirty
                     if h in self._index}
            deleted = set(self._deleted)
            self._dirty.clear()
            self._deleted.clear()
        for h, j in dirty.items():
            self._store.set(CACHE_PREFIX + h.hex(), json.dumps(j))
        for h in deleted:
            self._store.remove(CACHE_PREFIX + h.hex())
        return len(dirty) + len(deleted)

    def _on_watch(self, events: List[WatchEvent]) -> None:
        """Non-master sync (reference: global_kvcache_mgr.cpp:133-175)."""
        if self._is_master():
            return
        with self._mu:
            for ev in events:
                h = bytes.fromhex(ev.key[len(CACHE_PREFIX):])
                if ev.type == EventType.PUT:
                    try:
                        self._index[h] = CacheLocations.from_json(
                            json.loads(ev.value)
                        )
                    except Exception:
                        pass
                else:
                    self._index.pop(h, None)
