"""Cluster state managers (reference: xllm_service/scheduler/managers/)."""

from xllm_service_tpu.cluster.encoder_fabric import (
    EncoderFabric,
    encoder_fabric_enabled,
)
from xllm_service_tpu.cluster.global_kvcache_mgr import CACHE_PREFIX, GlobalKVCacheMgr
from xllm_service_tpu.cluster.instance_mgr import (
    INSTANCE_PREFIXES,
    LOADMETRICS_PREFIX,
    InstanceMgr,
    instance_key,
)
from xllm_service_tpu.cluster.time_predictor import TimePredictor

__all__ = [
    "CACHE_PREFIX",
    "EncoderFabric",
    "encoder_fabric_enabled",
    "GlobalKVCacheMgr",
    "INSTANCE_PREFIXES",
    "LOADMETRICS_PREFIX",
    "InstanceMgr",
    "instance_key",
    "TimePredictor",
]
