"""Goodput controller plane: per-request colocate-vs-disaggregate placement
plus continuous PD role reshaping.

Neither static deployment mode wins across a mixed trace (arxiv
2508.01989): short-decode requests pay the KV-handoff stall for nothing
under disaggregation, long-decode requests suffer prefill interference
when colocated, and the right PD ratio tracks the load mix rather than
being provisioned once (P/D-Serve, arxiv 2408.08147). This module is the
master-side controller that decides both, from signals the cluster
already publishes:

- estimated prefill cost: the instance's fitted TTFT curve over the
  prompt tokens NOT covered by local/fabric prefix cache;
- predicted decode length: a per-tenant EWMA over observed completions
  (tenant = model name — the strongest cheap predictor of output length);
- live handoff-stall: the per-instance `kv_stall_ms_ewma` heartbeat
  scalar folded from the xllm_kv_handoff_stall_ms stream, with a
  fleet-mean fallback for instances that have not pulled yet;
- decode-side TPOT headroom: the fitted TPOT curve inflated by queue
  depth and `moe_hot_expert_frac` (a hot expert serializes the grouped
  dispatch for every request in the batch).

The controller only ACTS when its inputs are trustworthy: off, cold
EWMA, stale EWMA, missing predictor, or a non-MIX target all degrade to
the static routing the policy already chose — every decision, including
the fallbacks, is counted in `xllm_goodput_decisions_total{mode}`.

Reshaping is deliberately slow: one flip per qualifying tick, after
`hysteresis_ticks` consecutive ticks agreeing on the direction and at
least `min_flip_interval_s` since the last flip. Flips go through the
drain-aware `InstanceMgr.flip_role` (idle-only), escalating to
`force=True` only after the same want has persisted past
`drain_timeout_s` — forced flips never kill inflight streams (the role
only steers NEW routing; token replay covers redispatch).

Hatches (all read per call so they flip on a live cluster):
  XLLM_GOODPUT_CONTROLLER=1|0      master on/off override
  XLLM_GOODPUT_FORCE=colocate|disaggregate
                                   pin every actionable decision (bench
                                   baselines and differential oracles)
  XLLM_GOODPUT_MIN_SAMPLES         EWMA completions before acting
  XLLM_GOODPUT_STALE_S             EWMA freshness window, seconds
  XLLM_GOODPUT_COLOCATE_MARGIN     colocate iff coloc <= disagg * margin
  XLLM_GOODPUT_HYSTERESIS_TICKS    same-direction ticks before a flip
  XLLM_GOODPUT_MIN_FLIP_INTERVAL_S floor between reshaping flips
  XLLM_GOODPUT_DRAIN_TIMEOUT_S     want age before force-flipping

Autoscaling signals (`autoscale_signals()`, master-loop cadence next to
`tick()`): reshaping only re-slices the fleet we HAVE; the same demand
model also says how many instances per role we'd WANT — the gauges an
external autoscaler (or bench_fleet's scenario guards) consumes.
`xllm_autoscale_wanted_instances{role}` is demand-derived (queued work
over the per-instance waiting target), `xllm_autoscale_encoder_headroom`
is the fraction of encoder capacity still free (negative = encoders are
the bottleneck). Hatches: XLLM_FLEET_AUTOSCALE=1|0 (default on) and
XLLM_FLEET_AUTOSCALE_TARGET_WAITING (waiting requests per serving
instance the fleet should absorb before asking for more, default 4).
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

from xllm_service_tpu.common import faults
from xllm_service_tpu.common.types import InstanceType

logger = logging.getLogger(__name__)

# Decode-length EWMA smoothing: ~last dozen completions dominate.
EWMA_ALPHA = 0.3
# TPOT inflation per waiting request on the serving instance: queueing
# delays every decode step of the new request.
WAITING_PENALTY = 0.08
# TPOT inflation at moe_hot_expert_frac=1.0 (one expert owns every
# assignment — the grouped dispatch degenerates to serial).
MOE_PENALTY = 0.5
# Recent decisions window for the reshaper's colocate-fraction signal.
DECISION_WINDOW = 64


def goodput_enabled(cfg=None) -> bool:
    """XLLM_GOODPUT_CONTROLLER=1|0 overrides config either way; read per
    call so the hatch flips on a live cluster."""
    env = os.environ.get("XLLM_GOODPUT_CONTROLLER")
    if env == "1":
        return True
    if env == "0":
        return False
    return bool(getattr(cfg, "enable_goodput_controller", True))


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


@dataclass
class PlacementDecision:
    """One per-request verdict plus the numbers behind it (observability:
    the bench and tests read these, the mode label feeds the counter)."""

    mode: str           # "colocate" | "disaggregate" | "static"
    reason: str         # why — "model" for a real comparison, else gate
    coloc_ms: float = 0.0
    disagg_ms: float = 0.0
    decode_est: float = 0.0
    stall_ms: float = 0.0

    @property
    def acted(self) -> bool:
        return self.mode in ("colocate", "disaggregate")


@dataclass
class _TenantStats:
    ewma: float = 0.0
    n: int = 0
    ts: float = 0.0


class GoodputController:
    """Master-side goodput controller (see module docstring). Constructed
    by the scheduler next to PrefixFabric; all methods are thread-safe
    (decisions on the dispatch path, ticks on the master loop)."""

    def __init__(self, config, instance_mgr, metrics=None,
                 clock=time.monotonic):
        self._config = config
        self._mgr = instance_mgr
        self._clock = clock
        self._mu = threading.Lock()
        self._tenants: Dict[str, _TenantStats] = {}
        self._recent_modes = collections.deque(maxlen=DECISION_WINDOW)
        # Reshaping hysteresis state.
        self._want_dir = 0          # -1 shrink prefill, +1 grow prefill
        self._want_streak = 0
        self._want_since = 0.0
        self._last_flip_ts = 0.0
        self.decisions = {"colocate": 0, "disaggregate": 0, "static": 0}
        self.reshape_flips = 0
        self._wanted_census = {"prefill": 0, "decode": 0, "mix": 0}
        # Latest autoscale verdict (autoscale_signals()); gauges read it.
        self._wanted_instances = {
            "prefill": 0, "decode": 0, "mix": 0, "encode": 0,
        }
        self._encoder_headroom = 1.0
        self._decisions_total = None
        self._flips_total = None
        if metrics is not None:
            self._decisions_total = metrics.counter(
                "xllm_goodput_decisions_total",
                "Per-request placement decisions by mode "
                "(colocate/disaggregate/static fallback)",
                labelnames=("mode",),
            )
            self._flips_total = metrics.counter(
                "xllm_goodput_reshape_flips_total",
                "Reshaping role flips issued by the controller",
                labelnames=("direction",),
            )
            wanted = metrics.gauge(
                "xllm_goodput_wanted_census",
                "Role census the reshaper currently wants",
                labelnames=("role",),
            )
            for role in ("prefill", "decode", "mix"):
                wanted.labels(role=role).set_function(
                    lambda r=role: float(self._wanted_census[r])
                )
            wanted_inst = metrics.gauge(
                "xllm_autoscale_wanted_instances",
                "Instances per role the demand model would provision "
                "(autoscaler input; 0 until the first signal tick)",
                labelnames=("role",),
            )
            for role in ("prefill", "decode", "mix", "encode"):
                wanted_inst.labels(role=role).set_function(
                    lambda r=role: float(self._wanted_instances[r])
                )
            metrics.gauge(
                "xllm_autoscale_encoder_headroom",
                "Fraction of encoder capacity still free "
                "(1 = idle, 0 = at the waiting target, negative = "
                "encoders are the bottleneck)",
            ).set_function(lambda: float(self._encoder_headroom))

    # ------------------------------------------------------------------ #
    # signals
    # ------------------------------------------------------------------ #

    def enabled(self) -> bool:
        return goodput_enabled(self._config)

    def observe_completion(self, tenant: str, generated_tokens: int) -> None:
        """Feed one clean completion into the tenant's decode-length EWMA
        (scheduler.finish_request; cancelled/errored streams are skipped —
        a truncated length would bias the predictor low)."""
        if generated_tokens <= 0:
            return
        with self._mu:
            st = self._tenants.setdefault(tenant, _TenantStats())
            if st.n == 0:
                st.ewma = float(generated_tokens)
            else:
                st.ewma += EWMA_ALPHA * (generated_tokens - st.ewma)
            st.n += 1
            st.ts = self._clock()

    def predicted_decode_len(self, tenant: str) -> Optional[float]:
        """EWMA estimate, or None while cold/stale (the decision then
        degrades to static)."""
        min_n = _env_int("XLLM_GOODPUT_MIN_SAMPLES", 4)
        stale_s = _env_float("XLLM_GOODPUT_STALE_S", 30.0)
        with self._mu:
            st = self._tenants.get(tenant)
            if st is None or st.n < min_n:
                return None
            if self._clock() - st.ts > stale_s:
                return None
            return st.ewma

    def stall_estimate_ms(self, decode_name: str) -> float:
        """Expected KV-handoff stall if this request disaggregates onto
        `decode_name`: its own heartbeat EWMA, else the fleet mean over
        instances that HAVE pulled (0.0 when nobody has — first requests
        assume the wire is free until told otherwise)."""
        load = self._mgr.get_load_metrics()
        own = load.get(decode_name)
        if own is not None and own.kv_stall_ms_ewma > 0.0:
            return own.kv_stall_ms_ewma
        seen = [
            lm.kv_stall_ms_ewma for lm in load.values()
            if lm.kv_stall_ms_ewma > 0.0
        ]
        return sum(seen) / len(seen) if seen else 0.0

    def _effective_tpot_ms(self, name: str, prompt_len: int,
                           decode_est: float) -> Optional[float]:
        """Fitted TPOT at the instance's CURRENT batch inflated by queue
        depth and expert hotness; None without a published model."""
        pred = self._mgr.get_time_predictor(name)
        if pred is None or not pred.has_tpot_model:
            return None
        rm = self._mgr.get_request_metrics(name)
        batch = (rm.decode_request_num if rm is not None else 0) + 1
        tokens = (rm.decode_token_num if rm is not None else 0)
        tpot = pred.predict_tpot(batch, tokens + prompt_len + int(decode_est))
        lm = self._mgr.get_load_metrics().get(name)
        if lm is not None:
            tpot *= 1.0 + WAITING_PENALTY * lm.waiting_requests_num
            tpot *= 1.0 + MOE_PENALTY * lm.moe_hot_expert_frac
        return max(tpot, 0.0)

    # ------------------------------------------------------------------ #
    # half (a): per-request placement
    # ------------------------------------------------------------------ #

    def decide_placement(self, prompt_len: int, tenant: str, routing,
                         covered_tokens: int = 0) -> PlacementDecision:
        """Choose COLOCATED (decode rides routing.prefill_name's mixed hot
        loop) vs DISAGGREGATED (keep the policy's PD pair). Every gate
        that prevents a real comparison returns mode="static" — the
        caller leaves the routing untouched."""
        d = self._decide(prompt_len, tenant, routing, covered_tokens)
        self.decisions[d.mode] = self.decisions.get(d.mode, 0) + 1
        if self._decisions_total is not None:
            self._decisions_total.labels(mode=d.mode).inc()
        if d.acted:
            with self._mu:
                self._recent_modes.append(d.mode)
        return d

    def _decide(self, prompt_len, tenant, routing,
                covered_tokens) -> PlacementDecision:
        if not self.enabled():
            return PlacementDecision("static", "disabled")
        p_name = getattr(routing, "prefill_name", "")
        d_name = getattr(routing, "decode_name", "")
        if not p_name or not d_name or p_name == d_name:
            return PlacementDecision("static", "already-colocated")
        meta = self._mgr.get_instance(p_name)
        if meta is None or meta.type != InstanceType.MIX:
            # Colocating needs the target's one-dispatch mixed hot loop.
            return PlacementDecision("static", "target-not-mix")
        force = os.environ.get("XLLM_GOODPUT_FORCE", "")
        if force in ("colocate", "disaggregate"):
            return PlacementDecision(force, "forced")
        decode_est = self.predicted_decode_len(tenant)
        if decode_est is None:
            return PlacementDecision("static", "ewma-cold-or-stale")
        coloc_tpot = self._effective_tpot_ms(p_name, prompt_len, decode_est)
        disagg_tpot = self._effective_tpot_ms(d_name, prompt_len, decode_est)
        if coloc_tpot is None or disagg_tpot is None:
            return PlacementDecision("static", "no-predictor")
        # TTFT is paid on p_name under BOTH placements, so it cancels out
        # of the comparison; keep it in the reported totals when a model
        # exists (prefix/fabric-covered tokens don't need recompute).
        pred = self._mgr.get_time_predictor(p_name)
        eff_prompt = max(1, prompt_len - max(0, covered_tokens))
        ttft = (
            pred.predict_ttft(eff_prompt)
            if pred is not None and pred.has_ttft_model else 0.0
        )
        stall = self.stall_estimate_ms(d_name)
        coloc_ms = ttft + decode_est * coloc_tpot
        disagg_ms = ttft + stall + decode_est * disagg_tpot
        margin = _env_float("XLLM_GOODPUT_COLOCATE_MARGIN", 1.0)
        mode = "colocate" if coloc_ms <= disagg_ms * margin else "disaggregate"
        return PlacementDecision(
            mode, "model",
            coloc_ms=coloc_ms, disagg_ms=disagg_ms,
            decode_est=decode_est, stall_ms=stall,
        )

    # ------------------------------------------------------------------ #
    # half (b): fleet reshaping
    # ------------------------------------------------------------------ #

    def colocate_fraction(self) -> float:
        """Share of recent ACTED decisions that chose colocation."""
        with self._mu:
            if not self._recent_modes:
                return 0.0
            coloc = sum(1 for m in self._recent_modes if m == "colocate")
            return coloc / len(self._recent_modes)

    def wanted_census(self) -> Dict[str, int]:
        return dict(self._wanted_census)

    def tick(self) -> str:
        """One reshaping step (master loop, heartbeat cadence): compute
        the wanted role census from windowed load, damp with hysteresis,
        and issue AT MOST one drain-aware flip. Returns the flipped
        instance's name or ''."""
        if not self.enabled():
            self._want_streak = 0
            self._want_dir = 0
            return ""
        census = self._mgr.role_census()
        cur_p, cur_d = census["prefill"], census["decode"]
        n = cur_p + cur_d
        if n < 2:
            return ""
        demand_p, demand_d = self._demand()
        want_p = self._wanted_prefill(n, demand_p, demand_d, cur_p)
        self._wanted_census = {
            "prefill": want_p, "decode": n - want_p, "mix": census["mix"],
        }
        now = self._clock()
        direction = (want_p > cur_p) - (want_p < cur_p)
        if direction == 0:
            self._want_streak = 0
            self._want_dir = 0
            return self._tick_mix(census, now)
        if direction == self._want_dir:
            self._want_streak += 1
        else:
            self._want_dir = direction
            self._want_streak = 1
            self._want_since = now
        ticks = _env_int("XLLM_GOODPUT_HYSTERESIS_TICKS", 3)
        min_interval = _env_float("XLLM_GOODPUT_MIN_FLIP_INTERVAL_S", 10.0)
        if self._want_streak < ticks:
            return ""
        if now - self._last_flip_ts < min_interval:
            return ""
        if direction > 0:
            flipped = self._mgr.flip_decode_to_prefill()
            label = "decode_to_prefill"
        else:
            flipped = self._mgr.flip_prefill_to_decode()
            label = "prefill_to_decode"
        if not flipped:
            # Every candidate is busy (drain-aware refusal). After the
            # same want has persisted past the drain timeout, force the
            # least-loaded declared-MIX candidate: inflight streams keep
            # running, only NEW routing changes.
            drain_s = _env_float("XLLM_GOODPUT_DRAIN_TIMEOUT_S", 30.0)
            if now - self._want_since >= drain_s:
                source = (
                    self._mgr.decode_instances() if direction > 0
                    else self._mgr.prefill_instances()
                )
                target = (
                    InstanceType.PREFILL if direction > 0
                    else InstanceType.DECODE
                )
                for name in source:
                    flipped = self._mgr.flip_role(name, target, force=True)
                    if flipped:
                        break
        if flipped:
            self._last_flip_ts = now
            self._want_streak = 0
            self.reshape_flips += 1
            if self._flips_total is not None:
                self._flips_total.labels(direction=label).inc()
            logger.info("goodput reshape: %s (%s)", flipped, label)
            return flipped
        return ""

    def _demand(self):
        """Windowed per-side work: prefill demand from queued prefill
        time/requests, decode demand from running decodes + waiting."""
        demand_p = 0.0
        demand_d = 0.0
        load = self._mgr.get_load_metrics()
        for meta in self._mgr.list_instances():
            rm = self._mgr.get_request_metrics(meta.name)
            if rm is None:
                continue
            demand_p += rm.prefill_request_num
            demand_d += rm.decode_request_num
            lm = load.get(meta.name)
            if lm is not None:
                demand_d += lm.waiting_requests_num
        return demand_p, demand_d

    # ------------------------------------------------------------------ #
    # half (c): autoscaling signals
    # ------------------------------------------------------------------ #

    def autoscale_signals(self) -> Dict[str, object]:
        """Emit the wanted-instances-per-role and encoder-headroom
        signals (master-loop cadence, and directly from bench_fleet).

        Reshaping moves roles WITHIN the fleet; this says how big the
        fleet should BE: queued+running work per role over the waiting
        target gives a wanted replica count, never below 1 per role that
        currently exists (scaling to zero is a provisioning decision,
        not a load signal). Encoder headroom is how much of the encode
        tier's waiting budget is unspent — the EPD-specific signal,
        since encoders saturate on media bursts long before the LM tiers
        notice. Returns the signal dict it also publishes as gauges."""
        if os.environ.get("XLLM_FLEET_AUTOSCALE", "1") == "0":
            return {}
        target = max(
            _env_float("XLLM_FLEET_AUTOSCALE_TARGET_WAITING", 4.0), 0.1
        )
        census = self._mgr.role_census()
        demand_p, demand_d = self._demand()
        serving = census["prefill"] + census["decode"] + census["mix"]
        total_demand = demand_p + demand_d
        # Wanted SERVING fleet size: enough instances that each absorbs
        # at most `target` units of queued+running work.
        want_serving = max(
            1, int(-(-total_demand // target))  # ceil
        ) if total_demand > 0 else max(serving, 1)
        want_serving = max(want_serving, 1)
        # Split the serving want by the same demand ratio the reshaper
        # uses; MIX capacity counts toward whichever side is thinner, so
        # a colocate-heavy fleet (all MIX) wants MIX replicas.
        if census["mix"] >= max(census["prefill"], census["decode"]):
            wanted = {
                "prefill": census["prefill"],
                "decode": census["decode"],
                "mix": max(
                    1, want_serving - census["prefill"] - census["decode"]
                ),
            }
        else:
            want_p = (
                self._wanted_prefill(
                    want_serving, demand_p, demand_d,
                    max(census["prefill"], 1),
                )
                if want_serving >= 2 else want_serving
            )
            wanted = {
                "prefill": want_p,
                "decode": max(want_serving - want_p, 0),
                "mix": census["mix"],
            }
        # Encoder headroom: unspent share of the encode tier's waiting
        # budget. No encoders registered = no EPD tier = full headroom.
        enc_names = self._mgr.encode_instances()
        enc_waiting = 0.0
        load = self._mgr.get_load_metrics()
        for name in enc_names:
            lm = load.get(name)
            if lm is not None:
                enc_waiting += lm.waiting_requests_num
        if enc_names:
            budget = target * len(enc_names)
            headroom = (budget - enc_waiting) / budget
            wanted["encode"] = max(
                len(enc_names), int(-(-enc_waiting // target))
            )
        else:
            headroom = 1.0
            wanted["encode"] = 0
        signal = {
            "wanted_instances": wanted,
            "encoder_headroom": headroom,
            "demand_prefill": demand_p,
            "demand_decode": demand_d,
        }
        # Chaos seam: a dropped signal tick must degrade to the previous
        # gauge values, never crash the master loop.
        try:
            faults.point(
                "autoscale.signal",
                wanted=str(sum(wanted.values())),
                headroom=f"{headroom:.3f}",
            )
        except faults.FaultInjected:
            return {}
        self._wanted_instances = wanted
        self._encoder_headroom = headroom
        return signal

    def wanted_instances(self) -> Dict[str, int]:
        return dict(self._wanted_instances)

    def encoder_headroom(self) -> float:
        return self._encoder_headroom

    @staticmethod
    def _wanted_prefill(n, demand_p, demand_d, cur_p):
        total = demand_p + demand_d
        if total <= 0:
            return cur_p  # idle fleet: leave the census alone
        want = round(n * demand_p / total)
        return max(1, min(n - 1, int(want)))

    def _tick_mix(self, census, now) -> str:
        """Serving-MIX transitions, only attempted when the PD census is
        already where we want it: a sustained colocate-heavy mix earns a
        dedicated MIX-serving instance (both sides route to it); a
        colocate-light mix returns it to the thinner side."""
        frac = self.colocate_fraction()
        min_interval = _env_float("XLLM_GOODPUT_MIN_FLIP_INTERVAL_S", 10.0)
        if now - self._last_flip_ts < min_interval:
            return ""
        flipped = ""
        if frac >= 0.6 and census["mix"] == 0 and len(self._recent_modes) >= 8:
            donor_side = (
                self._mgr.prefill_instances()
                if census["prefill"] >= census["decode"]
                else self._mgr.decode_instances()
            )
            for name in donor_side:
                flipped = self._mgr.flip_role(name, InstanceType.MIX)
                if flipped:
                    break
            label = "to_mix"
        elif frac <= 0.2 and census["mix"] > 0:
            target = (
                InstanceType.PREFILL
                if census["prefill"] <= census["decode"]
                else InstanceType.DECODE
            )
            for name in self._mgr.mix_instances():
                flipped = self._mgr.flip_role(name, target)
                if flipped:
                    break
            label = "from_mix"
        else:
            return ""
        if flipped:
            self._last_flip_ts = now
            self.reshape_flips += 1
            if self._flips_total is not None:
                self._flips_total.labels(direction=label).inc()
            logger.info("goodput reshape: %s (%s)", flipped, label)
        return flipped
