"""Audio encoder (EPD stage E): the Qwen2-Audio tower.

Completes the media triad the reference's message model carries
(jinja_chat_template.h:30-47 parses `audio_url` parts verbatim; no
encoder exists anywhere in the reference — this is capability beyond
parity, mirroring the vision towers' design).

Architecture = HF Qwen2AudioEncoder (a WhisperEncoder clone,
modeling_qwen2_audio.py) + the Qwen2AudioMultiModalProjector:

    log-mel [B, M, T]
      -> conv1 (M -> D, k3 p1) + GELU
      -> conv2 (D -> D, k3 s2 p1) + GELU       T -> ceil(T/2)
      -> + learned positions [max_source_positions, D]
      -> pre-LN transformer (biased q/v/out, BIAS-FREE k — the Whisper
         convention; GELU MLP; full bidirectional attention)
      -> avg_pool1d(2, 2)                      -> floor(ceil(T/2)/2)
      -> LayerNorm -> linear projector to the LM hidden size

TPU-first: the convs are einsums over unfolded frames (static shapes,
MXU-friendly), the layer stack is one lax.scan over stacked leaves —
same compile-once shape discipline as the vision towers. Output tokens
per clip are a pure function of the padded mel length
(`audio_out_tokens`), which the service tier uses to size placeholder
spans.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from xllm_service_tpu.models.vision import layer_norm

Params = Dict[str, Any]


@dataclass(frozen=True)
class AudioConfig:
    name: str
    num_mel_bins: int  # M — mel features per frame
    mel_frames: int  # T — padded mel length the tower compiles for
    hidden_size: int  # D (HF d_model)
    intermediate_size: int  # HF encoder_ffn_dim
    num_layers: int  # HF encoder_layers
    num_heads: int  # HF encoder_attention_heads
    out_dim: int  # LM hidden size (projector output)
    ln_eps: float = 1e-5

    @property
    def conv_frames(self) -> int:
        """Positions after conv2 (stride 2, k3, p1) == HF
        max_source_positions for the compiled mel length."""
        return (self.mel_frames + 1) // 2

    @property
    def out_tokens(self) -> int:
        """Media tokens per clip: conv2 then avg_pool(2, 2)."""
        return self.conv_frames // 2


_REGISTRY: Dict[str, AudioConfig] = {}


def register_audio(cfg: AudioConfig) -> AudioConfig:
    _REGISTRY[cfg.name] = cfg
    return cfg


def get_audio_config(name: str) -> AudioConfig:
    if name not in _REGISTRY:
        raise KeyError(
            f"unknown audio config '{name}'; have {sorted(_REGISTRY)}"
        )
    return _REGISTRY[name]


register_audio(
    AudioConfig(
        # Test-scale tower (CPU CI) paired with llama3-tiny's hidden 128.
        name="audio-tiny",
        num_mel_bins=16,
        mel_frames=40,  # -> 20 conv positions -> 10 media tokens
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        out_dim=128,
    )
)

register_audio(
    AudioConfig(
        # Real Qwen2-Audio-7B geometry (HF Qwen2AudioEncoderConfig
        # defaults): 30 s of 16 kHz audio -> 3000 mel frames -> 1500
        # positions -> 750 media tokens into a 4096-wide LM.
        name="qwen2audio-encoder",
        num_mel_bins=128,
        mel_frames=3000,
        hidden_size=1280,
        intermediate_size=5120,
        num_layers=32,
        num_heads=20,
        out_dim=4096,
    )
)


def audio_out_tokens(mel_frames: int) -> int:
    """Tokens the tower emits for a padded mel length (the service tier
    sizes placeholder spans with this — keep in lockstep with
    AudioConfig.out_tokens)."""
    return ((mel_frames + 1) // 2) // 2


def init_audio_params(
    cfg: AudioConfig, key: jax.Array, dtype=jnp.float32
) -> Params:
    D, M, F = cfg.hidden_size, cfg.num_mel_bins, cfg.intermediate_size
    L = cfg.num_layers
    keys = jax.random.split(key, 12)

    def w(k, shape, fan_in):
        return (
            jax.random.normal(k, shape, jnp.float32) / np.sqrt(fan_in)
        ).astype(dtype)

    def zeros(shape):
        return jnp.zeros(shape, dtype)

    layers = {
        "ln1_w": jnp.ones((L, D), jnp.float32),
        "ln1_b": jnp.zeros((L, D), jnp.float32),
        "wq": w(keys[0], (L, D, D), D), "bq": zeros((L, D)),
        "wk": w(keys[1], (L, D, D), D),  # Whisper: k_proj has NO bias
        "wv": w(keys[2], (L, D, D), D), "bv": zeros((L, D)),
        "wo": w(keys[3], (L, D, D), D), "bo": zeros((L, D)),
        "ln2_w": jnp.ones((L, D), jnp.float32),
        "ln2_b": jnp.zeros((L, D), jnp.float32),
        "fc1": w(keys[4], (L, D, F), D), "b1": zeros((L, F)),
        "fc2": w(keys[5], (L, F, D), F), "b2": zeros((L, D)),
    }
    return {
        # Conv kernels stored [k, in, out] for the unfolded einsum.
        "conv1_w": w(keys[6], (3, M, D), 3 * M),
        "conv1_b": zeros((D,)),
        "conv2_w": w(keys[7], (3, D, D), 3 * D),
        "conv2_b": zeros((D,)),
        "pos_embed": w(keys[8], (cfg.conv_frames, D), D),
        "layers": layers,
        "ln_post_w": jnp.ones((D,), jnp.float32),
        "ln_post_b": jnp.zeros((D,), jnp.float32),
        "proj": w(keys[9], (D, cfg.out_dim), D),
        "proj_b": zeros((cfg.out_dim,)),
    }


def _conv1d(x: jnp.ndarray, w: jnp.ndarray, b: jnp.ndarray,
            stride: int) -> jnp.ndarray:
    """[B, T, C_in] x [k=3, C_in, C_out] -> [B, T_out, C_out], padding 1
    — an unfold + einsum so XLA sees one MXU matmul per output frame
    block instead of a scalar conv loop."""
    B, T, Ci = x.shape
    xp = jnp.pad(x, ((0, 0), (1, 1), (0, 0)))
    starts = jnp.arange(0, T, stride)
    # windows [B, T_out, 3, Ci]
    win = jnp.stack([xp[:, s: s + T: 1][:, starts] for s in range(3)],
                    axis=2)
    return jnp.einsum("btkc,kcd->btd", win, w) + b


def encode_audio(
    params: Params, cfg: AudioConfig, mel: jnp.ndarray
) -> jnp.ndarray:
    """[B, M, T] log-mel (T == cfg.mel_frames) -> [B, out_tokens,
    out_dim] LM-ready media tokens."""
    B = mel.shape[0]
    assert mel.shape[1:] == (cfg.num_mel_bins, cfg.mel_frames), mel.shape
    H, D = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    x = mel.astype(params["conv1_w"].dtype).transpose(0, 2, 1)  # [B,T,M]
    x = jax.nn.gelu(
        _conv1d(x, params["conv1_w"], params["conv1_b"], 1),
        approximate=False,
    )
    x = jax.nn.gelu(
        _conv1d(x, params["conv2_w"], params["conv2_b"], 2),
        approximate=False,
    )
    x = x + params["pos_embed"][None]
    N = x.shape[1]

    def layer_fn(x, lp):
        h = layer_norm(x, lp["ln1_w"], lp["ln1_b"], cfg.ln_eps)
        q = (jnp.einsum("bne,ef->bnf", h, lp["wq"]) + lp["bq"]) * (
            D**-0.5
        )
        k = jnp.einsum("bne,ef->bnf", h, lp["wk"])  # bias-free (Whisper)
        v = jnp.einsum("bne,ef->bnf", h, lp["wv"]) + lp["bv"]
        q = q.reshape(B, N, H, D).astype(jnp.float32)
        k = k.reshape(B, N, H, D).astype(jnp.float32)
        v = v.reshape(B, N, H, D).astype(jnp.float32)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        attn = attn.reshape(B, N, -1).astype(x.dtype)
        x = x + jnp.einsum("bne,ef->bnf", attn, lp["wo"]) + lp["bo"]
        h = layer_norm(x, lp["ln2_w"], lp["ln2_b"], cfg.ln_eps)
        h = jax.nn.gelu(
            jnp.einsum("bne,ef->bnf", h, lp["fc1"]) + lp["b1"],
            approximate=False,
        )
        x = x + jnp.einsum("bnf,fe->bne", h, lp["fc2"]) + lp["b2"]
        return x, None

    x, _ = jax.lax.scan(layer_fn, x, params["layers"])
    # avg_pool1d(2, stride 2) over the position axis, then final LN.
    x = x[:, : (N // 2) * 2].reshape(B, N // 2, 2, -1).mean(axis=2)
    x = layer_norm(x, params["ln_post_w"], params["ln_post_b"], cfg.ln_eps)
    return (
        jnp.einsum("bne,ed->bnd", x, params["proj"]) + params["proj_b"]
    )
