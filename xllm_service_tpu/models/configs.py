"""Model architecture configs + registry.

The engine tier the reference delegates to a non-vendored CUDA submodule
(SURVEY.md §2.3) is first-class here. Configs cover the north-star families
(BASELINE.json): Llama-3 dense, Qwen2, and Mixtral/DeepSeek-style MoE.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    rope_theta: float = 500000.0
    # HF `rope_scaling` (ops/rope.rope_parameters implements the math;
    # runtime/weights.config_from_hf parses it and LOUDLY rejects types
    # not listed there). "" = plain theta. Tuples keep the frozen config
    # hashable for jit static args.
    # "linear" | "dynamic" | "llama3" | "longrope" | "yarn"
    rope_scaling_type: str = ""
    rope_scaling_factor: float = 1.0
    rope_original_max_position: int = 0  # 0 = max_position_embeddings
    rope_low_freq_factor: float = 1.0  # llama3
    rope_high_freq_factor: float = 4.0  # llama3
    rope_short_factor: tuple = ()  # longrope per-band tables [head_dim/2]
    rope_long_factor: tuple = ()
    rope_attention_factor: float = 0.0  # longrope/yarn; 0 = HF formula
    rope_beta_fast: float = 32.0  # yarn correction-range bounds
    rope_beta_slow: float = 1.0
    rope_mscale: float = 0.0  # yarn (DeepSeek): attention-factor numerator
    rope_mscale_all_dim: float = 0.0  # ...and denominator / softmax scale
    rope_scaling_truncate: bool = True  # yarn: floor/ceil the range
    rms_norm_eps: float = 1e-5
    max_position_embeddings: int = 8192
    tie_word_embeddings: bool = False
    # MoE (0 experts = dense).
    num_experts: int = 0
    num_experts_per_tok: int = 0
    moe_intermediate_size: int = 0
    # Router semantics. "softmax" scoring + norm_topk_prob covers
    # Mixtral/Qwen3 (top-k renormalized full-softmax probs — identical
    # to softmaxing the top-k logits); DeepSeek adds "sigmoid" scoring
    # (V3), group-limited selection (n_group/topk_group; "noaux_tc"
    # scores groups by top-2 sums with a selection-only correction bias,
    # "group_limited_greedy" by group max), optional non-normalized
    # weights, and routed_scaling_factor.
    scoring_func: str = "softmax"
    topk_method: str = "plain"
    n_group: int = 0
    topk_group: int = 0
    norm_topk_prob: bool = True
    routed_scaling_factor: float = 1.0
    # Sliding-window attention (0 = full).
    sliding_window: int = 0
    # Gemma-family deltas: GELU-tanh gated MLP (vs SwiGLU), embeddings
    # scaled by sqrt(hidden_size), and zero-centered RMSNorm weights in
    # the CHECKPOINT (the loader adds 1 so rms_norm stays uniform).
    mlp_act: str = "silu"
    embed_scale: bool = False
    norm_zero_centered: bool = False
    # Qwen2-VL M-RoPE half-dim sections ((t, h, w) streams; empty =
    # standard 1D RoPE). Equal streams reduce M-RoPE to standard RoPE,
    # so text tokens and decode steps need no special handling; image
    # spans inside a prompt carry [3, L] positions (models/llama.py).
    mrope_section: tuple = ()
    # Disable head_dim<128 packed cache rows (kv_cache.kv_pack_factor).
    # Set by the executor (sharding.resolve_kv_packing) when tp doesn't
    # divide the packed head count — the unpacked layout keeps every
    # tp that divides num_kv_heads functional via the gather path.
    kv_pack_disable: bool = False
    # QKV projection bias (Qwen2-style).
    attn_bias: bool = False
    # Per-head RMSNorm on q and k before RoPE (Qwen3-style QK-norm).
    qk_norm: bool = False
    # Multi-head Latent Attention (DeepSeek-V2/V3). kv_lora_rank > 0 turns
    # MLA on: the paged cache stores ONE compressed latent row per token
    # (kv_lora_rank + qk_rope_head_dim floats) instead of per-head K/V —
    # e.g. 576 vs 2048 floats/token for a 70B-class GQA layout, a ~3.5x
    # HBM/bandwidth win for long contexts.
    kv_lora_rank: int = 0
    q_lora_rank: int = 0  # 0 = direct q projection (V2-Lite style)
    qk_nope_head_dim: int = 0
    qk_rope_head_dim: int = 0
    v_head_dim: int = 0
    # MoE shared experts (DeepSeek style): dense FFN of
    # n_shared_experts * moe_intermediate_size always active.
    n_shared_experts: int = 0
    # DeepSeek-V2/V3 heterogeneous stack: the first k layers use a dense
    # SwiGLU of `intermediate_size` instead of the MoE block (HF config
    # first_k_dense_replace). The param pytree splits into a `dense_layers`
    # prefix stack and the MoE `layers` suffix stack; each runs its own
    # lax.scan (models/deepseek.py _scan_stack).
    first_k_dense_replace: int = 0

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @property
    def is_mla(self) -> bool:
        return self.kv_lora_rank > 0

    @property
    def mla_row_dim(self) -> int:
        """True latent floats per token: c_kv + shared RoPE key."""
        return self.kv_lora_rank + self.qk_rope_head_dim

    @property
    def mla_cache_dim(self) -> int:
        """Latent cache lanes per token: mla_row_dim padded to a multiple
        of 128. Mosaic DMA slices need 128-aligned lane extents on real
        hardware (chip finding, round 3), so the pool stores zero-padded
        rows; q_lat pads with zeros too, making the extra lanes inert in
        every score/context contraction."""
        return (self.mla_row_dim + 127) // 128 * 128


def approx_param_count(cfg: ModelConfig) -> int:
    """Analytic parameter count (norm weights omitted — noise at scale).
    Single source for HBM budgeting: runtime/executor._decide_num_blocks
    sizes the KV pool with it and __graft_entry__'s dress rehearsal
    checks serving layouts against it."""
    E, L = cfg.hidden_size, cfg.num_layers
    if cfg.is_mla:
        dn, dr, dv = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim
        kvr, qr, Hq = cfg.kv_lora_rank, cfg.q_lora_rank, cfg.num_heads
        attn = (
            E * (kvr + dr)
            + Hq * kvr * (dn + dv)
            + Hq * dv * E
            + (E * qr + qr * Hq * (dn + dr) if qr else E * Hq * (dn + dr))
        )
    else:
        attn = (
            E * (cfg.num_heads + 2 * cfg.num_kv_heads) * cfg.head_dim
            + cfg.num_heads * cfg.head_dim * E
        )
    if cfg.is_moe:
        moe_mlp = 3 * E * (
            cfg.moe_intermediate_size * cfg.num_experts
            + cfg.n_shared_experts * cfg.moe_intermediate_size
        ) + E * cfg.num_experts  # router
    else:
        moe_mlp = 3 * E * cfg.intermediate_size
    kd = cfg.first_k_dense_replace
    mlp_total = (L - kd) * moe_mlp + kd * 3 * E * cfg.intermediate_size
    return (
        cfg.vocab_size * E * (1 if cfg.tie_word_embeddings else 2)
        + L * attn
        + mlp_total
    )


_REGISTRY: Dict[str, ModelConfig] = {}


def register(cfg: ModelConfig) -> ModelConfig:
    _REGISTRY[cfg.name] = cfg
    return cfg


def get_model_config(name: str) -> ModelConfig:
    if name not in _REGISTRY:
        raise KeyError(f"unknown model config '{name}'; have {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def list_model_configs():
    return sorted(_REGISTRY)


# --- Test-scale configs (CPU-runnable CI; SURVEY.md §4) ---------------------

register(
    ModelConfig(
        name="llama3-tiny",
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=32,
        max_position_embeddings=1024,
    )
)

register(
    ModelConfig(
        name="moe-tiny",
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=32,
        num_experts=8,
        num_experts_per_tok=2,
        moe_intermediate_size=128,
        max_position_embeddings=1024,
    )
)

register(
    ModelConfig(
        name="gemma-tiny",
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=32,
        rope_theta=10000.0,
        rms_norm_eps=1e-6,
        tie_word_embeddings=True,
        mlp_act="gelu_tanh",
        embed_scale=True,
        norm_zero_centered=True,
        max_position_embeddings=1024,
    )
)

# --- Production configs -----------------------------------------------------

register(
    ModelConfig(
        name="llama3-1b",
        vocab_size=128256,
        hidden_size=2048,
        intermediate_size=8192,
        num_layers=16,
        num_heads=32,
        num_kv_heads=8,
        head_dim=64,
        tie_word_embeddings=True,
    )
)

register(
    ModelConfig(
        name="llama3-3b",
        vocab_size=128256,
        hidden_size=3072,
        intermediate_size=8192,
        num_layers=28,
        num_heads=24,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=500000.0,
        tie_word_embeddings=True,
    )
)

register(
    ModelConfig(
        name="llama3-8b",
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
    )
)

register(
    ModelConfig(
        name="llama3-70b",
        vocab_size=128256,
        hidden_size=8192,
        intermediate_size=28672,
        num_layers=80,
        num_heads=64,
        num_kv_heads=8,
        head_dim=128,
    )
)

register(
    ModelConfig(
        name="qwen2-7b",
        vocab_size=152064,
        hidden_size=3584,
        intermediate_size=18944,
        num_layers=28,
        num_heads=28,
        num_kv_heads=4,
        head_dim=128,
        rope_theta=1000000.0,
        rms_norm_eps=1e-6,
        attn_bias=True,
    )
)

register(
    ModelConfig(
        name="qwen3-8b",
        vocab_size=151936,
        hidden_size=4096,
        intermediate_size=12288,
        num_layers=36,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=1000000.0,
        rms_norm_eps=1e-6,
        qk_norm=True,
    )
)

register(
    # Qwen3-30B-A3B: 128-expert top-8 MoE, no shared experts; router
    # weighting is softmax over the selected experts' logits, which the
    # shared _mlp already computes (identical to renormalized-top-k).
    ModelConfig(
        name="qwen3-30b-a3b",
        vocab_size=151936,
        hidden_size=2048,
        intermediate_size=6144,
        num_layers=48,
        num_heads=32,
        num_kv_heads=4,
        head_dim=128,
        rope_theta=1000000.0,
        rms_norm_eps=1e-6,
        qk_norm=True,
        num_experts=128,
        num_experts_per_tok=8,
        moe_intermediate_size=768,
    )
)

register(
    ModelConfig(
        name="qwen3-tiny",
        vocab_size=512,
        hidden_size=96,
        intermediate_size=256,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=24,
        rope_theta=10000.0,
        qk_norm=True,
    )
)

register(
    # head_dim 64 with 2 kv heads: exercises the packed-pair KV layout
    # (kv_cache.kv_pack_factor P=2 -> one 128-lane cache row per pair).
    ModelConfig(
        name="llama3-packed-tiny",
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=64,
        max_position_embeddings=1024,
    )
)

register(
    # The sharded-engine differential geometry (docs/SHARDING.md): 8 KV
    # heads so tp ∈ {2, 4, 8} all divide (llama3-tiny's Hkv=2 caps at
    # tp=2), head_dim 128 so every Pallas path is kernel-eligible
    # per-shard down to 1 head/shard (interpret mode on the virtual
    # mesh), and GQA ratio 2 so per-shard query packing still exercises
    # grouping. CPU-runnable; the same shape class as the llama3-70b
    # tp=8 serving layout (BASELINE round 3), just tiny.
    ModelConfig(
        name="llama3-shard-tiny",
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=16,
        num_kv_heads=8,
        head_dim=128,
        max_position_embeddings=1024,
    )
)

register(
    # The MoE serving differential geometry (docs/MOE.md): llama3-
    # shard-tiny's kernel-eligible attention dims (Hkv=8, D=128 — every
    # tp ∈ {1, 2, 4, 8} divides, every Pallas path eligible per-shard)
    # plus an 8-expert top-2 MoE whose dims keep every tp×ep
    # combination eligible too: X=8 divides ep ∈ {1, 2, 4, 8},
    # E=128 and Fm=256 are 128-lane multiples (the grouped-dispatch
    # kernel gate), and Fm%tp holds through tp=2. CPU-runnable; the
    # same shape class as the qwen3-30b-a3b / deepseek-v3 EP serving
    # layouts, just tiny.
    ModelConfig(
        name="moe-shard-tiny",
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=16,
        num_kv_heads=8,
        head_dim=128,
        num_experts=8,
        num_experts_per_tok=2,
        moe_intermediate_size=256,
        max_position_embeddings=1024,
    )
)

register(
    ModelConfig(
        name="qwen3-moe-tiny",
        vocab_size=512,
        hidden_size=96,
        intermediate_size=256,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=24,
        rope_theta=10000.0,
        qk_norm=True,
        num_experts=4,
        num_experts_per_tok=2,
        moe_intermediate_size=64,
    )
)

register(
    ModelConfig(
        name="deepseek-tiny",
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=4,
        num_kv_heads=4,  # MLA is effectively MHA over latents
        head_dim=32,  # unused by MLA paths (qk dims below rule)
        # Pairwise-DISTINCT dims (kvr != dn != dv) so any transposed or
        # double-applied projection fails shape checks instead of silently
        # computing garbage.
        kv_lora_rank=40,
        q_lora_rank=48,
        qk_nope_head_dim=32,
        qk_rope_head_dim=16,
        v_head_dim=24,
        max_position_embeddings=1024,
    )
)

register(
    ModelConfig(
        name="deepseek-moe-tiny",
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=4,
        num_kv_heads=4,
        head_dim=32,
        kv_lora_rank=40,
        q_lora_rank=0,  # V2-Lite-style direct q projection
        qk_nope_head_dim=32,
        qk_rope_head_dim=16,
        v_head_dim=24,
        num_experts=8,
        num_experts_per_tok=2,
        moe_intermediate_size=64,
        n_shared_experts=2,
        max_position_embeddings=1024,
    )
)

register(
    ModelConfig(
        # Real-V2/V3 shape: dense first layer + MoE suffix (HF
        # first_k_dense_replace) — drives the split-stack pytree paths.
        name="deepseek-hetero-tiny",
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=3,
        num_heads=4,
        num_kv_heads=4,
        head_dim=32,
        kv_lora_rank=40,
        q_lora_rank=48,
        qk_nope_head_dim=32,
        qk_rope_head_dim=16,
        v_head_dim=24,
        num_experts=8,
        num_experts_per_tok=2,
        moe_intermediate_size=64,
        n_shared_experts=2,
        first_k_dense_replace=1,
        max_position_embeddings=1024,
    )
)

register(
    ModelConfig(
        name="mixtral-8x7b",
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=1000000.0,
        num_experts=8,
        num_experts_per_tok=2,
        moe_intermediate_size=14336,
    )
)

register(
    ModelConfig(
        name="deepseek-v3",
        # arxiv 2412.19437 table 1 / HF config.json of DeepSeek-V3:
        # 671B total, 37B active, MLA + 256-expert MoE with 1 shared expert.
        vocab_size=129280,
        hidden_size=7168,
        intermediate_size=18432,
        num_layers=61,
        num_heads=128,
        num_kv_heads=128,
        head_dim=128,
        kv_lora_rank=512,
        q_lora_rank=1536,
        qk_nope_head_dim=128,
        qk_rope_head_dim=64,
        v_head_dim=128,
        rope_theta=10000.0,
        num_experts=256,
        num_experts_per_tok=8,
        moe_intermediate_size=2048,
        n_shared_experts=1,
        first_k_dense_replace=3,  # V3: first 3 layers dense
        rms_norm_eps=1e-6,
        # Real V3 ships yarn (config.json rope_scaling): 4k pretraining
        # context extended 40x; mscale_all_dim also scales the MLA
        # softmax temperature (models/deepseek.mla_softmax_scale).
        max_position_embeddings=163840,
        rope_scaling_type="yarn",
        rope_scaling_factor=40.0,
        rope_original_max_position=4096,
        rope_beta_fast=32.0,
        rope_beta_slow=1.0,
        rope_mscale=1.0,
        rope_mscale_all_dim=1.0,
    )
)
