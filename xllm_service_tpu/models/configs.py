"""Model architecture configs + registry.

The engine tier the reference delegates to a non-vendored CUDA submodule
(SURVEY.md §2.3) is first-class here. Configs cover the north-star families
(BASELINE.json): Llama-3 dense, Qwen2, and Mixtral/DeepSeek-style MoE.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    max_position_embeddings: int = 8192
    tie_word_embeddings: bool = False
    # MoE (0 experts = dense).
    num_experts: int = 0
    num_experts_per_tok: int = 0
    moe_intermediate_size: int = 0
    # Sliding-window attention (0 = full).
    sliding_window: int = 0
    # QKV projection bias (Qwen2-style).
    attn_bias: bool = False

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0


_REGISTRY: Dict[str, ModelConfig] = {}


def register(cfg: ModelConfig) -> ModelConfig:
    _REGISTRY[cfg.name] = cfg
    return cfg


def get_model_config(name: str) -> ModelConfig:
    if name not in _REGISTRY:
        raise KeyError(f"unknown model config '{name}'; have {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def list_model_configs():
    return sorted(_REGISTRY)


# --- Test-scale configs (CPU-runnable CI; SURVEY.md §4) ---------------------

register(
    ModelConfig(
        name="llama3-tiny",
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=32,
        max_position_embeddings=1024,
    )
)

register(
    ModelConfig(
        name="moe-tiny",
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=32,
        num_experts=8,
        num_experts_per_tok=2,
        moe_intermediate_size=128,
        max_position_embeddings=1024,
    )
)

# --- Production configs -----------------------------------------------------

register(
    ModelConfig(
        name="llama3-1b",
        vocab_size=128256,
        hidden_size=2048,
        intermediate_size=8192,
        num_layers=16,
        num_heads=32,
        num_kv_heads=8,
        head_dim=64,
        tie_word_embeddings=True,
    )
)

register(
    ModelConfig(
        name="llama3-3b",
        vocab_size=128256,
        hidden_size=3072,
        intermediate_size=8192,
        num_layers=28,
        num_heads=24,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=500000.0,
        tie_word_embeddings=True,
    )
)

register(
    ModelConfig(
        name="llama3-8b",
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
    )
)

register(
    ModelConfig(
        name="llama3-70b",
        vocab_size=128256,
        hidden_size=8192,
        intermediate_size=28672,
        num_layers=80,
        num_heads=64,
        num_kv_heads=8,
        head_dim=128,
    )
)

register(
    ModelConfig(
        name="qwen2-7b",
        vocab_size=152064,
        hidden_size=3584,
        intermediate_size=18944,
        num_layers=28,
        num_heads=28,
        num_kv_heads=4,
        head_dim=128,
        rope_theta=1000000.0,
        rms_norm_eps=1e-6,
        attn_bias=True,
    )
)

register(
    ModelConfig(
        name="mixtral-8x7b",
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=1000000.0,
        num_experts=8,
        num_experts_per_tok=2,
        moe_intermediate_size=14336,
    )
)
