"""Vision encoder for the EPD multimodal stage.

The reference's only vestige of multimodal serving is the chat-template
MMContent message model (reference jinja_chat_template.h:30-47) and the
EPD architecture notes — the encoder itself lives in the absent engine.
Here it is first-class: a compact ViT whose output tokens are injected
into the language model's prompt at media-marker positions
(models/llama.py prefill embed overrides).

TPU design points:
  * patchify is a reshape + one [P*P*3, E] matmul — no conv lowering
    needed, lands straight on the MXU;
  * layers are scan-stacked like the LM (one compiled body);
  * pooling to a FIXED number of output tokens (cfg.out_tokens) keeps the
    LM-side injection shape static — the placeholder expansion in the
    service tier uses the same constant.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp

from xllm_service_tpu.ops.norms import rms_norm

Params = Dict[str, Any]


@dataclass(frozen=True)
class VisionConfig:
    name: str
    image_size: int  # square inputs [S, S, 3]
    patch_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    out_tokens: int  # media tokens emitted per image (LM placeholders)
    out_dim: int  # LM hidden size to project into
    rms_norm_eps: float = 1e-5
    # Tower architecture: "rms" is the compact in-house ViT (RMSNorm,
    # SiLU, bias-free); "siglip" matches the HF SiglipVisionModel tower
    # (pre-LayerNorm with biases, tanh-GELU MLP, biased projections, no
    # class token) so SigLIP-layout VLM checkpoints load weight-for-weight
    # (runtime/weights.load_vision_checkpoint; HF-parity-tested). CLIP
    # towers (class token, pre_layrnorm, quick_gelu) are NOT supported —
    # the loader rejects their position-embedding shape. "qwen2vl"
    # matches the HF Qwen2VisionTransformer (2D rotary, fused biased QKV,
    # QuickGELU, LayerNorm eps 1e-6, PatchMerger 2x2 -> LLM dim) —
    # north-star config 4's named family, HF-parity-tested.
    # "qwen25vl" is the Qwen2.5-VL tower (HF
    # Qwen2_5_VisionTransformerPretrainedModel): RMSNorm blocks, gated
    # SiLU MLP with biases, WINDOW attention (window_size pixels; full
    # attention on fullatt_block_indexes layers), RMSNorm PatchMerger.
    arch: str = "rms"
    # qwen2vl/qwen25vl geometry (HF vision-config names).
    spatial_merge_size: int = 2
    temporal_patch_size: int = 2
    window_size: int = 112  # qwen25vl: attention window in PIXELS
    fullatt_block_indexes: tuple = ()  # qwen25vl: full-attention layers

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


_REGISTRY: Dict[str, VisionConfig] = {}


def register_vision(cfg: VisionConfig) -> VisionConfig:
    _REGISTRY[cfg.name] = cfg
    return cfg


def get_vision_config(name: str) -> VisionConfig:
    if name not in _REGISTRY:
        raise KeyError(f"unknown vision config '{name}'; have {sorted(_REGISTRY)}")
    return _REGISTRY[name]


register_vision(
    VisionConfig(
        name="vit-tiny",
        image_size=32,
        patch_size=8,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        out_tokens=4,
        out_dim=128,  # matches llama3-tiny hidden_size
    )
)

register_vision(
    VisionConfig(
        name="vit-base-patch14",
        image_size=336,
        patch_size=14,
        hidden_size=1024,
        intermediate_size=4096,
        num_layers=24,
        num_heads=16,
        out_tokens=64,
        out_dim=4096,  # llama3-8b hidden
    )
)


def layer_norm(x: jnp.ndarray, weight, bias, eps: float) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    mu = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(axis=-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps) * weight + bias
    return out.astype(x.dtype)


register_vision(
    VisionConfig(
        # Test-scale SigLIP-arch tower (CI drives the checkpoint loader
        # and the LayerNorm/GELU/bias path on it).
        name="siglip-tiny",
        image_size=32,
        patch_size=8,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        out_tokens=16,  # = num_patches: no pooling, LLaVA-style
        out_dim=128,
        rms_norm_eps=1e-6,
        arch="siglip",
    )
)

register_vision(
    VisionConfig(
        # HF google/siglip-base-patch16-384 vision tower dims.
        name="siglip-base-patch16-384",
        image_size=384,
        patch_size=16,
        hidden_size=768,
        intermediate_size=3072,
        num_layers=12,
        num_heads=12,
        out_tokens=576,
        out_dim=4096,
        rms_norm_eps=1e-6,
        arch="siglip",
    )
)


register_vision(
    VisionConfig(
        # Test-scale Qwen2-VL-arch tower (CI drives the HF-parity path;
        # dims follow Qwen2VLVisionConfig ratios at toy size).
        name="qwen2vl-tiny",
        image_size=32,
        patch_size=8,
        hidden_size=64,          # embed_dim
        intermediate_size=256,   # embed_dim * mlp_ratio(4)
        num_layers=2,
        num_heads=4,
        out_tokens=4,            # (32/8)^2 / merge^2
        out_dim=128,             # LM hidden (llama3-tiny / qwen2-tiny)
        rms_norm_eps=1e-6,
        arch="qwen2vl",
    )
)

register_vision(
    VisionConfig(
        # HF Qwen/Qwen2-VL-7B-Instruct visual tower dims (fixed 448x448
        # inputs here; the HF processor's native dynamic resolution maps
        # to per-request grids — this config serves the square default).
        name="qwen2-vl-7b-visual",
        image_size=448,
        patch_size=14,
        hidden_size=1280,
        intermediate_size=5120,
        num_layers=32,
        num_heads=16,
        out_tokens=256,          # (448/14)^2 / 4
        out_dim=3584,
        rms_norm_eps=1e-6,
        arch="qwen2vl",
    )
)


register_vision(
    VisionConfig(
        # Test-scale Qwen2.5-VL-arch tower: 8x8 patch grid -> 4x4 merge
        # units -> 2x2 windows of 2x2 units (window_size 32px), full
        # attention on the last block — the real family's layer mix.
        name="qwen25vl-tiny",
        image_size=64,
        patch_size=8,
        hidden_size=64,
        intermediate_size=128,
        num_layers=4,
        num_heads=4,
        out_tokens=16,
        out_dim=128,
        rms_norm_eps=1e-6,
        arch="qwen25vl",
        window_size=32,
        fullatt_block_indexes=(3,),
    )
)

register_vision(
    VisionConfig(
        # HF Qwen/Qwen2.5-VL-7B-Instruct visual tower dims (square 448
        # serving default; window 112px -> 4x4 merge-unit windows).
        name="qwen2.5-vl-7b-visual",
        image_size=448,
        patch_size=14,
        hidden_size=1280,
        intermediate_size=3420,
        num_layers=32,
        num_heads=16,
        out_tokens=256,
        out_dim=3584,
        rms_norm_eps=1e-6,
        arch="qwen25vl",
        window_size=112,
        fullatt_block_indexes=(7, 15, 23, 31),
    )
)


def init_vision_params(cfg: VisionConfig, key, dtype=jnp.bfloat16) -> Params:
    keys = jax.random.split(key, 12)
    E, L = cfg.hidden_size, cfg.num_layers
    D = E // cfg.num_heads
    F = cfg.intermediate_size
    patch_dim = cfg.patch_size * cfg.patch_size * 3

    def w(k, shape, fan_in):
        return (
            jax.random.normal(k, shape, jnp.float32) / jnp.sqrt(fan_in)
        ).astype(dtype)

    if cfg.arch == "qwen25vl":
        F = cfg.intermediate_size
        M = E * cfg.spatial_merge_size**2
        qdim = patch_dim * cfg.temporal_patch_size
        return {
            "patch_embed": w(keys[0], (qdim, E), qdim),
            "layers": {
                "ln1_w": jnp.ones((L, E), jnp.float32),
                "wqkv": w(keys[2], (L, E, 3 * E), E),
                "bqkv": jnp.zeros((L, 3 * E), dtype),
                "wo": w(keys[3], (L, E, E), E),
                "bo": jnp.zeros((L, E), dtype),
                "ln2_w": jnp.ones((L, E), jnp.float32),
                "w_gate": w(keys[4], (L, E, F), E),
                "b_gate": jnp.zeros((L, F), dtype),
                "w_up": w(keys[5], (L, E, F), E),
                "b_up": jnp.zeros((L, F), dtype),
                "w_down": w(keys[6], (L, F, E), F),
                "b_down": jnp.zeros((L, E), dtype),
            },
            "merger_ln_w": jnp.ones((E,), jnp.float32),
            "merger_fc1": w(keys[7], (M, M), M),
            "merger_b1": jnp.zeros((M,), dtype),
            "merger_fc2": w(keys[8], (M, cfg.out_dim), M),
            "merger_b2": jnp.zeros((cfg.out_dim,), dtype),
        }
    if cfg.arch == "qwen2vl":
        F = cfg.intermediate_size
        M = E * cfg.spatial_merge_size**2
        qdim = patch_dim * cfg.temporal_patch_size
        return {
            "patch_embed": w(keys[0], (qdim, E), qdim),
            "layers": {
                "ln1_w": jnp.ones((L, E), jnp.float32),
                "ln1_b": jnp.zeros((L, E), jnp.float32),
                "wqkv": w(keys[2], (L, E, 3 * E), E),
                "bqkv": jnp.zeros((L, 3 * E), dtype),
                "wo": w(keys[3], (L, E, E), E),
                "bo": jnp.zeros((L, E), dtype),
                "ln2_w": jnp.ones((L, E), jnp.float32),
                "ln2_b": jnp.zeros((L, E), jnp.float32),
                "fc1": w(keys[4], (L, E, F), E),
                "b1": jnp.zeros((L, F), dtype),
                "fc2": w(keys[5], (L, F, E), F),
                "b2": jnp.zeros((L, E), dtype),
            },
            "merger_ln_w": jnp.ones((E,), jnp.float32),
            "merger_ln_b": jnp.zeros((E,), jnp.float32),
            "merger_fc1": w(keys[6], (M, M), M),
            "merger_b1": jnp.zeros((M,), dtype),
            "merger_fc2": w(keys[7], (M, cfg.out_dim), M),
            "merger_b2": jnp.zeros((cfg.out_dim,), dtype),
        }
    if cfg.arch == "siglip":
        return {
            "patch_embed": w(keys[0], (patch_dim, E), patch_dim),
            "patch_bias": jnp.zeros((E,), dtype),
            "pos_embed": w(keys[1], (cfg.num_patches, E), E),
            "layers": {
                "ln1_w": jnp.ones((L, E), jnp.float32),
                "ln1_b": jnp.zeros((L, E), jnp.float32),
                "wq": w(keys[2], (L, E, E), E),
                "bq": jnp.zeros((L, E), dtype),
                "wk": w(keys[3], (L, E, E), E),
                "bk": jnp.zeros((L, E), dtype),
                "wv": w(keys[4], (L, E, E), E),
                "bv": jnp.zeros((L, E), dtype),
                "wo": w(keys[5], (L, E, E), E),
                "bo": jnp.zeros((L, E), dtype),
                "ln2_w": jnp.ones((L, E), jnp.float32),
                "ln2_b": jnp.zeros((L, E), jnp.float32),
                "w_up": w(keys[6], (L, E, F), E),
                "b_up": jnp.zeros((L, F), dtype),
                "w_down": w(keys[7], (L, F, E), F),
                "b_down": jnp.zeros((L, E), dtype),
            },
            "final_norm_w": jnp.ones((E,), jnp.float32),
            "final_norm_b": jnp.zeros((E,), jnp.float32),
            "proj": w(keys[8], (E, cfg.out_dim), E),
            "proj_bias": jnp.zeros((cfg.out_dim,), dtype),
        }
    return {
        "patch_embed": w(keys[0], (patch_dim, E), patch_dim),
        "pos_embed": w(keys[1], (cfg.num_patches, E), E),
        "layers": {
            "attn_norm": jnp.ones((L, E), jnp.float32),
            "wqkv": w(keys[2], (L, E, 3 * E), E),
            "wo": w(keys[3], (L, E, E), E),
            "mlp_norm": jnp.ones((L, E), jnp.float32),
            "w_up": w(keys[4], (L, E, F), E),
            "w_down": w(keys[5], (L, F, E), F),
        },
        "final_norm": jnp.ones((E,), jnp.float32),
        # pooled media tokens -> LM hidden (LLaVA-style connector)
        "proj": w(keys[6], (E, cfg.out_dim), E),
    }


def _patchify(images: jnp.ndarray, patch: int) -> jnp.ndarray:
    """[B, S, S, 3] -> [B, N, patch*patch*3] (pure reshape/transpose)."""
    B, S, _, C = images.shape
    n = S // patch
    x = images.reshape(B, n, patch, n, patch, C)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(B, n * n, patch * patch * C)


def _encode_siglip(
    params: Params, cfg: VisionConfig, images: jnp.ndarray
) -> jnp.ndarray:
    """SigLIP/CLIP-style tower: pre-LayerNorm blocks with biases,
    tanh-GELU MLP — the HF SiglipVisionModel computation, weight-loaded
    by runtime/weights.load_vision_checkpoint."""
    B = images.shape[0]
    H, D = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    eps = cfg.rms_norm_eps
    x = _patchify(images.astype(params["patch_embed"].dtype), cfg.patch_size)
    x = jnp.einsum("bnp,pe->bne", x, params["patch_embed"]) + params["patch_bias"]
    x = x + params["pos_embed"][None]

    def layer_fn(x, lp):
        h = layer_norm(x, lp["ln1_w"], lp["ln1_b"], eps)
        N = h.shape[1]
        q = (jnp.einsum("bne,ef->bnf", h, lp["wq"]) + lp["bq"]).reshape(B, N, H, D)
        k = (jnp.einsum("bne,ef->bnf", h, lp["wk"]) + lp["bk"]).reshape(B, N, H, D)
        v = (jnp.einsum("bne,ef->bnf", h, lp["wv"]) + lp["bv"]).reshape(B, N, H, D)
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
        ) * (D**-0.5)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
        attn = attn.reshape(B, N, -1).astype(x.dtype)
        x = x + jnp.einsum("bne,ef->bnf", attn, lp["wo"]) + lp["bo"]
        h = layer_norm(x, lp["ln2_w"], lp["ln2_b"], eps)
        h = jnp.einsum("bne,ef->bnf", h, lp["w_up"]) + lp["b_up"]
        h = jax.nn.gelu(h, approximate=True)
        x = x + jnp.einsum("bnf,fe->bne", h, lp["w_down"]) + lp["b_down"]
        return x, None

    x, _ = jax.lax.scan(layer_fn, x, params["layers"])
    x = layer_norm(x, params["final_norm_w"], params["final_norm_b"], eps)
    N = x.shape[1]
    G = max(N // cfg.out_tokens, 1)
    pooled = x[:, : G * cfg.out_tokens].reshape(
        B, cfg.out_tokens, G, cfg.hidden_size
    ).mean(axis=2)
    return (
        jnp.einsum("bte,ed->btd", pooled, params["proj"]) + params["proj_bias"]
    )


def _qwen2vl_patch_rows(images: jnp.ndarray, cfg: VisionConfig):
    """HF Qwen2VLImageProcessor patch arrangement for a square still
    image: rows ordered (h_group, w_group, merge_h, merge_w) so the
    PatchMerger takes 4 CONSECUTIVE rows per output token; each row is
    the [C, T, Ph, Pw] flattened patch with the single frame repeated to
    temporal_patch_size. Returns (rows [B, N, C*T*P*P], h_ids, w_ids)."""
    B, S, _, C = images.shape
    P, m, T = cfg.patch_size, cfg.spatial_merge_size, cfg.temporal_patch_size
    g = S // P
    gg = g // m
    x = images.reshape(B, gg, m, P, gg, m, P, C)
    # -> [B, hg, wg, mh, mw, C, Ph, Pw]
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 7, 3, 6))
    rows = x.reshape(B, g * g, C, 1, P, P)
    rows = jnp.broadcast_to(
        rows[:, :, :, None, 0], (B, g * g, C, T, P, P)
    ).reshape(B, g * g, C * T * P * P)
    import numpy as _np

    hg, wg, mh, mw = _np.meshgrid(
        _np.arange(gg), _np.arange(gg), _np.arange(m), _np.arange(m),
        indexing="ij",
    )
    # match the row order (hg, wg, mh, mw)
    h_ids = (hg * m + mh).reshape(-1)
    w_ids = (wg * m + mw).reshape(-1)
    return rows, h_ids, w_ids


def _rot_half(t):
    a, b = jnp.split(t, 2, axis=-1)
    return jnp.concatenate([-b, a], axis=-1)


def _qwen2vl_rope_tables(h_ids, w_ids, D: int):
    """2D vision rotary tables, shared by both Qwen-VL generations:
    VisionRotaryEmbedding(head_dim // 2) -> inv_freq of length
    head_dim//4 per axis; emb = cat(h_freqs, w_freqs) doubled. Returns
    (cos, sin) as [N, D] float32."""
    import numpy as _np

    hd4 = D // 4
    inv = 1.0 / (
        10000.0 ** (_np.arange(0, hd4, dtype=_np.float64) / hd4)
    )
    half = _np.concatenate(
        [h_ids[:, None] * inv[None], w_ids[:, None] * inv[None]], axis=1
    )  # [N, D/2]
    emb = _np.concatenate([half, half], axis=1)  # [N, D]
    return (
        jnp.asarray(_np.cos(emb), jnp.float32),
        jnp.asarray(_np.sin(emb), jnp.float32),
    )


def _merger_mlp(params: Params, cfg: VisionConfig, x: jnp.ndarray):
    """PatchMerger tail shared by both generations: group m^2 consecutive
    rows, fc1 -> exact-erf GELU -> fc2 (nn.GELU default is erf)."""
    B, N = x.shape[0], x.shape[1]
    m2 = cfg.spatial_merge_size**2
    x = x.reshape(B, N // m2, m2 * cfg.hidden_size)
    h = jnp.einsum("bnm,mf->bnf", x, params["merger_fc1"]) + params["merger_b1"]
    h = jax.nn.gelu(h, approximate=False)
    return (
        jnp.einsum("bnf,fd->bnd", h, params["merger_fc2"])
        + params["merger_b2"]
    )


def _qwen2vl_video_rows(frames: jnp.ndarray, cfg: VisionConfig):
    """HF Qwen2VLImageProcessor patch arrangement for VIDEO frames
    [T, S, S, C] (T a multiple of temporal_patch_size): each temporal
    group of tps REAL frames becomes one grid of [C, tps, Ph, Pw]
    flattened patches in the same (hg, wg, mh, mw) spatial order as the
    still-image path. Returns (rows [G, g*g, C*tps*P*P], h_ids, w_ids)
    with G = T // tps temporal groups on the leading axis."""
    T, S, _, C = frames.shape
    P, m, tps = cfg.patch_size, cfg.spatial_merge_size, cfg.temporal_patch_size
    assert T % tps == 0, (T, tps)
    G = T // tps
    g = S // P
    gg = g // m
    x = frames.reshape(G, tps, gg, m, P, gg, m, P, C)
    # -> [G, hg, wg, mh, mw, C, tps, Ph, Pw]
    x = jnp.transpose(x, (0, 2, 5, 3, 6, 8, 1, 4, 7))
    rows = x.reshape(G, g * g, C * tps * P * P)
    import numpy as _np

    hg, wg, mh, mw = _np.meshgrid(
        _np.arange(gg), _np.arange(gg), _np.arange(m), _np.arange(m),
        indexing="ij",
    )
    h_ids = (hg * m + mh).reshape(-1)
    w_ids = (wg * m + mw).reshape(-1)
    return rows, h_ids, w_ids


def encode_video(
    params: Params, cfg: VisionConfig, frames: jnp.ndarray
) -> jnp.ndarray:
    """[T, S, S, 3] video frames -> media tokens [G * tokens_per_slice,
    out_dim], G = T // temporal_patch_size.

    Both Qwen-VL towers attend PER temporal slice (HF cu_seqlens
    repeats grid_h*grid_w per grid_t; Qwen2.5-VL additionally computes
    its window indices per slice), so the group axis rides the shared
    encoder body's batch dimension — each slice is an independent
    attention span with the same (h, w) rotary tables, exactly the HF
    semantics."""
    if cfg.arch not in ("qwen2vl", "qwen25vl"):
        raise NotImplementedError(
            f"video encoding is implemented for the qwen2vl/qwen25vl "
            f"towers only (got arch {cfg.arch!r})"
        )
    rows, h_ids, w_ids = _qwen2vl_video_rows(
        frames.astype(params["patch_embed"].dtype), cfg
    )
    body = _qwen25vl_body if cfg.arch == "qwen25vl" else _qwen2vl_body
    out = body(params, cfg, rows, h_ids, w_ids)  # [G, n, D]
    return out.reshape(-1, out.shape[-1])


def _encode_qwen2vl(
    params: Params, cfg: VisionConfig, images: jnp.ndarray
) -> jnp.ndarray:
    """HF Qwen2VisionTransformer: bias-free Conv3d patch embed (a matmul
    over the flattened [C, T, P, P] patch), 2D rotary position embedding
    over (h, w) patch ids, pre-LayerNorm blocks with fused biased QKV +
    QuickGELU MLP, full (non-causal) attention over the image's patches,
    then PatchMerger (ln_q -> 2x2 concat -> GELU MLP -> LLM dim).
    Reference: transformers modeling_qwen2_vl.py."""
    rows, h_ids, w_ids = _qwen2vl_patch_rows(
        images.astype(params["patch_embed"].dtype), cfg
    )
    return _qwen2vl_body(params, cfg, rows, h_ids, w_ids)


def _qwen2vl_body(
    params: Params, cfg: VisionConfig, rows: jnp.ndarray, h_ids, w_ids
) -> jnp.ndarray:
    """Shared Qwen2-VL encoder body over pre-arranged patch rows
    [B, N, C*tps*P*P]: still images put images on the batch axis; videos
    put temporal groups there (per-slice attention)."""
    B = rows.shape[0]
    H, D = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    x = jnp.einsum("bnp,pe->bne", rows, params["patch_embed"])  # [B, N, E]

    cos_t, sin_t = _qwen2vl_rope_tables(h_ids, w_ids, D)
    cos = cos_t[None, :, None, :]
    sin = sin_t[None, :, None, :]
    rot_half = _rot_half

    def layer_fn(x, lp):
        h = layer_norm(x, lp["ln1_w"], lp["ln1_b"], cfg.rms_norm_eps)
        N = h.shape[1]
        qkv = jnp.einsum("bne,ef->bnf", h, lp["wqkv"]) + lp["bqkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, N, H, D).astype(jnp.float32)
        k = k.reshape(B, N, H, D).astype(jnp.float32)
        v = v.reshape(B, N, H, D).astype(jnp.float32)
        q = q * cos + rot_half(q) * sin
        k = k * cos + rot_half(k) * sin
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D**-0.5)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        attn = attn.reshape(B, N, -1).astype(x.dtype)
        x = x + jnp.einsum("bne,ef->bnf", attn, lp["wo"]) + lp["bo"]
        h = layer_norm(x, lp["ln2_w"], lp["ln2_b"], cfg.rms_norm_eps)
        h = jnp.einsum("bne,ef->bnf", h, lp["fc1"]) + lp["b1"]
        h = h * jax.nn.sigmoid(1.702 * h)  # QuickGELU
        x = x + jnp.einsum("bnf,fe->bne", h, lp["fc2"]) + lp["b2"]
        return x, None

    x, _ = jax.lax.scan(layer_fn, x, params["layers"])
    x = layer_norm(
        x, params["merger_ln_w"], params["merger_ln_b"], cfg.rms_norm_eps
    )
    return _merger_mlp(params, cfg, x)


def _qwen25_window_perm(cfg: VisionConfig):
    """Merge-UNIT permutation into window order (HF get_window_index for
    a square grid with no padding): units (hg, wg) row-major -> windows
    (win_h, win_w) of win x win units each, units row-major inside.
    Returns (unit_perm [U], inverse [U], win_units) as numpy."""
    import numpy as _np

    gg = cfg.image_size // cfg.patch_size // cfg.spatial_merge_size
    wu = cfg.window_size // cfg.spatial_merge_size // cfg.patch_size
    if wu <= 0 or gg % wu:
        raise ValueError(
            f"window_size {cfg.window_size} must cover a whole number of "
            f"merge units dividing the {gg}-unit grid"
        )
    idx = _np.arange(gg * gg).reshape(gg // wu, wu, gg // wu, wu)
    perm = idx.transpose(0, 2, 1, 3).reshape(-1)
    return perm, _np.argsort(perm), wu


def _encode_qwen25vl(
    params: Params, cfg: VisionConfig, images: jnp.ndarray
) -> jnp.ndarray:
    rows, h_ids, w_ids = _qwen2vl_patch_rows(
        images.astype(params["patch_embed"].dtype), cfg
    )
    return _qwen25vl_body(params, cfg, rows, h_ids, w_ids)


def _qwen25vl_body(
    params: Params, cfg: VisionConfig, rows: jnp.ndarray, h_ids, w_ids
) -> jnp.ndarray:
    """HF Qwen2_5_VisionTransformer: the qwen2vl patch pipeline with
    RMSNorm blocks, gated-SiLU MLP (biased), and WINDOW attention —
    hidden states permute into window order at merge-unit granularity,
    windowed layers attend within each (equal-size) window, the layers
    in fullatt_block_indexes attend globally, and the merger output
    permutes back. One scanned block body (lax.cond picks the attention
    scope per layer — a 32-deep python unroll would inflate the traced
    HLO 32x). Still images ride the batch axis; VIDEO temporal slices
    do too (HF computes window indices AND full-attention cu_seqlens
    per slice, so per-slice batching is exactly its semantics).
    Reference: transformers modeling_qwen2_5_vl.py."""
    import numpy as _np

    B = rows.shape[0]
    H, D = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    m2 = cfg.spatial_merge_size**2
    x = jnp.einsum("bnp,pe->bne", rows, params["patch_embed"])  # [B, N, E]
    N = x.shape[1]

    unit_perm, unit_inv, wu = _qwen25_window_perm(cfg)
    row_perm = (
        unit_perm[:, None] * m2 + _np.arange(m2)[None, :]
    ).reshape(-1)
    x = x[:, jnp.asarray(row_perm)]
    W = wu * wu * m2  # rows per window (all equal: no padding)
    nW = N // W

    cos_t, sin_t = _qwen2vl_rope_tables(h_ids[row_perm], w_ids[row_perm], D)
    cos = cos_t[None, :, None, :]
    sin = sin_t[None, :, None, :]

    def attend(q, k, v):
        # q/k/v [..., T, H, D] f32 within one attention scope
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D**-0.5)
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, v)

    fullatt = jnp.asarray(
        [li in cfg.fullatt_block_indexes for li in range(cfg.num_layers)]
    )

    def layer_fn(x, scanned):
        lp, full = scanned
        h = rms_norm(x, lp["ln1_w"], cfg.rms_norm_eps)
        qkv = jnp.einsum("bne,ef->bnf", h, lp["wqkv"]) + lp["bqkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, N, H, D).astype(jnp.float32)
        k = k.reshape(B, N, H, D).astype(jnp.float32)
        v = v.reshape(B, N, H, D).astype(jnp.float32)
        q = q * cos + _rot_half(q) * sin
        k = k * cos + _rot_half(k) * sin
        attn = jax.lax.cond(
            full,
            lambda args: attend(*args),
            lambda args: attend(
                *(t.reshape(B * nW, W, H, D) for t in args)
            ).reshape(B, N, H, D),
            (q, k, v),
        )
        attn = attn.reshape(B, N, -1).astype(x.dtype)
        x = x + jnp.einsum("bne,ef->bnf", attn, lp["wo"]) + lp["bo"]
        h = rms_norm(x, lp["ln2_w"], cfg.rms_norm_eps)
        gate = jnp.einsum("bne,ef->bnf", h, lp["w_gate"]) + lp["b_gate"]
        up = jnp.einsum("bne,ef->bnf", h, lp["w_up"]) + lp["b_up"]
        x = x + (
            jnp.einsum("bnf,fe->bne", jax.nn.silu(gate) * up, lp["w_down"])
            + lp["b_down"]
        )
        return x, None

    x, _ = jax.lax.scan(layer_fn, x, (params["layers"], fullatt))
    x = rms_norm(x, params["merger_ln_w"], cfg.rms_norm_eps)
    out = _merger_mlp(params, cfg, x)
    return out[:, jnp.asarray(unit_inv)]


def encode_images(
    params: Params, cfg: VisionConfig, images: jnp.ndarray
) -> jnp.ndarray:
    """[B, S, S, 3] float in [0, 1] -> media tokens [B, out_tokens, out_dim]."""
    if cfg.arch == "siglip":
        return _encode_siglip(params, cfg, images)
    if cfg.arch == "qwen2vl":
        return _encode_qwen2vl(params, cfg, images)
    if cfg.arch == "qwen25vl":
        return _encode_qwen25vl(params, cfg, images)
    B = images.shape[0]
    H, D = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    x = _patchify(images.astype(params["patch_embed"].dtype), cfg.patch_size)
    x = jnp.einsum("bnp,pe->bne", x, params["patch_embed"])
    x = x + params["pos_embed"][None]

    def layer_fn(x, lp):
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        qkv = jnp.einsum("bne,ef->bnf", h, lp["wqkv"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        N = q.shape[1]
        q = q.reshape(B, N, H, D)
        k = k.reshape(B, N, H, D)
        v = v.reshape(B, N, H, D)
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
        ) * (D**-0.5)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
        attn = attn.reshape(B, N, -1).astype(x.dtype)
        x = x + jnp.einsum("bne,ef->bnf", attn, lp["wo"])
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        h = jnp.einsum("bne,ef->bnf", h, lp["w_up"])
        x = x + jnp.einsum("bnf,fe->bne", jax.nn.silu(h), lp["w_down"])
        return x, None

    x, _ = jax.lax.scan(layer_fn, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    # Pool N patches into out_tokens groups (mean), then project to LM dim.
    N = x.shape[1]
    G = max(N // cfg.out_tokens, 1)
    pooled = x[:, : G * cfg.out_tokens].reshape(
        B, cfg.out_tokens, G, cfg.hidden_size
    ).mean(axis=2)
    return jnp.einsum("bte,ed->btd", pooled, params["proj"])
