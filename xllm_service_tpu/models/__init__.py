"""Model families (engine tier, SURVEY.md §2.3).

Every module exports the same function surface — init_params / decode_step /
prefill_batch_step / forward_dense — over the shared paged-cache runtime;
`get_module(cfg)` dispatches on the architecture the config describes so the
executor never branches on family internals.
"""

from __future__ import annotations

from xllm_service_tpu.models.configs import ModelConfig


def get_module(cfg: ModelConfig):
    """The model-family module for a config: MLA configs (kv_lora_rank > 0)
    run models/deepseek.py; everything else (Llama/Qwen2/Mixtral-style
    GQA + optional MoE) runs models/llama.py."""
    if cfg.is_mla:
        from xllm_service_tpu.models import deepseek

        return deepseek
    from xllm_service_tpu.models import llama

    return llama


def cache_row_dims(cfg: ModelConfig):
    """(head_axis, row_dim) of one paged-cache row — delegated to the
    family module, the single source of truth for its cache layout."""
    return get_module(cfg).cache_row_dims(cfg)


def num_caches(cfg: ModelConfig) -> int:
    """Paged-cache array count: 2 (K + V) for GQA; 1 (latent) for MLA —
    delegated to the family module."""
    return get_module(cfg).NUM_CACHES
