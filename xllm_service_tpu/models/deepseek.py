"""DeepSeek-V2/V3-family model: Multi-head Latent Attention + (optionally)
shared-expert MoE, over the same paged-cache runtime as Llama.

Engine-tier component (SURVEY.md §2.3 — the reference's engine submodule is
absent; BASELINE.json names "DeepSeek-V3 / Mixtral (MoE + expert-parallel
decode)" as north-star config 3). TPU-first design choices:

  * the paged cache stores ONE latent row per token
    (concat(c_kv[kv_lora_rank], k_pe[qk_rope_head_dim]) — e.g. 576 floats
    for V3 vs 2048 for a 70B-class GQA layout), so decode's HBM traffic —
    the bound resource — shrinks ~3.5x on top of any int8 win;
  * decode runs in ABSORBED form (q_nope @ W_UK into latent space; W_UV
    applied once to the attention-weighted latent), so per-head K/V for
    cached tokens is never materialized — scores are one [Hq, C] x [T, C]
    matmul per sequence, MXU-friendly;
  * the module exports the same function surface as models/llama.py
    (init_params / decode_step / prefill_batch_step / forward_dense), so
    the executor, engine, PD migration, and host tiers are unchanged; the
    latent cache rides the k_cache slot ([L, N, 1, BS, C]) and the v_cache
    slot is a 1-element dummy (models.get_module() reports num_caches=1).

Interface contract mirrored from models/llama.py; MLA math follows the
DeepSeek-V2 paper (arxiv 2405.04434 §2.1) / V3 (arxiv 2412.19437).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from xllm_service_tpu.models.configs import ModelConfig
from xllm_service_tpu.models.llama import _mlp, _mlp_block, _unembed
from xllm_service_tpu.ops import kv_cache as kv_cache_ops
from xllm_service_tpu.ops.attention import (
    mla_paged_attention,
    mla_prefill_attention,
)
from xllm_service_tpu.ops import rope as rope_ops
from xllm_service_tpu.ops.norms import rms_norm
from xllm_service_tpu.ops.quant import wdtype, wt

Params = Dict[str, Any]

NUM_CACHES = 1  # latent cache only — no separate V cache

# Stacked matmul leaves eligible for int8 weight quantization. Scales are
# per-axis(-1)-channel over axis -2 (ops/quant.py); for most leaves that
# is per-OUTPUT-channel over the contraction. Exception: w_uk's absorbed
# use (_absorb_q) contracts its LAST axis (dn), so its scales are
# per-contracting-channel there — numerically fine because leaves
# dequantize before the matmul, but don't assume the per-output invariant
# when adding leaves or changing the quantization axis.
QUANTIZABLE_WEIGHT_LEAVES = (
    "w_dkv", "w_uk", "w_uv", "wo", "w_dq", "w_uq", "w_q",
    "w_gate", "w_up", "w_down", "w_sh_gate", "w_sh_up", "w_sh_down",
)


def cache_row_dims(cfg: ModelConfig) -> Tuple[int, int]:
    """(heads, row_dim) of one cache row: MLA caches one [C] latent per
    token (head axis 1), vs (Hkv, head_dim) for GQA models."""
    return 1, cfg.mla_cache_dim


def mla_softmax_scale(cfg: ModelConfig) -> float:
    """Score scale for MLA attention: (dn + dr)^-0.5, times the yarn
    temperature correction real DeepSeek-V2/V3 checkpoints apply — HF
    DeepseekV2/V3Attention multiplies its softmax scale by
    yarn_get_mscale(factor, mscale_all_dim)^2 when rope_scaling carries
    mscale_all_dim."""
    scale = (cfg.qk_nope_head_dim + cfg.qk_rope_head_dim) ** -0.5
    if cfg.rope_scaling_type == "yarn" and cfg.rope_mscale_all_dim:
        m = rope_ops.yarn_mscale(
            cfg.rope_scaling_factor, cfg.rope_mscale_all_dim
        )
        scale *= m * m
    return scale


def _layer_stack(
    cfg: ModelConfig, key: jax.Array, dtype, n: int, moe: bool
) -> Dict[str, jnp.ndarray]:
    """One stacked-layer leaf dict of `n` layers: MLA attention plus either
    the MoE block (`moe=True`, dims from moe_intermediate_size) or a dense
    SwiGLU (`moe=False`, dims from intermediate_size)."""
    E, Hq = cfg.hidden_size, cfg.num_heads
    dn, dr, dv = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim
    kvr, qr = cfg.kv_lora_rank, cfg.q_lora_rank
    keys = jax.random.split(key, 14)

    def norm_init(shape):
        return jnp.ones(shape, dtype=jnp.float32)

    def w(key, shape, fan_in):
        return (
            jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(fan_in)
        ).astype(dtype)

    layers: Dict[str, jnp.ndarray] = {
        "attn_norm": norm_init((n, E)),
        "mlp_norm": norm_init((n, E)),
        # KV down-projection to the shared latent + rope key.
        "w_dkv": w(keys[0], (n, E, kvr + dr), E),
        "kv_norm": norm_init((n, kvr)),
        # Per-head up-projections OUT of the latent space.
        "w_uk": w(keys[1], (n, Hq, kvr, dn), kvr),
        "w_uv": w(keys[2], (n, Hq, kvr, dv), kvr),
        "wo": w(keys[3], (n, Hq * dv, E), Hq * dv),
    }
    if qr > 0:
        layers["w_dq"] = w(keys[4], (n, E, qr), E)
        layers["q_norm"] = norm_init((n, qr))
        layers["w_uq"] = w(keys[5], (n, qr, Hq * (dn + dr)), qr)
    else:
        layers["w_q"] = w(keys[5], (n, E, Hq * (dn + dr)), E)
    if moe:
        X, Fm = cfg.num_experts, cfg.moe_intermediate_size
        layers.update(
            {
                "router": w(keys[6], (n, E, X), E),
                "w_gate": w(keys[7], (n, X, E, Fm), E),
                "w_up": w(keys[8], (n, X, E, Fm), E),
                "w_down": w(keys[9], (n, X, Fm, E), Fm),
            }
        )
        if cfg.topk_method == "noaux_tc":
            layers["router_bias"] = jnp.zeros((n, X), jnp.float32)
        if cfg.n_shared_experts > 0:
            Fs = cfg.n_shared_experts * Fm
            layers.update(
                {
                    "w_sh_gate": w(keys[10], (n, E, Fs), E),
                    "w_sh_up": w(keys[11], (n, E, Fs), E),
                    "w_sh_down": w(keys[12], (n, Fs, E), Fs),
                }
            )
    else:
        F = cfg.intermediate_size
        layers.update(
            {
                "w_gate": w(keys[7], (n, E, F), E),
                "w_up": w(keys[8], (n, E, F), E),
                "w_down": w(keys[9], (n, F, E), F),
            }
        )
    return layers


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    """Param pytree. With first_k_dense_replace > 0 (real DeepSeek-V2/V3:
    HF config first_k_dense_replace, the first layers dense) the stack
    splits: `dense_layers` holds the k-layer dense prefix, `layers` the
    (L - k)-layer MoE suffix — each runs its own lax.scan."""
    E, L = cfg.hidden_size, cfg.num_layers
    kd = cfg.first_k_dense_replace
    k_embed, k_lm, k_stack, k_dense = jax.random.split(key, 4)

    def w(key, shape, fan_in):
        return (
            jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(fan_in)
        ).astype(dtype)

    params: Params = {
        "embed": w(k_embed, (cfg.vocab_size, E), E),
        "layers": _layer_stack(cfg, k_stack, dtype, L - kd, cfg.is_moe),
        "final_norm": jnp.ones((E,), jnp.float32),
    }
    if kd > 0:
        params["dense_layers"] = _layer_stack(cfg, k_dense, dtype, kd, False)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = w(k_lm, (E, cfg.vocab_size), E)
    return params


def _dense_cfg(cfg: ModelConfig) -> ModelConfig:
    """cfg with MoE off — routes llama._mlp to its dense-SwiGLU branch for
    the dense-prefix stack (trace-time only)."""
    import dataclasses

    return dataclasses.replace(cfg, num_experts=0)


def _split_stack(tree, k: int):
    return (
        jax.tree_util.tree_map(lambda a: a[:k], tree),
        jax.tree_util.tree_map(lambda a: a[k:], tree),
    )


def _concat_stack(a, b):
    return jax.tree_util.tree_map(
        lambda x, y: jnp.concatenate([x, y], axis=0), a, b
    )


def _scan_stack(params, cfg: ModelConfig, make_layer_fn, x, k_caches, v_caches):
    """Apply the layer stack: one scan for a homogeneous model, or a
    dense-prefix scan over cache[:k] followed by the MoE-suffix scan over
    cache[k:] (first_k_dense_replace). The two cache outputs concatenate
    back to the [L, ...] layout the executor owns; under donation XLA
    writes the scan outputs directly into slices of the output buffer."""
    kd = cfg.first_k_dense_replace if "dense_layers" in params else 0
    if kd == 0:
        x, (kc, vc) = jax.lax.scan(
            make_layer_fn(cfg.is_moe), x, (params["layers"], k_caches, v_caches)
        )
        return x, kc, vc
    kc_pre, kc_suf = _split_stack(k_caches, kd)
    vc_pre, vc_suf = _split_stack(v_caches, kd)
    x, (kc1, vc1) = jax.lax.scan(
        make_layer_fn(False), x, (params["dense_layers"], kc_pre, vc_pre)
    )
    x, (kc2, vc2) = jax.lax.scan(
        make_layer_fn(cfg.is_moe), x, (params["layers"], kc_suf, vc_suf)
    )
    return x, _concat_stack(kc1, kc2), _concat_stack(vc1, vc2)


def _q_heads(lp, cfg: ModelConfig, h: jnp.ndarray, positions: jnp.ndarray):
    """h [T, E] -> (q_nope [T, Hq, dn], q_pe [T, Hq, dr] roped)."""
    T = h.shape[0]
    dn, dr = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    if cfg.q_lora_rank > 0:
        cq = jnp.einsum("te,eq->tq", h, wt(lp["w_dq"]))
        cq = rms_norm(cq, lp["q_norm"], cfg.rms_norm_eps)
        q = jnp.einsum("tq,qh->th", cq, wt(lp["w_uq"]))
    else:
        q = jnp.einsum("te,eh->th", h, wt(lp["w_q"]))
    q = q.reshape(T, cfg.num_heads, dn + dr)
    q_nope, q_pe = q[..., :dn], q[..., dn:]
    q_pe = rope_ops.apply_rope_scaled(q_pe, positions, cfg)
    return q_nope, q_pe


def _pad_lanes(x: jnp.ndarray, width: int) -> jnp.ndarray:
    """Zero-pad the last dim to `width` (the cache's 128-aligned lane
    count, cfg.mla_cache_dim). Zeros on both q and cache rows keep the
    padded lanes out of every q·k score and tile[:, :kvr] context read."""
    if x.shape[-1] == width:
        return x
    pad = [(0, 0)] * (x.ndim - 1) + [(0, width - x.shape[-1])]
    return jnp.pad(x, pad)


def _latent_rows(lp, cfg: ModelConfig, h: jnp.ndarray, positions: jnp.ndarray):
    """h [T, E] -> cache rows [T, C]: concat(normed c_kv, roped k_pe),
    lane-padded to cfg.mla_cache_dim."""
    kvr, dr = cfg.kv_lora_rank, cfg.qk_rope_head_dim
    ckv = jnp.einsum("te,ec->tc", h, wt(lp["w_dkv"]))  # [T, kvr + dr]
    c, k_pe = ckv[..., :kvr], ckv[..., kvr:]
    c = rms_norm(c, lp["kv_norm"], cfg.rms_norm_eps)
    # Single shared rope key per token (head axis of 1 for apply_rope).
    k_pe = rope_ops.apply_rope_scaled(k_pe[:, None, :], positions, cfg)[:, 0]
    return _pad_lanes(
        jnp.concatenate([c, k_pe], axis=-1), cfg.mla_cache_dim
    )


def _absorb_q(lp, cfg: ModelConfig, q_nope, q_pe) -> jnp.ndarray:
    """Project q_nope into the latent space and append q_pe: [.., Hq, C]
    (lane-padded to match the cache rows)."""
    q_lat = jnp.einsum("...hd,hkd->...hk", q_nope, wt(lp["w_uk"]))
    return _pad_lanes(
        jnp.concatenate([q_lat, q_pe], axis=-1), cfg.mla_cache_dim
    )


def _attn_out(lp, cfg: ModelConfig, ctx_lat: jnp.ndarray) -> jnp.ndarray:
    """ctx_lat [..., Hq, kvr] -> hidden [..., E] via W_UV then W_O."""
    o = jnp.einsum("...hk,hkv->...hv", ctx_lat, wt(lp["w_uv"]))
    flat = o.reshape(*o.shape[:-2], cfg.num_heads * cfg.v_head_dim)
    return jnp.einsum("...h,he->...e", flat, wt(lp["wo"]))


def decode_step(
    params: Params,
    cfg: ModelConfig,
    k_caches,  # latent cache [L, N, 1, BS, C] (plain or PagedKV)
    v_caches,  # unused dummy (NUM_CACHES = 1); returned untouched
    token_ids: jnp.ndarray,  # [R]
    positions: jnp.ndarray,  # [R]
    block_tables: jnp.ndarray,  # [R, MB]
    active: jnp.ndarray,  # [R] bool
    use_kernel: bool | None = None,
):
    """One generation step for R sequences; mirrors llama.decode_step."""
    bs = k_caches.shape[3]
    scale = mla_softmax_scale(cfg)
    kvr = cfg.kv_lora_rank
    x = params["embed"][token_ids].astype(wdtype(params["layers"]["w_dkv"]))

    block_idx = positions // bs
    offset = jnp.where(active, positions % bs, 0)
    blk = jnp.take_along_axis(block_tables, block_idx[:, None], axis=1)[:, 0]
    blk = jnp.where(active, blk, 0)
    seq_lens = jnp.where(active, positions + 1, 0)

    def make_layer_fn(moe: bool):
        mcfg = cfg if moe else _dense_cfg(cfg)

        def layer_fn(x, scanned):
            lp, c_l, v_l = scanned
            h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
            q_nope, q_pe = _q_heads(lp, cfg, h, positions)
            rows = _latent_rows(lp, cfg, h, positions)
            c_l = kv_cache_ops.scatter_rows(c_l, blk, offset, rows[:, None, :])
            q_lat = _absorb_q(lp, cfg, q_nope, q_pe)
            ctx = mla_paged_attention(
                q_lat, c_l, block_tables, seq_lens, scale, kvr,
                use_kernel=use_kernel,
            )
            x = x + _attn_out(lp, cfg, ctx)
            h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
            x = x + _mlp_block(lp, mcfg, h, rows_valid=active)
            return x, (c_l, v_l)

        return layer_fn

    x, k_caches, v_caches = _scan_stack(
        params, cfg, make_layer_fn, x, k_caches, v_caches
    )
    logits = _unembed(params, cfg, x)
    return logits, k_caches, v_caches


def prefill_batch_step(
    params: Params,
    cfg: ModelConfig,
    k_caches,
    v_caches,
    token_ids: jnp.ndarray,  # [P, Lpad]
    start_pos: jnp.ndarray,  # [P]
    true_len: jnp.ndarray,  # [P]
    block_tables: jnp.ndarray,  # [P, CB]
    embed_overrides: jnp.ndarray | None = None,
    override_positions: jnp.ndarray | None = None,
    all_logits: bool = False,  # speculative verify: unembed EVERY position
):
    """Batched chunked prefill; mirrors llama.prefill_batch_step (media
    embedding injection included — the EPD encoder stage is model-family
    agnostic)."""
    bs = k_caches.shape[3]
    scale = mla_softmax_scale(cfg)
    kvr = cfg.kv_lora_rank
    P, Lpad = token_ids.shape
    x = params["embed"][token_ids].astype(wdtype(params["layers"]["w_dkv"]))
    if embed_overrides is not None and embed_overrides.shape[1] > 0:
        E = x.shape[-1]
        ext = jnp.concatenate([x, jnp.zeros((P, 1, E), x.dtype)], axis=1)
        ext = ext.at[
            jnp.arange(P, dtype=jnp.int32)[:, None], override_positions
        ].set(embed_overrides.astype(x.dtype))
        x = ext[:, :Lpad]

    offsets = jnp.arange(Lpad, dtype=jnp.int32)[None, :]
    positions = start_pos[:, None] + offsets  # [P, Lpad]
    valid = offsets < true_len[:, None]
    block_idx = positions // bs
    blk = jnp.where(
        valid, jnp.take_along_axis(block_tables, block_idx, axis=1), 0
    )
    in_block = jnp.where(valid, positions % bs, 0)
    flat_blk = blk.reshape(P * Lpad)
    flat_off = in_block.reshape(P * Lpad)

    def make_layer_fn(moe: bool):
        mcfg = cfg if moe else _dense_cfg(cfg)

        def layer_fn(x, scanned):
            lp, c_l, v_l = scanned
            h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
            q_nope, q_pe = jax.vmap(
                lambda hx, pos: _q_heads(lp, cfg, hx, pos)
            )(h, positions)  # [P, Lpad, Hq, *]
            rows = jax.vmap(lambda hx, pos: _latent_rows(lp, cfg, hx, pos))(
                h, positions
            )  # [P, Lpad, C]
            c_l = kv_cache_ops.scatter_rows(
                c_l, flat_blk, flat_off,
                rows.reshape(P * Lpad, 1, rows.shape[-1]),
            )
            q_lat = _absorb_q(lp, cfg, q_nope, q_pe)  # [P, Lpad, Hq, C]
            ctx = mla_prefill_attention(
                q_lat, c_l, block_tables, start_pos, true_len, scale, kvr
            )  # [P, Lpad, Hq, kvr] — flash kernel on TPU
            x = x + _attn_out(lp, cfg, ctx)
            h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
            x = x + _mlp_block(lp, mcfg, h, rows_valid=valid)
            return x, (c_l, v_l)

        return layer_fn

    x, k_caches, v_caches = _scan_stack(
        params, cfg, make_layer_fn, x, k_caches, v_caches
    )
    if all_logits:
        return _unembed(params, cfg, x), k_caches, v_caches  # [P, Lpad, V]
    last = jnp.take_along_axis(
        x, jnp.maximum(true_len - 1, 0)[:, None, None], axis=1
    )[:, 0]
    logits = _unembed(params, cfg, last)
    return logits, k_caches, v_caches


def forward_dense(
    params: Params,
    cfg: ModelConfig,
    token_ids: jnp.ndarray,  # [B, L]
) -> jnp.ndarray:
    """NAIVE (non-absorbed) causal forward — the correctness oracle for the
    absorbed paged paths: materializes per-head K = concat(c_kv @ W_UK,
    broadcast k_pe) and V = c_kv @ W_UV, then standard MHA."""
    from xllm_service_tpu.models.llama import _project

    return _project(params, cfg, hidden_dense(params, cfg, token_ids))


def hidden_dense(
    params: Params,
    cfg: ModelConfig,
    token_ids: jnp.ndarray,  # [B, L]
    rows_valid: jnp.ndarray | None = None,  # accepted for surface parity
) -> jnp.ndarray:
    """Final-norm hidden states [B, L, E] (the /v1/embeddings path).
    `rows_valid` is accepted for function-surface parity with
    models/llama.py but unused: this naive forward is the MLA
    correctness oracle and keeps the dense MoE combine (its vmapped
    per-sequence body cannot host the grouped dispatch's shard_map)."""
    B, L = token_ids.shape
    dn, dr = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    kvr = cfg.kv_lora_rank
    scale = mla_softmax_scale(cfg)
    positions = jnp.arange(L, dtype=jnp.int32)
    x = params["embed"][token_ids].astype(wdtype(params["layers"]["w_dkv"]))
    causal = (
        jnp.arange(L)[None, :] <= jnp.arange(L)[:, None]
    )  # [L, L] True = attend

    def make_layer_fn(moe: bool):
        mcfg = cfg if moe else _dense_cfg(cfg)

        def layer_fn(x, lp):
            def one_seq(hx):
                h = rms_norm(hx, lp["attn_norm"], cfg.rms_norm_eps)
                q_nope, q_pe = _q_heads(lp, cfg, h, positions)
                rows = _latent_rows(lp, cfg, h, positions)  # [L, C]
                # rows are lane-padded past kvr + dr; slice the true spans.
                c, k_pe = rows[..., :kvr], rows[..., kvr:kvr + dr]
                k_nope = jnp.einsum(
                    "tk,hkd->thd", c, wt(lp["w_uk"])
                )  # [L,Hq,dn]
                v = jnp.einsum("tk,hkv->thv", c, wt(lp["w_uv"]))  # [L,Hq,dv]
                k_pe_b = jnp.broadcast_to(
                    k_pe[:, None, :], (L, cfg.num_heads, dr)
                )
                q = jnp.concatenate([q_nope, q_pe], axis=-1).astype(jnp.float32)
                k = jnp.concatenate([k_nope, k_pe_b], axis=-1).astype(jnp.float32)
                scores = jnp.einsum("qhd,khd->hqk", q, k) * scale
                scores = jnp.where(causal[None], scores, -1e30)
                p = jax.nn.softmax(scores, axis=-1)
                # v is ALREADY up-projected per head — apply only wo here
                # (_attn_out would apply W_UV a second time; caught by the
                # paged-vs-dense parity test once tiny dims were made
                # pairwise distinct).
                o = jnp.einsum("hqk,khv->qhv", p, v.astype(jnp.float32))
                flat = o.reshape(L, cfg.num_heads * cfg.v_head_dim)
                attn = jnp.einsum(
                    "qf,fe->qe", flat.astype(hx.dtype), wt(lp["wo"])
                )
                hx = hx + attn
                h2 = rms_norm(hx, lp["mlp_norm"], cfg.rms_norm_eps)
                return hx + _mlp(lp, mcfg, h2)

            return jax.vmap(one_seq)(x), None

        return layer_fn

    if cfg.first_k_dense_replace > 0 and "dense_layers" in params:
        x, _ = jax.lax.scan(make_layer_fn(False), x, params["dense_layers"])
    x, _ = jax.lax.scan(make_layer_fn(cfg.is_moe), x, params["layers"])
    return rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
