"""Llama-family transformer, TPU-first.

Engine-tier model (reference delegates to the absent xLLM submodule;
SURVEY.md §2.3). Design choices:

  * Parameters are a plain pytree with per-layer tensors STACKED on a leading
    layer axis and the block applied with `lax.scan` — one compiled layer
    body regardless of depth (fast compiles, XLA-friendly).
  * Decode processes a fixed batch of R sequences against the paged KV cache
    (ops/attention.py); prefill processes one bucketed-length chunk for one
    sequence. Both scatter new K/V into the cache first, then attend over
    gathered context, which makes fresh prefill, chunked prefill, and
    prefix-cache-hit prefill the same code path.
  * GQA throughout; SwiGLU MLP; optional MoE block (Mixtral-style top-k
    router). MoE here computes all experts and combines by router weight —
    exact and fine at test scale; the expert-parallel ragged-dispatch path
    lives in parallel/ (later rounds route through it).
  * Everything is shape-static: R, bucketed prefill lengths, max_blocks.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from xllm_service_tpu.models.configs import ModelConfig
from xllm_service_tpu.ops import kv_cache as kv_cache_ops
from xllm_service_tpu.ops.attention import (
    mixed_attention,
    mixed_prefill_attention,
    paged_attention,
    prefill_attention,
)
from xllm_service_tpu.ops import collective_matmul as cm_ops
from xllm_service_tpu.ops.norms import rms_norm
from xllm_service_tpu.ops import lora as lora_ops
from xllm_service_tpu.ops import moe as moe_ops
from xllm_service_tpu.ops.quant import wdtype, wt
from xllm_service_tpu.ops import rope as rope_ops

Params = Dict[str, Any]

NUM_CACHES = 2  # separate paged K and V caches

# Stacked matmul leaves eligible for int8 weight quantization (all are
# [L, in, out] / [L, X, in, out] with the contraction on axis -2 —
# ops/quant.py). Norms/biases/router stay high precision; embed/lm_head
# are gathers (dequant-at-use would materialize the full table).
QUANTIZABLE_WEIGHT_LEAVES = (
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
    "w_sh_gate", "w_sh_up", "w_sh_down",
)


def cache_row_dims(cfg: ModelConfig) -> Tuple[int, int]:
    """(heads, row_dim) of one paged-cache row. head_dim < 128 models
    pack P = 128/head_dim consecutive KV heads per row so the Pallas
    kernels' 128-lane DMA tiling holds (kv_cache.kv_pack_factor)."""
    P = (
        1 if cfg.kv_pack_disable
        else kv_cache_ops.kv_pack_factor(cfg.num_kv_heads, cfg.head_dim)
    )
    return cfg.num_kv_heads // P, cfg.head_dim * P


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    """Random-init parameters (tests/bench; checkpoint loading replaces these
    values with the same pytree structure — runtime/weights.py)."""
    E, L = cfg.hidden_size, cfg.num_layers
    Hq, Hkv, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    F = cfg.intermediate_size
    keys = jax.random.split(key, 16)

    def norm_init(shape):
        return jnp.ones(shape, dtype=jnp.float32)

    def w(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(fan_in)).astype(
            dtype
        )

    layers: Dict[str, jnp.ndarray] = {
        "attn_norm": norm_init((L, E)),
        "wq": w(keys[0], (L, E, Hq * D), E),
        "wk": w(keys[1], (L, E, Hkv * D), E),
        "wv": w(keys[2], (L, E, Hkv * D), E),
        "wo": w(keys[3], (L, Hq * D, E), Hq * D),
        "mlp_norm": norm_init((L, E)),
    }
    if cfg.attn_bias:
        layers["bq"] = jnp.zeros((L, Hq * D), dtype)
        layers["bk"] = jnp.zeros((L, Hkv * D), dtype)
        layers["bv"] = jnp.zeros((L, Hkv * D), dtype)
    if cfg.qk_norm:
        # Qwen3 QK-norm: one RMSNorm weight over head_dim, shared by all
        # q heads (q_head_norm) / kv heads (k_head_norm) of a layer.
        layers["q_head_norm"] = norm_init((L, D))
        layers["k_head_norm"] = norm_init((L, D))
    if cfg.is_moe:
        X, Fm = cfg.num_experts, cfg.moe_intermediate_size
        layers.update(
            {
                "router": w(keys[4], (L, E, X), E),
                "w_gate": w(keys[5], (L, X, E, Fm), E),
                "w_up": w(keys[6], (L, X, E, Fm), E),
                "w_down": w(keys[7], (L, X, Fm, E), Fm),
            }
        )
        if cfg.topk_method == "noaux_tc":
            layers["router_bias"] = jnp.zeros((L, X), jnp.float32)
        if cfg.n_shared_experts > 0:
            # Shared experts are family-agnostic (_mlp reads these for any
            # MoE config with n_shared_experts > 0).
            Fs = cfg.n_shared_experts * Fm
            layers.update(
                {
                    "w_sh_gate": w(keys[10], (L, E, Fs), E),
                    "w_sh_up": w(keys[11], (L, E, Fs), E),
                    "w_sh_down": w(keys[12], (L, Fs, E), Fs),
                }
            )
    else:
        layers.update(
            {
                "w_gate": w(keys[5], (L, E, F), E),
                "w_up": w(keys[6], (L, E, F), E),
                "w_down": w(keys[7], (L, F, E), F),
            }
        )

    params: Params = {
        "embed": w(keys[8], (cfg.vocab_size, E), E),
        "layers": layers,
        "final_norm": norm_init((E,)),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = w(keys[9], (E, cfg.vocab_size), E)
    return params


def _unembed(params: Params, cfg: ModelConfig, x: jnp.ndarray) -> jnp.ndarray:
    return _project(
        params, cfg, rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    )


def _project(params: Params, cfg: ModelConfig, h: jnp.ndarray) -> jnp.ndarray:
    """Vocab projection of ALREADY-final-normed hidden states."""
    if cfg.tie_word_embeddings:
        return jnp.einsum("...e,ve->...v", h.astype(jnp.float32),
                          params["embed"].astype(jnp.float32))
    return jnp.einsum("...e,ev->...v", h.astype(jnp.float32),
                      params["lm_head"].astype(jnp.float32))


def _row_parallel(eq: str, x: jnp.ndarray, w2d: jnp.ndarray) -> jnp.ndarray:
    """Row-parallel contraction over a tp-sharded axis (o-proj and the
    FFN down-proj): the ring collective-matmul pipeline when
    XLLM_OVERLAP_COLLECTIVES + a tp>1 shard context apply (the
    reduction rides under the next tile's compute instead of after it
    — ops/collective_matmul.py), else the caller's exact einsum, whose
    GSPMD lowering (local matmul + psum) is the serving default."""
    o = cm_ops.maybe_overlap_matmul(x, w2d)
    return o if o is not None else jnp.einsum(eq, x, w2d)


def _act(cfg: ModelConfig):
    """Gated-MLP activation: SwiGLU (default) or Gemma's GELU-tanh —
    delegated to the one shared selector (ops/moe.py) so the dense,
    oracle, and kernel MoE paths can never drift."""
    return moe_ops._act_fn(cfg.mlp_act)


def _embed(params: Params, cfg: ModelConfig, token_ids, wd) -> jnp.ndarray:
    """Token embeddings in weight dtype; Gemma scales by sqrt(E) (HF
    computes the normalizer in model dtype)."""
    x = params["embed"][token_ids].astype(wd)
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.hidden_size**0.5, x.dtype)
    return x


def _mlp(
    lp: Dict[str, jnp.ndarray], cfg: ModelConfig, x: jnp.ndarray,
    lora_idx=None,
) -> jnp.ndarray:
    """SwiGLU (dense) or top-k MoE block. x: [T, E]."""
    if not cfg.is_moe:
        gate = jnp.einsum("te,ef->tf", x, wt(lp["w_gate"]))
        up = jnp.einsum("te,ef->tf", x, wt(lp["w_up"]))
        d = lora_ops.maybe_apply(lp, "w_gate", x, lora_idx, 1.0)
        gate = gate + d if d is not None else gate
        d = lora_ops.maybe_apply(lp, "w_up", x, lora_idx, 1.0)
        up = up + d if d is not None else up
        h = _act(cfg)(gate) * up
        out = _row_parallel("tf,fe->te", h, wt(lp["w_down"]))
        d = lora_ops.maybe_apply(lp, "w_down", h, lora_idx, 1.0)
        return out + d if d is not None else out
    # MoE: router scores -> top-k weights; every expert's FFN runs on its
    # own shard and the top-k combine is a CONTRACTION over the expert
    # axis. With w_gate/w_up/w_down sharded on X over an `ep` mesh axis
    # (parallel/sharding.py), the XLA SPMD partitioner keeps each device's
    # expert compute local and inserts one psum for the combine — the EP
    # serving path, with no gather that would force an all-gather of
    # [T, X, E] activations. (The grouped ragged dispatch — compute
    # tracking ACTIVE params — is the XLLM_MOE_KERNEL path in
    # _mlp_block; this dense all-experts combine is the default and the
    # semantic reference, docs/MOE.md.)
    topi, weights = moe_route(lp, cfg, x)
    T, X = x.shape[0], cfg.num_experts
    combine = jnp.zeros((T, X), jnp.float32)
    combine = combine.at[
        jnp.arange(T, dtype=jnp.int32)[:, None], topi
    ].set(weights)  # [T, X]: top-k combine weight or 0
    gate = jnp.einsum("te,xef->txf", x, wt(lp["w_gate"]))
    up = jnp.einsum("te,xef->txf", x, wt(lp["w_up"]))
    expert_out = jnp.einsum(
        "txf,xfe->txe", _act(cfg)(gate) * up, wt(lp["w_down"])
    )
    out = jnp.einsum("txe,tx->te", expert_out, combine.astype(expert_out.dtype))
    if cfg.n_shared_experts > 0:
        out = out + _shared_experts(lp, x)
    return out


def moe_route(lp, cfg: ModelConfig, x: jnp.ndarray):
    """Router top-k selection + combine weights, x [T, E] ->
    (topi [T, k] int32, weights [T, k] f32). THE routing semantics —
    shared verbatim by the dense all-experts combine (_mlp) and the
    grouped ragged dispatch (_moe_grouped), so flipping the dispatch
    strategy can never change which experts serve a token or at what
    weight."""
    logits = jnp.einsum(
        "te,ex->tx", x.astype(jnp.float32), lp["router"].astype(jnp.float32)
    )
    if cfg.scoring_func == "sigmoid":  # DeepSeek-V3
        scores = jax.nn.sigmoid(logits)
    else:
        scores = jax.nn.softmax(logits, axis=-1)
    T, X = scores.shape
    # Selection scores may differ from COMBINE weights: V3's noaux_tc
    # adds a correction bias for selection only (HF DeepseekV3TopkRouter).
    sel = scores
    if lp.get("router_bias") is not None:
        sel = sel + lp["router_bias"].astype(jnp.float32)
    if cfg.n_group > 1 and cfg.topk_group > 0:
        # Group-limited routing: keep topk_group groups (scored by their
        # top-2 sum for noaux_tc, group max for group_limited_greedy),
        # zero the rest (scores are non-negative post-softmax/sigmoid).
        gs = sel.reshape(T, cfg.n_group, X // cfg.n_group)
        if cfg.topk_method == "noaux_tc":
            group_scores = jax.lax.top_k(gs, 2)[0].sum(-1)
        else:
            group_scores = gs.max(-1)
        _, gidx = jax.lax.top_k(group_scores, cfg.topk_group)
        gmask = jnp.zeros((T, cfg.n_group), jnp.float32)
        gmask = gmask.at[
            jnp.arange(T, dtype=jnp.int32)[:, None], gidx
        ].set(1.0)
        sel = (gs * gmask[..., None]).reshape(T, X)
    _, topi = jax.lax.top_k(sel, cfg.num_experts_per_tok)
    weights = jnp.take_along_axis(scores, topi, axis=-1)  # [T, k]
    # Scaling placement differs between the HF gates: V2's MoEGate
    # applies routed_scaling_factor ONLY in its no-renorm branch, while
    # V3's TopkRouter (sigmoid / noaux_tc configs) renormalizes AND
    # scales. Mixtral/Qwen3 renorm unconditionally and never scale.
    # (Advisor finding, round 4.)
    v3_style = cfg.topk_method == "noaux_tc" or cfg.scoring_func == "sigmoid"
    if cfg.norm_topk_prob:
        weights = weights / (
            jnp.sum(weights, axis=-1, keepdims=True) + 1e-20
        )
    if cfg.routed_scaling_factor != 1.0 and (
        v3_style or not cfg.norm_topk_prob
    ):
        weights = weights * cfg.routed_scaling_factor
    return topi, weights


def _shared_experts(lp, x: jnp.ndarray) -> jnp.ndarray:
    """DeepSeek-style always-active shared expert(s): a dense SwiGLU of
    n_shared * moe_intermediate width alongside the routed experts."""
    sg = jnp.einsum("te,ef->tf", x, wt(lp["w_sh_gate"]))
    su = jnp.einsum("te,ef->tf", x, wt(lp["w_sh_up"]))
    return jnp.einsum(
        "tf,fe->te", jax.nn.silu(sg) * su, wt(lp["w_sh_down"])
    )


def _moe_grouped(
    lp, cfg: ModelConfig, x: jnp.ndarray, row_mask=None
) -> jnp.ndarray:
    """MoE block via the grouped ragged expert dispatch (ops/moe.py —
    the XLLM_MOE_KERNEL serving path, ISSUE 15): exact _mlp routing
    (moe_route), ONE grouped launch per expert slice (shard_map over ep
    under an executor shard context), dense shared-expert tail."""
    topi, weights = moe_route(lp, cfg, x)
    out = moe_ops.grouped_moe(
        x, topi, weights,
        wt(lp["w_gate"]), wt(lp["w_up"]), wt(lp["w_down"]),
        act=cfg.mlp_act, row_mask=row_mask,
    )
    if cfg.n_shared_experts > 0:
        out = out + _shared_experts(lp, x)
    return out


def _mlp_block(
    lp, cfg: ModelConfig, h: jnp.ndarray, lora_idx=None, rows_valid=None
) -> jnp.ndarray:
    """MLP over [T, E] or batched [P, L, E] activations — every step
    function's MLP entry point. Default: EXACTLY the split per-row
    programs (_mlp direct for 2D, vmapped for 3D — the pre-ISSUE-15
    jaxprs, byte for byte). With the grouped MoE dispatch enabled
    (ops.moe.grouped_moe_enabled) the leading axes flatten into one
    token axis for the routed experts — the flatten is OUTSIDE any
    vmap, which is what lets the dispatch wrap in shard_map over ep —
    and the SAME flatten applies in every step family (decode, batched
    prefill, mixed, verify), so grouped-mode streams stay byte-stable
    across step builders and mesh sizes (docs/MOE.md).

    `rows_valid` (h's leading shape, bool) marks LIVE rows — every step
    function already owns this mask (decode `active`, prefill/verify
    `valid`): padding lanes and inactive slots stay out of the grouped
    dispatch's routing stats and capacity (ops.moe row_mask docstring).
    The legacy paths ignore it (dense computes padding rows and
    discards them downstream, exactly as before)."""
    if cfg.is_moe and moe_ops.grouped_moe_enabled():
        lead = h.shape[:-1]
        mask = rows_valid.reshape(-1) if rows_valid is not None else None
        y = _moe_grouped(
            lp, cfg, h.reshape(-1, h.shape[-1]), row_mask=mask
        )
        return y.reshape(*lead, y.shape[-1])
    if h.ndim == 2:
        return _mlp(lp, cfg, h, lora_idx)
    li = (
        lora_idx if lora_idx is not None
        else jnp.zeros((h.shape[0],), jnp.int32)
    )
    return jax.vmap(
        lambda t, ai: _mlp(
            lp, cfg, t, ai if lora_idx is not None else None
        )
    )(h, li)


def _qkv(lp, cfg: ModelConfig, x: jnp.ndarray, positions: jnp.ndarray,
         lora_idx=None):
    """x: [T, E] -> q [T, Hq, D], k/v [T, Hkv, D] with RoPE applied."""
    T = x.shape[0]
    q = jnp.einsum("te,eh->th", x, wt(lp["wq"]))
    k = jnp.einsum("te,eh->th", x, wt(lp["wk"]))
    v = jnp.einsum("te,eh->th", x, wt(lp["wv"]))
    d = lora_ops.maybe_apply(lp, "wq", x, lora_idx, 1.0)
    q = q + d if d is not None else q
    d = lora_ops.maybe_apply(lp, "wk", x, lora_idx, 1.0)
    k = k + d if d is not None else k
    d = lora_ops.maybe_apply(lp, "wv", x, lora_idx, 1.0)
    v = v + d if d is not None else v
    if cfg.attn_bias:
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    q = q.reshape(T, cfg.num_heads, cfg.head_dim)
    k = k.reshape(T, cfg.num_kv_heads, cfg.head_dim)
    v = v.reshape(T, cfg.num_kv_heads, cfg.head_dim)
    if cfg.qk_norm:
        # Qwen3: per-head RMSNorm over head_dim BEFORE RoPE (HF
        # Qwen3Attention ordering).
        q = rms_norm(q, lp["q_head_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, lp["k_head_norm"], cfg.rms_norm_eps)
    if cfg.mrope_section and positions.ndim == 2:
        # Qwen2-VL M-RoPE: [3, T] (t, h, w) streams diverge inside image
        # spans. 1D positions (text-only prompts, every decode step) take
        # the standard path below — equal streams make them identical.
        q = rope_ops.apply_mrope(
            q, positions, cfg.rope_theta, cfg.mrope_section
        )
        k = rope_ops.apply_mrope(
            k, positions, cfg.rope_theta, cfg.mrope_section
        )
        return q, k, v
    q = rope_ops.apply_rope_scaled(q, positions, cfg)
    k = rope_ops.apply_rope_scaled(k, positions, cfg)
    return q, k, v


def _scatter_kv(k_cache, v_cache, blk, offset, k, v):
    """Write per-token K/V rows into cache slots.

    k_cache: [num_blocks, Hc, bs, Dc] plain array or PagedKV (int8 caches
    quantize the rows on write); blk/offset: [T] block ids and in-block
    offsets per token; inactive/invalid tokens carry (0, 0), pointing into
    the reserved garbage block 0. Packed caches (Hc < Hkv — head_dim < 128
    models, see cache_row_dims) take the rows reshaped to the packed
    layout: consecutive heads concatenate on lanes."""
    kf = kv_cache_ops.scatter_rows(
        k_cache, blk, offset, kv_cache_ops.pack_rows(k, k_cache)
    )
    vf = kv_cache_ops.scatter_rows(
        v_cache, blk, offset, kv_cache_ops.pack_rows(v, v_cache)
    )
    return kf, vf


def decode_step(
    params: Params,
    cfg: ModelConfig,
    k_caches: jnp.ndarray,  # [L, num_blocks, Hkv, bs, D]
    v_caches: jnp.ndarray,
    token_ids: jnp.ndarray,  # [R] int32
    positions: jnp.ndarray,  # [R] int32 (0-based position of this token)
    block_tables: jnp.ndarray,  # [R, max_blocks] int32
    active: jnp.ndarray,  # [R] bool
    use_kernel: bool | None = None,
    lora_idx: jnp.ndarray | None = None,  # [R] per-slot adapter rows
    rope_delta: jnp.ndarray | None = None,  # [R] int32 (M-RoPE, <= 0)
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One generation step for R sequences. Returns (logits [R, V],
    k_caches', v_caches')."""
    bs = k_caches.shape[3]
    scale = cfg.head_dim**-0.5
    x = _embed(params, cfg, token_ids, wdtype(params["layers"]["wq"]))  # [R, E]

    # Rope positions may lag cache positions (Qwen2-VL M-RoPE compresses
    # image spans): rope_delta <= 0 shifts the ROTATION only — cache
    # slots, block lookup, and attention lengths stay token-count-based.
    rope_pos = positions + rope_delta if rope_delta is not None else positions
    block_idx = positions // bs
    offset = jnp.where(active, positions % bs, 0)
    blk = jnp.take_along_axis(block_tables, block_idx[:, None], axis=1)[:, 0]
    blk = jnp.where(active, blk, 0)
    seq_lens = jnp.where(active, positions + 1, 0)

    def layer_fn(x, scanned):
        lp, k_l, v_l = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(lp, cfg, h, rope_pos, lora_idx)
        k_l, v_l = _scatter_kv(k_l, v_l, blk, offset, k, v)
        attn = paged_attention(
            q, k_l, v_l, block_tables, seq_lens, scale,
            use_kernel=use_kernel, window=cfg.sliding_window,
        )
        attn_flat = attn.reshape(attn.shape[0], -1)
        o = _row_parallel("rh,he->re", attn_flat,
                          wt(lp["wo"]).reshape(-1, cfg.hidden_size))
        d = lora_ops.maybe_apply(lp, "wo", attn_flat, lora_idx, 1.0)
        x = x + (o + d if d is not None else o)
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        x = x + _mlp_block(lp, cfg, h, lora_idx, rows_valid=active)
        return x, (k_l, v_l)

    x, (k_caches, v_caches) = jax.lax.scan(
        layer_fn, x, (params["layers"], k_caches, v_caches)
    )
    logits = _unembed(params, cfg, x)  # [R, V]
    return logits, k_caches, v_caches


def mixed_step(
    params: Params,
    cfg: ModelConfig,
    k_caches: jnp.ndarray,
    v_caches: jnp.ndarray,
    dec_tokens: jnp.ndarray,  # [R] int32 — decode-slot input tokens
    dec_positions: jnp.ndarray,  # [R] int32
    dec_tables: jnp.ndarray,  # [R, CBd] int32
    dec_active: jnp.ndarray,  # [R] bool
    pf_tokens: jnp.ndarray,  # [P, Lpad] int32 — due prefill chunks
    pf_start: jnp.ndarray,  # [P] int32 (cached tokens before each chunk)
    pf_len: jnp.ndarray,  # [P] int32 (valid tokens per chunk; 0 = pad row)
    pf_tables: jnp.ndarray,  # [P, CBp] int32
    use_ragged: bool | None = None,
    lora_dec: jnp.ndarray | None = None,  # [R] adapter rows
    lora_pf: jnp.ndarray | None = None,  # [P] adapter rows
    rope_delta: jnp.ndarray | None = None,  # [R] M-RoPE lag (decode slots)
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """ONE compiled step for a MIXED batch: R decode slots and P chunked-
    prefill rows in a single dispatch, fused at the DISPATCH and
    ATTENTION level but NOT at the dense matmuls: each half runs with
    exactly the shapes decode_step ([R, E]) and prefill_batch_step
    (vmapped [P, Lpad]) would use, because matmul row values are only
    bit-stable under a fixed row count — flattening both halves into one
    [R + P*Lpad, E] buffer made mixed-step streams drift from split-step
    streams at bf16 ULP scale (docs/KERNELS.md pins this contract; the
    engine-level differential in tests/test_ragged_attention.py enforces
    it). Attention runs through ops.attention.mixed_attention — one
    ragged Pallas dispatch over both halves when the kernel is enabled,
    the exact split-path decode+prefill attention ops otherwise.

    Returns (dec_logits [R, V], pf_logits [P, V] — each prefill row's
    LAST valid position — k', v')."""
    bs = k_caches.shape[3]
    scale = cfg.head_dim**-0.5
    R = dec_tokens.shape[0]
    P, Lpad = pf_tokens.shape
    wd = wdtype(params["layers"]["wq"])
    x_dec = _embed(params, cfg, dec_tokens, wd)  # [R, E]
    x_pf = _embed(params, cfg, pf_tokens, wd)  # [P, Lpad, E]

    # Decode-half coordinates: verbatim decode_step (M-RoPE rope_delta
    # shifts the rotation only; inactive slots scatter into garbage
    # block 0).
    dec_rope = (
        dec_positions + rope_delta if rope_delta is not None
        else dec_positions
    )
    dec_blk = jnp.take_along_axis(
        dec_tables, (dec_positions // bs)[:, None], axis=1
    )[:, 0]
    dec_blk = jnp.where(dec_active, dec_blk, 0)
    dec_off = jnp.where(dec_active, dec_positions % bs, 0)
    dec_seq_lens = jnp.where(dec_active, dec_positions + 1, 0)

    # Prefill-half coordinates: verbatim prefill_batch_step (invalid
    # rows land in garbage block 0). Media prompts never ride the mixed
    # step, so positions are always the plain sequential streams.
    offsets = jnp.arange(Lpad, dtype=jnp.int32)[None, :]
    pf_positions = pf_start[:, None] + offsets  # [P, Lpad]
    pf_valid = offsets < pf_len[:, None]
    pf_blk = jnp.where(
        pf_valid,
        jnp.take_along_axis(pf_tables, pf_positions // bs, axis=1),
        0,
    )
    pf_off = jnp.where(pf_valid, pf_positions % bs, 0)
    pf_flat_blk = pf_blk.reshape(P * Lpad)
    pf_flat_off = pf_off.reshape(P * Lpad)
    li = lora_pf if lora_pf is not None else jnp.zeros((P,), jnp.int32)

    def layer_fn(carry, scanned):
        x_dec, x_pf = carry
        lp, k_l, v_l = scanned
        # Decode half QKV: decode_step's [R, E] shapes.
        h_dec = rms_norm(x_dec, lp["attn_norm"], cfg.rms_norm_eps)
        q_dec, k_dec, v_dec = _qkv(lp, cfg, h_dec, dec_rope, lora_dec)
        # Prefill half QKV: prefill_batch_step's vmapped [Lpad, E] rows.
        h_pf = rms_norm(x_pf, lp["attn_norm"], cfg.rms_norm_eps)
        q_pf, k_pf, v_pf = jax.vmap(
            lambda hx, pos, ai: _qkv(
                lp, cfg, hx, pos, ai if lora_pf is not None else None
            )
        )(h_pf, pf_positions, li)  # q_pf [P, Lpad, Hq, D]
        k_l, v_l = _scatter_kv(k_l, v_l, dec_blk, dec_off, k_dec, v_dec)
        k_l, v_l = _scatter_kv(
            k_l, v_l, pf_flat_blk, pf_flat_off,
            k_pf.reshape(P * Lpad, *k_pf.shape[2:]),
            v_pf.reshape(P * Lpad, *v_pf.shape[2:]),
        )
        attn_dec, attn_pf = mixed_attention(
            q_dec, q_pf, k_l, v_l,
            dec_tables, dec_seq_lens,
            pf_tables, pf_start, pf_len,
            scale, use_ragged=use_ragged, interpret=interpret,
            window=cfg.sliding_window,
        )
        # Output projection + MLP, per half, split-step shapes.
        attn_dec_flat = attn_dec.reshape(attn_dec.shape[0], -1)
        o = _row_parallel("rh,he->re", attn_dec_flat,
                          wt(lp["wo"]).reshape(-1, cfg.hidden_size))
        d = lora_ops.maybe_apply(lp, "wo", attn_dec_flat, lora_dec, 1.0)
        x_dec = x_dec + (o + d if d is not None else o)
        h_dec = rms_norm(x_dec, lp["mlp_norm"], cfg.rms_norm_eps)
        x_dec = x_dec + _mlp_block(
            lp, cfg, h_dec, lora_dec, rows_valid=dec_active
        )

        attn_pf_flat = attn_pf.reshape(P, Lpad, -1)
        o = _row_parallel("plh,he->ple", attn_pf_flat,
                          wt(lp["wo"]).reshape(-1, cfg.hidden_size))
        if lora_pf is not None and lp.get("lora_wo_a") is not None:
            o = o + jax.vmap(
                lambda af, ai: lora_ops.apply(
                    af, lp["lora_wo_a"], lp["lora_wo_b"], ai
                )
            )(attn_pf_flat, li)
        x_pf = x_pf + o
        h_pf = rms_norm(x_pf, lp["mlp_norm"], cfg.rms_norm_eps)
        x_pf = x_pf + _mlp_block(
            lp, cfg, h_pf, lora_pf, rows_valid=pf_valid
        )
        return (x_dec, x_pf), (k_l, v_l)

    (x_dec, x_pf), (k_caches, v_caches) = jax.lax.scan(
        layer_fn, (x_dec, x_pf), (params["layers"], k_caches, v_caches)
    )
    dec_logits = _unembed(params, cfg, x_dec)  # [R, V]
    last = jnp.take_along_axis(
        x_pf, jnp.maximum(pf_len - 1, 0)[:, None, None], axis=1
    )[:, 0]  # [P, E]
    pf_logits = _unembed(params, cfg, last)  # [P, V]
    return dec_logits, pf_logits, k_caches, v_caches


def mixed_verify_step(
    params: Params,
    cfg: ModelConfig,
    k_caches: jnp.ndarray,
    v_caches: jnp.ndarray,
    ver_tokens: jnp.ndarray,  # [R, S] int32 — last accepted token + drafts
    ver_start: jnp.ndarray,  # [R] int32 — position of the first fed token
    ver_len: jnp.ndarray,  # [R] int32 — fed tokens per row (0 = inactive)
    ver_tables: jnp.ndarray,  # [R, CBv] int32
    pf_tokens: jnp.ndarray,  # [P, Lpad] int32 — due prefill chunks
    pf_start: jnp.ndarray,  # [P] int32
    pf_len: jnp.ndarray,  # [P] int32 (0 = pad row)
    pf_tables: jnp.ndarray,  # [P, CBp] int32
    use_ragged: bool | None = None,
    lora_ver: jnp.ndarray | None = None,  # [R] adapter rows (verify rows)
    lora_pf: jnp.ndarray | None = None,  # [P] adapter rows (prefill rows)
    ver_rope_delta: jnp.ndarray | None = None,  # [R] M-RoPE lag (<= 0)
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """ONE compiled step for a speculative MIXED batch: R verify rows
    (q_len = k+1 — the multi-query speculative-verify half) and P
    chunked-prefill rows in a single dispatch. Same fusion contract as
    mixed_step: fused at the DISPATCH and ATTENTION level, while each
    half keeps exactly the matmul shapes its split program uses — the
    verify half IS prefill_batch_step's vmapped [R, S] program (the one
    executor.verify runs, with all_logits), the prefill half the
    [P, Lpad] one — because matmul row values are only bit-stable under
    a fixed row count (docs/KERNELS.md pins this; the composed
    differential in tests/test_spec_pipeline.py enforces it). Attention
    runs through ops.attention.mixed_prefill_attention — one ragged
    Pallas dispatch over the whole heterogeneous batch when the kernel
    is enabled, the exact split prefill dispatcher per half otherwise.

    Returns (ver_logits [R, S, V] — every position, the speculative
    verify contract — pf_logits [P, V], k', v')."""
    bs = k_caches.shape[3]
    scale = cfg.head_dim**-0.5
    R, S = ver_tokens.shape
    P, Lpad = pf_tokens.shape
    wd = wdtype(params["layers"]["wq"])
    x_ver = _embed(params, cfg, ver_tokens, wd)  # [R, S, E]
    x_pf = _embed(params, cfg, pf_tokens, wd)  # [P, Lpad, E]

    def half_coords(start, length, tables, L):
        offs = jnp.arange(L, dtype=jnp.int32)[None, :]
        pos = start[:, None] + offs
        valid = offs < length[:, None]
        blk = jnp.where(
            valid, jnp.take_along_axis(tables, pos // bs, axis=1), 0
        )
        off = jnp.where(valid, pos % bs, 0)
        return pos, blk.reshape(-1), off.reshape(-1)

    ver_pos, ver_blk, ver_off = half_coords(
        ver_start, ver_len, ver_tables, S
    )
    pf_pos, pf_blk, pf_off = half_coords(pf_start, pf_len, pf_tables, Lpad)
    # Live-row masks for the grouped-MoE dispatch (_mlp_block rows_valid
    # — padding lanes stay out of routing stats/capacity).
    ver_valid = (
        jnp.arange(S, dtype=jnp.int32)[None, :] < ver_len[:, None]
    )
    pf_valid = (
        jnp.arange(Lpad, dtype=jnp.int32)[None, :] < pf_len[:, None]
    )
    # M-RoPE verify rows (media sequences decoding under spec): the
    # generation streams are equal, only the lag vs cache positions
    # matters — exactly executor._verify_impl's broadcast.
    if ver_rope_delta is not None:
        base = (ver_start + ver_rope_delta)[:, None] + jnp.arange(
            S, dtype=jnp.int32
        )[None]
        ver_rp = jnp.broadcast_to(base[:, None, :], (R, 3, S))
    else:
        ver_rp = ver_pos
    li_ver = lora_ver if lora_ver is not None else jnp.zeros((R,), jnp.int32)
    li_pf = lora_pf if lora_pf is not None else jnp.zeros((P,), jnp.int32)

    def layer_fn(carry, scanned):
        x_ver, x_pf = carry
        lp, k_l, v_l = scanned
        h_ver = rms_norm(x_ver, lp["attn_norm"], cfg.rms_norm_eps)
        q_ver, k_v, v_v = jax.vmap(
            lambda hx, pos, ai: _qkv(
                lp, cfg, hx, pos, ai if lora_ver is not None else None
            )
        )(h_ver, ver_rp, li_ver)  # q_ver [R, S, Hq, D]
        h_pf = rms_norm(x_pf, lp["attn_norm"], cfg.rms_norm_eps)
        q_pf, k_p, v_p = jax.vmap(
            lambda hx, pos, ai: _qkv(
                lp, cfg, hx, pos, ai if lora_pf is not None else None
            )
        )(h_pf, pf_pos, li_pf)
        k_l, v_l = _scatter_kv(
            k_l, v_l, ver_blk, ver_off,
            k_v.reshape(R * S, *k_v.shape[2:]),
            v_v.reshape(R * S, *v_v.shape[2:]),
        )
        k_l, v_l = _scatter_kv(
            k_l, v_l, pf_blk, pf_off,
            k_p.reshape(P * Lpad, *k_p.shape[2:]),
            v_p.reshape(P * Lpad, *v_p.shape[2:]),
        )
        attn_ver, attn_pf = mixed_prefill_attention(
            q_ver, q_pf, k_l, v_l,
            ver_tables, ver_start, ver_len,
            pf_tables, pf_start, pf_len,
            scale, use_ragged=use_ragged, interpret=interpret,
            window=cfg.sliding_window,
        )

        def half_tail(x, attn, L_, n_rows, lora, li, valid):
            attn_flat = attn.reshape(n_rows, L_, -1)
            o = _row_parallel("plh,he->ple", attn_flat,
                              wt(lp["wo"]).reshape(-1, cfg.hidden_size))
            if lora is not None and lp.get("lora_wo_a") is not None:
                o = o + jax.vmap(
                    lambda af, ai: lora_ops.apply(
                        af, lp["lora_wo_a"], lp["lora_wo_b"], ai
                    )
                )(attn_flat, li)
            x = x + o
            h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
            return x + _mlp_block(lp, cfg, h, lora, rows_valid=valid)

        x_ver = half_tail(x_ver, attn_ver, S, R, lora_ver, li_ver,
                          ver_valid)
        x_pf = half_tail(x_pf, attn_pf, Lpad, P, lora_pf, li_pf,
                         pf_valid)
        return (x_ver, x_pf), (k_l, v_l)

    (x_ver, x_pf), (k_caches, v_caches) = jax.lax.scan(
        layer_fn, (x_ver, x_pf), (params["layers"], k_caches, v_caches)
    )
    ver_logits = _unembed(params, cfg, x_ver)  # [R, S, V]
    last = jnp.take_along_axis(
        x_pf, jnp.maximum(pf_len - 1, 0)[:, None, None], axis=1
    )[:, 0]
    pf_logits = _unembed(params, cfg, last)  # [P, V]
    return ver_logits, pf_logits, k_caches, v_caches


def prefill_batch_step(
    params: Params,
    cfg: ModelConfig,
    k_caches: jnp.ndarray,
    v_caches: jnp.ndarray,
    token_ids: jnp.ndarray,  # [P, Lpad] int32 — per-seq chunks, padded
    start_pos: jnp.ndarray,  # [P] int32: cached tokens before each chunk
    true_len: jnp.ndarray,  # [P] int32: valid tokens per chunk
    block_tables: jnp.ndarray,  # [P, CB] int32 — SLICED to the group's
    # context-block bound, capping the per-layer gather (round-1 weak
    # item 4: gathering max_blocks*BS rows per chunk was O(L^2) with a
    # full-context materialization)
    embed_overrides: jnp.ndarray | None = None,  # [P, M, E] media tokens
    override_positions: jnp.ndarray | None = None,  # [P, M] chunk-relative;
    # padding entries point at Lpad (a dummy row, sliced off)
    all_logits: bool = False,  # speculative verify: unembed EVERY position
    lora_idx: jnp.ndarray | None = None,  # [P] per-sequence adapter rows
    rope_positions: jnp.ndarray | None = None,  # [P, 3, Lpad] M-RoPE streams
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Prefill P sequences' chunks in ONE compiled step (batched admission).

    K/V rows for all P*Lpad tokens scatter into the paged cache in a single
    flattened write (invalid rows land in garbage block 0); attention is
    vmapped per sequence over its own sliced block table. Media embeddings
    (EPD encoder outputs) overwrite placeholder-token rows before the first
    layer. Returns (last-token logits [P, V] — or [P, Lpad, V] when
    `all_logits`, the speculative-decoding verify pass — k', v')."""
    bs = k_caches.shape[3]
    scale = cfg.head_dim**-0.5
    P, Lpad = token_ids.shape
    x = _embed(params, cfg, token_ids, wdtype(params["layers"]["wq"]))
    if embed_overrides is not None and embed_overrides.shape[1] > 0:
        # Scatter into an extended buffer whose last row is a discard slot
        # so padded positions (== Lpad) never corrupt real rows.
        E = x.shape[-1]
        ext = jnp.concatenate([x, jnp.zeros((P, 1, E), x.dtype)], axis=1)
        ext = ext.at[
            jnp.arange(P, dtype=jnp.int32)[:, None], override_positions
        ].set(embed_overrides.astype(x.dtype))
        x = ext[:, :Lpad]

    offsets = jnp.arange(Lpad, dtype=jnp.int32)[None, :]  # [1, Lpad]
    positions = start_pos[:, None] + offsets  # [P, Lpad]
    valid = offsets < true_len[:, None]
    block_idx = positions // bs
    blk = jnp.where(
        valid, jnp.take_along_axis(block_tables, block_idx, axis=1), 0
    )
    in_block = jnp.where(valid, positions % bs, 0)
    flat_blk = blk.reshape(P * Lpad)
    flat_off = in_block.reshape(P * Lpad)

    li = lora_idx if lora_idx is not None else jnp.zeros((P,), jnp.int32)
    # Cache slots/attention stay token-count positional; only the q/k
    # ROTATION takes the (t, h, w) streams when M-RoPE positions ride in.
    rp = rope_positions if rope_positions is not None else positions

    def layer_fn(x, scanned):
        lp, k_l, v_l = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = jax.vmap(
            lambda hx, pos, ai: _qkv(
                lp, cfg, hx, pos, ai if lora_idx is not None else None
            )
        )(h, rp, li)  # q [P, Lpad, Hq, D]
        k_l, v_l = _scatter_kv(
            k_l, v_l, flat_blk, flat_off,
            k.reshape(P * Lpad, *k.shape[2:]),
            v.reshape(P * Lpad, *v.shape[2:]),
        )
        attn = prefill_attention(
            q, k_l, v_l, block_tables, start_pos, true_len, scale,
            window=cfg.sliding_window,
        )  # [P, Lpad, Hq, D] — flash kernel on TPU, blockwise elsewhere
        attn_flat = attn.reshape(P, Lpad, -1)
        o = _row_parallel("plh,he->ple", attn_flat,
                          wt(lp["wo"]).reshape(-1, cfg.hidden_size))
        if lora_idx is not None and lp.get("lora_wo_a") is not None:
            o = o + jax.vmap(
                lambda af, ai: lora_ops.apply(
                    af, lp["lora_wo_a"], lp["lora_wo_b"], ai
                )
            )(attn_flat, li)
        x = x + o
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        x = x + _mlp_block(lp, cfg, h, lora_idx, rows_valid=valid)
        return x, (k_l, v_l)

    x, (k_caches, v_caches) = jax.lax.scan(
        layer_fn, x, (params["layers"], k_caches, v_caches)
    )
    if all_logits:
        return _unembed(params, cfg, x), k_caches, v_caches  # [P, Lpad, V]
    last = jnp.take_along_axis(
        x, jnp.maximum(true_len - 1, 0)[:, None, None], axis=1
    )[:, 0]  # [P, E]
    logits = _unembed(params, cfg, last)  # [P, V]
    return logits, k_caches, v_caches


def prefill_step(
    params: Params,
    cfg: ModelConfig,
    k_caches: jnp.ndarray,
    v_caches: jnp.ndarray,
    token_ids: jnp.ndarray,  # [Lpad] int32 — one sequence's chunk, padded
    start_pos: jnp.ndarray,  # scalar int32: cached tokens before this chunk
    true_len: jnp.ndarray,  # scalar int32: valid tokens in chunk
    block_table: jnp.ndarray,  # [max_blocks] int32
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Process one prefill chunk (P=1 wrapper over prefill_batch_step).
    Returns (last-token logits [V], k', v')."""
    logits, k_caches, v_caches = prefill_batch_step(
        params, cfg, k_caches, v_caches,
        token_ids[None],
        jnp.asarray(start_pos, jnp.int32)[None],
        jnp.asarray(true_len, jnp.int32)[None],
        block_table[None],
    )
    return logits[0], k_caches, v_caches


def prefill_sp_step(
    params: Params,
    cfg: ModelConfig,
    token_ids: jnp.ndarray,  # [Lsp] int32 — padded to a multiple of sp
    true_len: jnp.ndarray,  # scalar int32
    mesh,
    sp_axis: str = "sp",
    tp_axis=None,  # compose with tensor parallelism on the same mesh:
    # params keep their Megatron tp sharding and the ring shards heads
    # over tp_axis too (ops/ring_attention.ring_attention)
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sequence-parallel long-context prefill: the prompt's sequence axis is
    sharded over the `sp` mesh ring and every layer's attention is EXACT
    ring attention (ops/ring_attention.py — K/V shards rotate via ppermute,
    queries stay resident), so max prompt length scales linearly with the
    ring size instead of one device's HBM.

    Returns (last-token logits [V], k_all [layers, Lsp, Hkv, D],
    v_all [...]) — the caller scatters K/V into the paged cache
    (runtime/executor.py prefill_long) and decode proceeds normally.
    """
    from xllm_service_tpu.ops.ring_attention import ring_attention

    Lsp = token_ids.shape[0]
    positions = jnp.arange(Lsp, dtype=jnp.int32)
    x = _embed(params, cfg, token_ids, wdtype(params["layers"]["wq"]))
    x = x[None]  # [1, Lsp, E] — ring_attention is batched

    def layer_fn(x, lp):
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(lp, cfg, h[0], positions)
        attn = ring_attention(
            q[None], k[None], v[None], mesh, sp_axis=sp_axis,
            scale=cfg.head_dim**-0.5, causal=True, tp_axis=tp_axis,
        )
        x = x + jnp.einsum(
            "blh,he->ble",
            attn.reshape(1, Lsp, -1),
            wt(lp["wo"]).reshape(-1, cfg.hidden_size),
        )
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        x = x + _mlp_block(
            lp, cfg, h[0],
            rows_valid=jnp.arange(Lsp, dtype=jnp.int32) < true_len,
        )[None]
        return x, (k, v)

    x, (k_all, v_all) = jax.lax.scan(layer_fn, x, params["layers"])
    last = x[0, jnp.maximum(true_len - 1, 0)]
    logits = _unembed(params, cfg, last)
    return logits, k_all, v_all


def forward_dense(
    params: Params,
    cfg: ModelConfig,
    token_ids: jnp.ndarray,  # [B, L] int32
) -> jnp.ndarray:
    """Plain causal forward without KV cache — the correctness oracle for
    prefill/decode and the body of the training step (__graft_entry__)."""
    return _project(params, cfg, hidden_dense(params, cfg, token_ids))


def hidden_dense(
    params: Params,
    cfg: ModelConfig,
    token_ids: jnp.ndarray,  # [B, L] int32
    rows_valid: jnp.ndarray | None = None,  # [B, L] bool live-row mask
) -> jnp.ndarray:
    """Final-norm hidden states [B, L, E] of a plain causal forward —
    the /v1/embeddings path (pooling happens executor-side) and the body
    forward_dense unembeds. `rows_valid` marks real tokens when the
    caller bucket-padded (executor.embed_tokens) — the grouped-MoE
    dispatch keeps padding rows out of routing stats/capacity exactly
    like the serving steps (_mlp_block docstring)."""
    B, L = token_ids.shape
    scale = cfg.head_dim**-0.5
    x = _embed(params, cfg, token_ids, wdtype(params["layers"]["wq"]))
    positions = jnp.arange(L, dtype=jnp.int32)
    causal = jnp.tril(jnp.ones((L, L), dtype=bool))
    if cfg.sliding_window:
        # HF SWA semantics: position p attends [p-window+1, p].
        causal &= (
            positions[None, :] > positions[:, None] - cfg.sliding_window
        )

    def layer_fn(x, lp):
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)

        def one_seq(hx):
            q, k, v = _qkv(lp, cfg, hx, positions)
            Hq, Hkv, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
            g = Hq // Hkv
            qf = q.astype(jnp.float32).reshape(L, Hkv, g, D)
            scores = jnp.einsum("qhgd,khd->hgqk", qf, k.astype(jnp.float32)) * scale
            scores = jnp.where(causal[None, None], scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1)
            out = jnp.einsum("hgqk,khd->qhgd", probs, v.astype(jnp.float32))
            return out.reshape(L, Hq * D).astype(hx.dtype)

        attn = jax.vmap(one_seq)(h)  # [B, L, Hq*D]
        x = x + jnp.einsum("blh,he->ble", attn, wt(lp["wo"]).reshape(-1, cfg.hidden_size))
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        x = x + _mlp_block(lp, cfg, h, rows_valid=rows_valid)
        return x, None

    x, _ = jax.lax.scan(layer_fn, x, params["layers"])
    return rms_norm(x, params["final_norm"], cfg.rms_norm_eps)  # [B, L, E]
