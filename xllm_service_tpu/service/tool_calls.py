"""OpenAI tool-call extraction from generated text.

The chat template serializes `tools` into the prompt (the reference
does the same through minja and stops there — it never parses the
model's answer back, jinja_chat_template.cpp:53-99). Models trained on
that format (Qwen2/2.5, Hermes) emit calls as

    <tool_call>
    {"name": "get_weather", "arguments": {"city": "Paris"}}
    </tool_call>

Non-streaming chat completions parse these into the OpenAI
`message.tool_calls` array with `finish_reason: "tool_calls"`;
STREAMING responses deliberately emit the spans verbatim as content
(clients parse the well-known format themselves — structured streamed
tool deltas would require holding back every partial `<tool_call`
prefix across chunks, trading interactivity for a convenience the
OpenAI SDK reconstructs anyway). Malformed JSON inside a span stays in
the content untouched — never drop model output on a parse failure.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Tuple

_TOOL_CALL_RE = re.compile(r"<tool_call>\s*(.*?)\s*</tool_call>", re.S)


def parse_tool_calls(
    text: str, request_id: str, choice_index: int = 0
) -> Tuple[Optional[str], List[Dict[str, Any]]]:
    """(remaining_content, tool_calls) from one choice's full text.

    tool_calls follow the OpenAI wire shape (`function.arguments` is a
    JSON STRING). Content becomes None when nothing but whitespace
    remains outside the parsed spans. `choice_index` keeps ids unique
    across an n>1 fan-out (OpenAI call ids are response-unique)."""
    calls: List[Dict[str, Any]] = []

    def replace(m: re.Match) -> str:
        try:
            obj = json.loads(m.group(1))
            name = obj["name"]
            args = obj.get("arguments", {})
        except (ValueError, TypeError, KeyError):
            return m.group(0)  # malformed: keep the span as content
        if not isinstance(name, str):
            return m.group(0)
        calls.append({
            "id": f"call_{request_id}_{choice_index}_{len(calls)}",
            "type": "function",
            "function": {
                "name": name,
                "arguments": (
                    args if isinstance(args, str)
                    else json.dumps(args, ensure_ascii=False)
                ),
            },
        })
        return ""

    content = _TOOL_CALL_RE.sub(replace, text)
    if not calls:
        return text, []
    content = content.strip()
    return (content or None), calls
