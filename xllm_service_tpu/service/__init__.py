"""Service tier: scheduler, request model, response handling, tracing."""

from xllm_service_tpu.service.ordered_streams import OrderedStreams
from xllm_service_tpu.service.request import (
    RequestTracer,
    ServiceRequest,
    make_service_request_id,
)
from xllm_service_tpu.service.response_handler import ClientStream, ResponseHandler
from xllm_service_tpu.service.scheduler import Scheduler

__all__ = [
    "OrderedStreams",
    "RequestTracer",
    "ServiceRequest",
    "make_service_request_id",
    "ClientStream",
    "ResponseHandler",
    "Scheduler",
]
