"""Service-side request record + tracer.

Reference: xllm_service/request/request.h:25-63 (the record) and
http_service/request_tracer.{h,cpp} (JSONL per-request I/O tracing gated by
--enable_request_trace, hooked into every stream write).
The `offline` flag here is consumed by hybrid online/offline admission in
the scheduler — in the reference it exists but nothing reads it
(request.h:38; README.md:40 roadmap).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from xllm_service_tpu.common.shortuuid import generate_service_request_id
from xllm_service_tpu.common.types import Routing
from xllm_service_tpu.tokenizer.chat_template import Message

# 'method-threadid-uuid22' (reference: service.cpp:41-48).
make_service_request_id = generate_service_request_id


@dataclass
class ServiceRequest:
    service_request_id: str = ""
    model: str = ""
    stream: bool = False
    include_usage: bool = False
    echo: bool = False
    # Hybrid scheduling priority class; offline work yields to online.
    offline: bool = False
    n: int = 1
    best_of: int = 1
    logprobs: Optional[int] = None  # completions API: top-k count
    top_logprobs: int = 0  # chat API
    max_tokens: int = 0
    temperature: float = 1.0
    top_p: float = 1.0
    prompt: str = ""
    messages: List[Message] = field(default_factory=list)
    tools: Optional[List[Dict[str, Any]]] = None
    token_ids: List[int] = field(default_factory=list)
    # OpenAI `stop`: up to 4 strings; generation halts BEFORE any of them
    # appears. Enforced service-side on detokenized text (the engine speaks
    # token ids; stop strings can span token boundaries).
    stop: List[str] = field(default_factory=list)
    routing: Routing = field(default_factory=Routing)
    created_time: float = field(default_factory=time.time)
    # EPD multimodal (filled by the scheduler's media expansion): raw media
    # payloads for the encoder stage + the placeholder-token positions in
    # token_ids where its embeddings land.
    media_parts: List[Dict[str, Any]] = field(default_factory=list)
    mm_positions: List[int] = field(default_factory=list)
    # Per-part merged (t, gh, gw) grids for the engine's M-RoPE streams
    # (t > 1 = video); empty when the geometry isn't square.
    mm_grids: List[List[int]] = field(default_factory=list)
    # Filled by the scheduler:
    num_generated_tokens: int = 0
    estimated_ttft_ms: float = 0.0
    # Prefix-fabric fetch hint for the routed prefill instance (empty =
    # no fetch planned): {holder, addr, blocks, total_blocks} — the peer
    # holding the fleet-best prefix match (docs/KV_CACHE.md).
    kv_fabric: Dict[str, Any] = field(default_factory=dict)
    # Mid-stream failover (docs/FAULT_TOLERANCE.md). `wire_srid` is the
    # on-the-wire service_request_id for the CURRENT dispatch attempt —
    # the bare id for attempt 0, `<id>#rN` after N replays; outputs
    # carrying an older wire id are late pushes from a dead attempt and
    # are dropped. `resumable` is computed at admission (n=1/best_of=1,
    # non-guided, no media); `resume_token_ids` is prompt + every
    # delivered token, `resume_base` the replayed-token count.
    wire_srid: str = ""
    resumable: bool = True
    resume_token_ids: List[int] = field(default_factory=list)
    resume_base: int = 0
    # Admission (service/admission.py): `tenant` is the fair-share key —
    # the OpenAI `user` field when the client sends one, else the model
    # name. `retry_after_s` is set on a shed and rendered as the HTTP
    # Retry-After header; `_admitted` marks a charged admission slot
    # (release is idempotent on it).
    tenant: str = ""
    retry_after_s: float = 0.0
    _admitted: bool = False
    # Tracing hook (reference: Request::trace_callback, service.cpp:212-218).
    trace_callback: Optional[Callable[[str, Any], None]] = None

    @property
    def is_chat(self) -> bool:
        return bool(self.messages)

    def trace(self, direction: str, payload: Any) -> None:
        if self.trace_callback is not None:
            self.trace_callback(direction, payload)


class RequestTracer:
    """Mutex-guarded JSONL appender (reference: request_tracer.cpp:38-62
    opens trace/trace.json and appends {timestamp, service_request_id,
    payload} per streamed write), extended with:

      * structured `stage` records — the request-lifecycle spans consumed
        by obs.spans (receive -> tokenize -> route -> dispatch ->
        first_token -> decode ticks -> finish/cancel/redispatch), each
        stamped with one process monotonic clock so per-stage durations
        subtract exactly;
      * size-based rotation with a configurable keep-count
        (trace.jsonl -> trace.jsonl.1 .. .N, oldest dropped) so a long
        bench run keeps a bounded WINDOW of generations rather than only
        the newest half;
      * a drop counter instead of unbounded error growth: a failed disk
        write increments `dropped` and the record is lost, never buffered;
      * an optional SpanRing mirror: every `stage` record is also appended
        to the process's in-memory flight-recorder ring (obs.flight).
    """

    def __init__(
        self,
        trace_dir: str = "trace",
        enabled: bool = False,
        max_bytes: int = 64 * 1024 * 1024,
        keep: int = 1,
        ring: Optional[Any] = None,
    ):
        self._enabled = enabled
        self._mu = threading.Lock()
        self._fh = None
        self._path = os.path.join(trace_dir, "trace.jsonl")
        self._max_bytes = max(int(max_bytes), 1)
        self._keep = max(int(keep), 1)
        self._ring = ring
        self._size = 0
        self.dropped = 0  # records lost to write failures / closed tracer
        if enabled:
            os.makedirs(trace_dir, exist_ok=True)
            self._fh = open(self._path, "a", encoding="utf-8")
            try:
                self._size = os.path.getsize(self._path)
            except OSError:
                self._size = 0

    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def path(self) -> str:
        return self._path

    def _rotate_locked(self) -> None:
        """Keep-count rotation under self._mu: trace.jsonl.N-1 -> .N for
        N = keep..2 (the oldest generation falls off the end), then the
        live file becomes .1 — bounded disk, newest `keep` windows
        intact."""
        try:
            self._fh.close()
            for n in range(self._keep, 1, -1):
                src = "%s.%d" % (self._path, n - 1)
                if os.path.exists(src):
                    os.replace(src, "%s.%d" % (self._path, n))
            os.replace(self._path, self._path + ".1")
            self._fh = open(self._path, "a", encoding="utf-8")
            self._size = 0
        except OSError:
            # Rotation failed (e.g. the .1 target is unwritable): TRUNCATE
            # the live file instead of appending on — the disk bound is
            # the hard guarantee; the lost window is the trade. A doomed
            # rotation must also not be re-attempted on every write.
            self._size = 0
            try:
                self._fh = open(self._path, "w", encoding="utf-8")
            except OSError:
                self._fh = None

    def _write_entry(self, entry: Dict[str, Any]) -> None:
        line = json.dumps(entry, ensure_ascii=False, default=str)
        with self._mu:
            if self._fh is None:
                self.dropped += 1
                return
            try:
                self._fh.write(line + "\n")
                self._fh.flush()
            except (OSError, ValueError):
                self.dropped += 1
                return
            # Bytes, not characters: ensure_ascii=False means multi-byte
            # text would otherwise under-count 3-4x against max_bytes.
            self._size += len(line.encode("utf-8")) + 1
            if self._size >= self._max_bytes:
                self._rotate_locked()

    def record(self, service_request_id: str, direction: str, payload: Any) -> None:
        if not self._enabled:
            return
        self._write_entry(
            {
                "timestamp_ms": int(time.time() * 1000),
                "service_request_id": service_request_id,
                "direction": direction,
                "payload": payload,
            }
        )

    def stage(self, service_request_id: str, stage: str, **fields: Any) -> None:
        """One request-lifecycle span record (obs.spans schema). Mirrored
        into the flight-recorder ring (always-on) when one is bound; the
        JSONL write stays gated on --enable_request_trace."""
        if not self._enabled and self._ring is None:
            return
        entry = {
            "type": "stage",
            "timestamp_ms": int(time.time() * 1000),
            "t_mono_ms": time.monotonic() * 1000.0,
            "service_request_id": service_request_id,
            "stage": stage,
        }
        entry.update(fields)
        if self._ring is not None:
            self._ring.append(entry)
        if self._enabled:
            self._write_entry(entry)

    def bind(self, service_request_id: str) -> Callable[[str, Any], None]:
        return lambda direction, payload: self.record(
            service_request_id, direction, payload
        )

    def flush(self) -> None:
        with self._mu:
            if self._fh is not None:
                try:
                    self._fh.flush()
                except (OSError, ValueError):
                    self.dropped += 1

    def close(self) -> None:
        with self._mu:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    self._fh.close()
                except (OSError, ValueError):
                    pass
                self._fh = None


class StopStringMonitor:
    """Streaming stop-sequence matcher with partial-match hold-back.

    Text flows through `push`; anything that could still be the start of a
    stop string is held until disambiguated, so a stop spanning chunk (or
    token) boundaries is caught and NEVER partially emitted. OpenAI
    semantics: output ends BEFORE the matched stop string.
    """

    def __init__(self, stops: List[str]):
        self.stops = [s for s in stops if s]
        self.stopped = False
        self._buf = ""

    def push(self, text: str) -> "tuple[str, bool]":
        """Returns (emittable_text, hit)."""
        if not self.stops or self.stopped:
            return ("", True) if self.stopped else (text, False)
        self._buf += text
        first = -1
        for s in self.stops:
            j = self._buf.find(s)
            if j != -1 and (first == -1 or j < first):
                first = j
        if first != -1:
            out, self._buf = self._buf[:first], ""
            self.stopped = True
            return out, True
        # Hold back the longest suffix that is a proper prefix of any stop.
        hold = 0
        for s in self.stops:
            for k in range(min(len(s) - 1, len(self._buf)), hold, -1):
                if self._buf.endswith(s[:k]):
                    hold = k
                    break
        cut = len(self._buf) - hold
        out, self._buf = self._buf[:cut], self._buf[cut:]
        return out, False

    def flush(self) -> str:
        """Natural end of generation: release any held-back partial."""
        out, self._buf = self._buf, ""
        return out
