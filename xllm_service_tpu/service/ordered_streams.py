"""Per-request ordered output executors.

The reference guarantees per-request token order by hashing each request to
one of 128 single-thread pools (reference: scheduler.h:112-117, dispatch at
scheduler.cpp:312-333). Same design: N worker threads, each owning a FIFO;
a request is pinned to one lane for its lifetime, so its callbacks are
serialized while different requests fan out across lanes.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, List, Optional


class OrderedStreams:
    def __init__(self, num_streams: int = 128, queue_capacity: int = 4096):
        self._num = max(1, num_streams)
        self._queues: List["queue.Queue[Optional[Callable[[], None]]]"] = [
            queue.Queue(maxsize=queue_capacity) for _ in range(self._num)
        ]
        self._threads = [
            threading.Thread(
                target=self._run, args=(q,), name=f"ordered-out-{i}", daemon=True
            )
            for i, q in enumerate(self._queues)
        ]
        self._next = 0
        self._mu = threading.Lock()
        for t in self._threads:
            t.start()

    @property
    def num_streams(self) -> int:
        return self._num

    def assign(self) -> int:
        """Pick a lane for a new request (round-robin,
        reference: scheduler.cpp:209-214)."""
        with self._mu:
            idx = self._next % self._num
            self._next += 1
            return idx

    def submit(self, lane: int, fn: Callable[[], None]) -> None:
        self._queues[lane % self._num].put(fn)

    @staticmethod
    def _run(q: "queue.Queue[Optional[Callable[[], None]]]") -> None:
        while True:
            fn = q.get()
            if fn is None:
                return
            try:
                fn()
            except Exception:
                pass  # a client callback failure must not kill the lane

    def shutdown(self) -> None:
        for q in self._queues:
            q.put(None)
        for t in self._threads:
            t.join(timeout=2.0)
