"""Per-tenant admission control and fair-share overload protection.

Until this module, the only overload defense was evserve's
connection-level 503 shed (api/evserve/server.py) — a full accept
backlog. Everything admitted past the socket joined the scheduler's
unbounded inflight set, so demand past capacity collapsed goodput for
EVERY tenant at once: queues grew without bound, each request's TTFT
blew through the SLO, and the SLO-met-token rate ("Unifying Both for
Goodput-Optimized LLM Serving", arxiv 2508.01989) went to zero exactly
when the fleet was busiest. P/D-Serve (arxiv 2408.08147) runs this
control at the front door of tens of thousands of devices.

The controller sits at the very top of `Scheduler.schedule()` — BEFORE
the chat template and tokenizer, so a shed costs microseconds, not a
tokenize of a prompt we will refuse anyway. Three mechanisms, all
per-tenant (tenant = the request's `user` field, falling back to the
model name — the same key the goodput controller's decode-length EWMA
uses):

* **Token-bucket rate limit** — `rate` requests/s refilled
  continuously, `burst` deep. A dry bucket sheds immediately with
  `Retry-After = (deficit / rate)` so well-behaved clients back off to
  exactly the sustainable rate instead of hammering the door.
* **Inflight caps** — per-tenant and global. The global cap is the
  scheduler's real protection (bounded queues bound TTFT); the
  per-tenant cap keeps one tenant from owning the whole window.
* **Fair-share weighted queuing** — when the global cap is hit,
  arrivals may briefly WAIT for a slot instead of shedding. Waiters
  queue per tenant and releases grant by deficit-weighted round-robin
  (`XLLM_ADMISSION_WEIGHTS`, e.g. "gold:4,free:1"), so a heavy tenant
  cannot starve a light one no matter how fast it retries. The wait is
  deadline-aware: when the estimated wait (queue depth over the
  observed release rate) already exceeds the queue timeout, the
  request sheds IMMEDIATELY with that estimate as Retry-After —
  shedding early under hopeless backlog is what keeps the queue from
  collapsing into a convoy of doomed waiters.

Sheds return `RESOURCE_EXHAUSTED`, which the master's `_HTTP_STATUS`
map renders as HTTP 429 with a `Retry-After` header from
`request.retry_after_s`. Admission never touches token bytes: an
admitted stream is byte-identical to the same stream with the hatch
off (tests/test_admission.py differential).

Hatches (all read per call, so they flip on a live cluster;
docs/ARCHITECTURE.md hatch table):

  XLLM_ADMISSION=1|0                   master on/off override
  XLLM_ADMISSION_RATE                  per-tenant token-bucket rate, req/s
                                       (0 = unlimited)
  XLLM_ADMISSION_BURST                 bucket depth (0 = max(rate, 1))
  XLLM_ADMISSION_MAX_INFLIGHT          per-tenant inflight cap
  XLLM_ADMISSION_MAX_GLOBAL_INFLIGHT   fleet-wide inflight cap
  XLLM_ADMISSION_QUEUE_TIMEOUT_S       fair-queue wait bound (0 = shed
                                       instead of waiting)
  XLLM_ADMISSION_WEIGHTS               "tenant:weight,..." fair shares

The injectable `clock` follows the PR 18 `MemoryStore(clock=...)`
pattern: bucket refill and rate estimation advance on the injected
clock only, so tests pin expiry deterministically and the fleet
simulator (cluster/fleet_sim) runs admission on SIMULATED time.
"""

from __future__ import annotations

import collections
import math
import os
import threading
import time
from typing import Callable, Deque, Dict, Optional, Tuple

from xllm_service_tpu.common import faults
from xllm_service_tpu.common.types import Status, StatusCode

# Release-rate EWMA smoothing for the deadline estimate: recent
# completions dominate, one burst of finishes doesn't whipsaw it.
RATE_ALPHA = 0.2


def admission_enabled(cfg=None) -> bool:
    """XLLM_ADMISSION=1|0 overrides config either way; read per call so
    the hatch flips on a live cluster."""
    env = os.environ.get("XLLM_ADMISSION")
    if env == "1":
        return True
    if env == "0":
        return False
    return bool(getattr(cfg, "enable_admission_control", True))


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def parse_weights(spec: str) -> Dict[str, float]:
    """"gold:4,free:1" -> {"gold": 4.0, "free": 1.0}; malformed entries
    are dropped (an operator typo must not take the front door down)."""
    out: Dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        name, _, w = part.rpartition(":")
        try:
            val = float(w)
        except ValueError:
            continue
        if name and val > 0:
            out[name] = val
    return out


class _TenantState:
    __slots__ = ("tokens", "last_refill", "inflight", "credit")

    def __init__(self, now: float, burst: float) -> None:
        self.tokens = burst
        self.last_refill = now
        self.inflight = 0
        self.credit = 0.0  # deficit-round-robin credit while waiting


class _Waiter:
    __slots__ = ("tenant", "event", "granted")

    def __init__(self, tenant: str) -> None:
        self.tenant = tenant
        self.event = threading.Event()
        self.granted = False


class AdmissionController:
    """Front-door admission (see module docstring). Thread-safe: acquire
    on HTTP handler threads, release on scheduler lane threads."""

    def __init__(self, config=None, metrics=None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._config = config
        self._clock = clock
        self._mu = threading.Lock()
        self._tenants: Dict[str, _TenantState] = {}
        self._global_inflight = 0
        # Per-tenant FIFO of waiters + tenant arrival order for the
        # deficit-weighted grant scan.
        self._waiting: Dict[str, Deque[_Waiter]] = {}
        # Release-rate EWMA (req/s on the injected clock) for the
        # deadline-aware shed estimate.
        self._release_rate = 0.0
        self._last_release = 0.0
        # Lifetime counters (bench/report surfaces; the labeled counter
        # below carries the same numbers into /metrics).
        self.sheds = {"rate": 0, "tenant_inflight": 0, "queue_full": 0,
                      "queue_timeout": 0, "injected": 0}
        self.admitted_total = 0
        self._m_sheds = None
        self._m_queue_wait = None
        self._m_tenant_inflight = None
        if metrics is not None:
            self._m_sheds = metrics.counter(
                "xllm_admission_sheds_total",
                "Requests shed at the front door by reason "
                "(rate/tenant_inflight/queue_full/queue_timeout/injected)",
                labelnames=("reason",),
            )
            self._m_queue_wait = metrics.histogram(
                "xllm_admission_queue_wait_ms",
                "Admission fair-queue wait for ADMITTED requests "
                "(sheds are counted, not timed)",
            )
            self._m_tenant_inflight = metrics.gauge(
                "xllm_admission_tenant_inflight",
                "Admitted, unreleased requests per tenant",
                labelnames=("tenant",),
            )
            metrics.gauge(
                "xllm_admission_queued_waiters",
                "Requests currently parked in the admission fair queue",
            ).set_function(lambda: float(self._num_waiting()))

    # ------------------------------------------------------------------ #
    # knobs (env wins over config, read per call)
    # ------------------------------------------------------------------ #

    def _rate(self) -> float:
        return _env_float(
            "XLLM_ADMISSION_RATE",
            float(getattr(self._config, "admission_rate", 0.0)),
        )

    def _burst(self) -> float:
        burst = _env_float(
            "XLLM_ADMISSION_BURST",
            float(getattr(self._config, "admission_burst", 0.0)),
        )
        return burst if burst > 0 else max(self._rate(), 1.0)

    def _tenant_cap(self) -> int:
        return _env_int(
            "XLLM_ADMISSION_MAX_INFLIGHT",
            int(getattr(self._config, "admission_max_inflight", 2048)),
        )

    def _global_cap(self) -> int:
        return _env_int(
            "XLLM_ADMISSION_MAX_GLOBAL_INFLIGHT",
            int(getattr(
                self._config, "admission_max_global_inflight", 8192
            )),
        )

    def _queue_timeout_s(self) -> float:
        return _env_float(
            "XLLM_ADMISSION_QUEUE_TIMEOUT_S",
            float(getattr(self._config, "admission_queue_timeout_s", 2.0)),
        )

    def _weight(self, tenant: str) -> float:
        spec = os.environ.get(
            "XLLM_ADMISSION_WEIGHTS",
            str(getattr(self._config, "admission_weights", "") or ""),
        )
        return parse_weights(spec).get(tenant, 1.0)

    # ------------------------------------------------------------------ #
    # acquire / release
    # ------------------------------------------------------------------ #

    def acquire(self, request) -> Optional[Status]:
        """Admit or shed one request. Returns None when admitted (the
        request is charged; `release(request)` MUST follow exactly once)
        and a RESOURCE_EXHAUSTED Status when shed, with
        `request.retry_after_s` set for the master's Retry-After header.
        Disabled: always admits, charges nothing (release no-ops)."""
        if not admission_enabled(self._config):
            return None
        tenant = getattr(request, "tenant", "") or request.model or "-"
        request.tenant = tenant
        # Chaos seam: a matching XLLM_CHAOS_SPEC rule FORCES a shed here,
        # so chaos runs exercise every 429 client path without needing a
        # real overload (docs/FAULT_TOLERANCE.md shed matrix).
        try:
            faults.point("admission.shed", tenant=tenant,
                         request_id=request.service_request_id)
        except faults.FaultInjected:
            return self._shed(request, tenant, "injected", 1.0)
        now = self._clock()
        rate = self._rate()
        with self._mu:
            st = self._tenants.get(tenant)
            if st is None:
                st = self._tenants[tenant] = _TenantState(
                    now, self._burst()
                )
            # Token bucket (rate 0 = unlimited).
            if rate > 0:
                burst = self._burst()
                st.tokens = min(
                    burst, st.tokens + (now - st.last_refill) * rate
                )
                st.last_refill = now
                if st.tokens < 1.0:
                    retry = (1.0 - st.tokens) / rate
                    return self._shed_locked(
                        request, tenant, "rate", retry
                    )
                st.tokens -= 1.0
            # Per-tenant inflight cap: refund the bucket token — the
            # request never ran, its rate share shouldn't burn.
            if st.inflight >= self._tenant_cap():
                if rate > 0:
                    st.tokens = min(self._burst(), st.tokens + 1.0)
                return self._shed_locked(
                    request, tenant, "tenant_inflight",
                    self._wait_estimate_locked(),
                )
            # Global cap: deadline-aware fair queue.
            if self._global_inflight >= self._global_cap():
                if rate > 0:
                    st.tokens = min(self._burst(), st.tokens + 1.0)
                timeout = self._queue_timeout_s()
                est = self._wait_estimate_locked()
                if timeout <= 0 or est > timeout:
                    return self._shed_locked(
                        request, tenant, "queue_full", max(est, 1.0)
                    )
                waiter = _Waiter(tenant)
                self._waiting.setdefault(
                    tenant, collections.deque()
                ).append(waiter)
            else:
                self._admit_locked(tenant, st)
                request._admitted = True
                return None
        # Park OUTSIDE the lock (releases need it to grant).
        t0 = time.monotonic()
        waiter.event.wait(timeout)
        with self._mu:
            if not waiter.granted:
                # Timed out: withdraw from the queue and shed. (A grant
                # racing the timeout sets `granted` under this same
                # lock, so the re-check here is authoritative.)
                q = self._waiting.get(tenant)
                if q is not None:
                    try:
                        q.remove(waiter)
                    except ValueError:
                        pass
                    if not q:
                        self._waiting.pop(tenant, None)
                return self._shed_locked(
                    request, tenant, "queue_timeout",
                    max(self._wait_estimate_locked(), 1.0),
                )
        request._admitted = True
        if self._m_queue_wait is not None:
            self._m_queue_wait.observe((time.monotonic() - t0) * 1000.0)
        return None

    def release(self, request) -> None:
        """Return one admitted request's charges. Idempotent per request
        (the `_admitted` flag): error paths between schedule() and
        terminal bookkeeping may release defensively."""
        if not getattr(request, "_admitted", False):
            return
        request._admitted = False
        tenant = getattr(request, "tenant", "") or request.model or "-"
        grant: Optional[_Waiter] = None
        with self._mu:
            self._global_inflight = max(0, self._global_inflight - 1)
            st = self._tenants.get(tenant)
            if st is not None:
                st.inflight = max(0, st.inflight - 1)
            now = self._clock()
            if self._last_release > 0.0 and now > self._last_release:
                inst = 1.0 / (now - self._last_release)
                self._release_rate += RATE_ALPHA * (
                    inst - self._release_rate
                )
            self._last_release = now
            grant = self._grant_next_locked()
        if self._m_tenant_inflight is not None and st is not None:
            self._m_tenant_inflight.labels(tenant=tenant).set(
                float(st.inflight)
            )
        if grant is not None:
            grant.event.set()

    # ------------------------------------------------------------------ #
    # internals (all _locked helpers run under self._mu)
    # ------------------------------------------------------------------ #

    def _admit_locked(self, tenant: str, st: _TenantState) -> None:
        st.inflight += 1
        self._global_inflight += 1
        self.admitted_total += 1
        if self._m_tenant_inflight is not None:
            self._m_tenant_inflight.labels(tenant=tenant).set(
                float(st.inflight)
            )

    def _grant_next_locked(self) -> Optional[_Waiter]:
        """Deficit-weighted round-robin over waiting tenants: every
        grant opportunity adds each waiting tenant's weight to its
        credit, and the largest credit wins (then pays 1.0). A weight-4
        tenant therefore drains its queue 4x as fast as a weight-1
        tenant, and an idle tenant accrues nothing (credits exist only
        while waiting)."""
        if not self._waiting or self._global_inflight >= self._global_cap():
            return None
        best_tenant = None
        best_credit = -math.inf
        for tenant in self._waiting:
            st = self._tenants.get(tenant)
            if st is None:
                continue
            if st.inflight >= self._tenant_cap():
                continue  # its own cap holds it back, not fairness
            st.credit += self._weight(tenant)
            if st.credit > best_credit:
                best_credit = st.credit
                best_tenant = tenant
        if best_tenant is None:
            return None
        q = self._waiting[best_tenant]
        waiter = q.popleft()
        if not q:
            self._waiting.pop(best_tenant, None)
        st = self._tenants[best_tenant]
        st.credit -= 1.0
        if best_tenant not in self._waiting:
            st.credit = 0.0  # queue drained: no banked advantage
        waiter.granted = True
        self._admit_locked(best_tenant, st)
        return waiter

    def _num_waiting(self) -> int:
        with self._mu:
            return sum(len(q) for q in self._waiting.values())

    def _wait_estimate_locked(self) -> float:
        """Expected seconds until a NEW waiter would be granted: queue
        depth ahead of it over the observed release rate. Zero observed
        rate (cold start) estimates one queue-timeout — optimistic
        enough to try waiting once, pessimistic enough that a dead
        fleet sheds on the second look."""
        depth = sum(len(q) for q in self._waiting.values()) + 1
        if self._release_rate <= 0.0:
            return float(depth) * max(self._queue_timeout_s(), 1.0)
        return depth / self._release_rate

    def _shed_locked(self, request, tenant: str, reason: str,
                     retry_after_s: float) -> Status:
        return self._shed(request, tenant, reason, retry_after_s)

    def _shed(self, request, tenant: str, reason: str,
              retry_after_s: float) -> Status:
        self.sheds[reason] = self.sheds.get(reason, 0) + 1
        if self._m_sheds is not None:
            self._m_sheds.labels(reason=reason).inc()
        request.retry_after_s = max(1.0, math.ceil(retry_after_s))
        return Status(
            StatusCode.RESOURCE_EXHAUSTED,
            f"admission: tenant {tenant!r} shed ({reason}); retry after "
            f"{request.retry_after_s:.0f}s",
        )

    # ------------------------------------------------------------------ #
    # introspection (bench_fleet / tests)
    # ------------------------------------------------------------------ #

    @property
    def global_inflight(self) -> int:
        return self._global_inflight

    @property
    def queued_waiters(self) -> int:
        return self._num_waiting()

    def tenant_inflight(self, tenant: str) -> int:
        with self._mu:
            st = self._tenants.get(tenant)
            return st.inflight if st is not None else 0

    def sheds_total(self) -> int:
        return sum(self.sheds.values())
