"""OpenAI-compatible response construction.

Builds chat.completion[.chunk] / text_completion JSON (logprobs, usage,
finish_reason, the terminal `data: [DONE]`) from engine RequestOutputs
(reference: xllm_service/scheduler/response_handler.{h,cpp} — streaming chat
:20-114, streaming completion :116-196, non-stream :198-306) over a
transport-agnostic ClientStream so HTTP/SSE lives in the API tier
(the reference couples this to brpc call_data).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from xllm_service_tpu.common.types import (
    FinishReason,
    LogProb,
    RequestOutput,
    SequenceOutput,
    StatusCode,
)
from xllm_service_tpu.service.request import ServiceRequest


class ClientStream:
    """Transport seam (reference: StreamCallData/CallData, call_data.h).

    write/write_done return False when the client went away — the scheduler
    uses that to cancel upstream generation."""

    def write(self, payload: Dict[str, Any]) -> bool:
        raise NotImplementedError

    def write_done(self) -> bool:
        """Terminal SSE `data: [DONE]` marker (no-op for non-stream)."""
        return True

    def finish(self, payload: Dict[str, Any]) -> bool:
        """Single non-streaming response body."""
        raise NotImplementedError

    def finish_with_error(self, code: StatusCode, message: str) -> bool:
        raise NotImplementedError


def _chat_logprobs(logprobs: List[LogProb]) -> Optional[Dict[str, Any]]:
    if not logprobs:
        return None
    content = []
    for lp in logprobs:
        content.append(
            {
                "token": lp.data.token,
                "logprob": lp.data.logprob,
                "bytes": list(lp.data.token.encode("utf-8")),
                "top_logprobs": [
                    {
                        "token": t.token,
                        "logprob": t.logprob,
                        "bytes": list(t.token.encode("utf-8")),
                    }
                    for t in lp.top_logprobs
                ],
            }
        )
    return {"content": content}


def _completion_logprobs(logprobs: List[LogProb]) -> Optional[Dict[str, Any]]:
    if not logprobs:
        return None
    return {
        "tokens": [lp.data.token for lp in logprobs],
        "token_logprobs": [lp.data.logprob for lp in logprobs],
        "top_logprobs": [
            {t.token: t.logprob for t in lp.top_logprobs} for lp in logprobs
        ],
        "text_offset": [],
    }


def _usage_json(output: RequestOutput) -> Optional[Dict[str, Any]]:
    if output.usage is None:
        return None
    return {
        "prompt_tokens": output.usage.num_prompt_tokens,
        "completion_tokens": output.usage.num_generated_tokens,
        "total_tokens": output.usage.num_total_tokens,
    }


def _finish_reason(seq: SequenceOutput) -> Optional[str]:
    return seq.finish_reason.to_string()


def accumulate_sequences(
    acc: Dict[int, SequenceOutput], output: RequestOutput
) -> None:
    """Merge one step's per-sequence deltas into an accumulator keyed by
    sequence index — the single merge used by both the service scheduler
    (non-stream responses) and the instance's direct mode."""
    for seq in output.outputs:
        cur = acc.get(seq.index)
        if cur is None:
            acc[seq.index] = SequenceOutput(
                index=seq.index,
                text=seq.text,
                token_ids=list(seq.token_ids),
                finish_reason=seq.finish_reason,
                logprobs=list(seq.logprobs),
            )
        else:
            cur.text += seq.text
            cur.token_ids.extend(seq.token_ids)
            cur.logprobs.extend(seq.logprobs)
            if seq.finish_reason != FinishReason.NONE:
                cur.finish_reason = seq.finish_reason


class ResponseHandler:
    """Stateless JSON builders + the stream/non-stream send policies
    (reference: response_handler.cpp)."""

    # ------------------------------------------------------------------ #
    # streaming
    # ------------------------------------------------------------------ #

    def send_delta_to_client(
        self,
        stream: ClientStream,
        request: ServiceRequest,
        output: RequestOutput,
        first_chunk_sent: bool,
    ) -> bool:
        """One generation step -> SSE chunk(s). Returns False if the client
        disconnected (reference: streaming paths, response_handler.cpp:20-196).
        """
        created = int(request.created_time)
        ok = True
        for seq in output.outputs:
            if request.is_chat:
                delta: Dict[str, Any] = {}
                if not first_chunk_sent:
                    delta["role"] = "assistant"
                if seq.text:
                    delta["content"] = seq.text
                chunk = {
                    "id": request.service_request_id,
                    "object": "chat.completion.chunk",
                    "created": created,
                    "model": request.model,
                    "choices": [
                        {
                            "index": seq.index,
                            "delta": delta,
                            "logprobs": _chat_logprobs(seq.logprobs),
                            "finish_reason": _finish_reason(seq),
                        }
                    ],
                }
            else:
                chunk = {
                    "id": request.service_request_id,
                    "object": "text_completion",
                    "created": created,
                    "model": request.model,
                    "choices": [
                        {
                            "index": seq.index,
                            "text": seq.text,
                            "logprobs": _completion_logprobs(seq.logprobs),
                            "finish_reason": _finish_reason(seq),
                        }
                    ],
                }
            request.trace("out", chunk)
            ok = stream.write(chunk) and ok
            if not ok:
                return False
        if output.finished:
            if request.include_usage and output.usage is not None:
                usage_chunk = {
                    "id": request.service_request_id,
                    "object": "chat.completion.chunk"
                    if request.is_chat
                    else "text_completion",
                    "created": created,
                    "model": request.model,
                    "choices": [],
                    "usage": _usage_json(output),
                }
                request.trace("out", usage_chunk)
                ok = stream.write(usage_chunk) and ok
            ok = stream.write_done() and ok
        return ok

    # ------------------------------------------------------------------ #
    # non-streaming
    # ------------------------------------------------------------------ #

    def send_result_to_client(
        self,
        stream: ClientStream,
        request: ServiceRequest,
        output: RequestOutput,
    ) -> bool:
        """Full accumulated result -> single response body
        (reference: response_handler.cpp:198-306)."""
        if not output.status.ok():
            return stream.finish_with_error(output.status.code, output.status.message)
        created = int(request.created_time)
        if request.is_chat:
            choices = []
            for seq in output.outputs:
                message: Dict[str, Any] = {
                    "role": "assistant", "content": seq.text,
                }
                finish = _finish_reason(seq) or "stop"
                if request.tools:
                    # service/tool_calls.py: Hermes/Qwen <tool_call>
                    # spans -> OpenAI message.tool_calls (non-streaming
                    # only; streaming emits the spans as content).
                    from xllm_service_tpu.service.tool_calls import (
                        parse_tool_calls,
                    )

                    content, calls = parse_tool_calls(
                        seq.text, request.service_request_id, seq.index
                    )
                    if calls:
                        message["content"] = content
                        message["tool_calls"] = calls
                        if finish == "stop":
                            finish = "tool_calls"
                choices.append({
                    "index": seq.index,
                    "message": message,
                    "logprobs": _chat_logprobs(seq.logprobs),
                    "finish_reason": finish,
                })
            body = {
                "id": request.service_request_id,
                "object": "chat.completion",
                "created": created,
                "model": request.model,
                "choices": choices,
            }
        else:
            choices = [
                {
                    "index": seq.index,
                    "text": seq.text,
                    "logprobs": _completion_logprobs(seq.logprobs),
                    "finish_reason": _finish_reason(seq) or "stop",
                }
                for seq in output.outputs
            ]
            body = {
                "id": request.service_request_id,
                "object": "text_completion",
                "created": created,
                "model": request.model,
                "choices": choices,
            }
        usage = _usage_json(output)
        if usage is not None:
            body["usage"] = usage
        request.trace("out", body)
        return stream.finish(body)
