"""Service-tier orchestration core.

TPU-native redesign of the reference Scheduler
(reference: xllm_service/scheduler/scheduler.{h,cpp}): owns the tokenizer +
chat template, the coordination store + master election, the cluster
managers and routing policy, the request registry, and the ordered output
lanes. `schedule()` is the request hot path (template -> tokenize -> policy
-> metrics, scheduler.cpp:73-106); `handle_generation()` the token hot path
(per-request serialized dispatch, :293-336); the master loop replicates
cluster state every heartbeat period (:113-121).

Additions over the reference, per SURVEY.md §5/§7:
  * hybrid online/offline admission — `offline` requests are parked under
    cluster pressure and re-dispatched when load drops (the reference only
    declares the flag, request.h:38);
  * real disconnected-instance pruning on the master loop;
  * graceful stop that drains instead of the reference's exit(1) handler.
"""

from __future__ import annotations

import logging
import math
import os
import re
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional

import numpy as np

from xllm_service_tpu.cluster.encoder_fabric import EncoderFabric
from xllm_service_tpu.cluster.global_kvcache_mgr import GlobalKVCacheMgr
from xllm_service_tpu.cluster.goodput import GoodputController
from xllm_service_tpu.cluster.instance_mgr import HealthState, InstanceMgr
from xllm_service_tpu.cluster.policies import LoadBalancePolicy, make_policy
from xllm_service_tpu.cluster.prefix_fabric import PrefixFabric
from xllm_service_tpu.common.config import ServiceConfig
from xllm_service_tpu.common.types import (
    FinishReason,
    KvCacheEvent,
    LatencyMetrics,
    LoadMetrics,
    RequestAction,
    RequestOutput,
    Routing,
    SequenceOutput,
    Status,
    StatusCode,
    Usage,
)
from xllm_service_tpu.common import faults
from xllm_service_tpu.coordination import store as coord_store
from xllm_service_tpu.coordination.election import (
    MASTER_RPC_KEY,
    MasterElection,
)
from xllm_service_tpu.coordination.store import CoordinationStore, connect
from xllm_service_tpu.obs import (
    LATENCY_BUCKETS_MS,
    FlightRecorder,
    MetricsRegistry,
    SpanRing,
)
from xllm_service_tpu.service.admission import AdmissionController
from xllm_service_tpu.service.ordered_streams import OrderedStreams
from xllm_service_tpu.service.request import (
    RequestTracer,
    ServiceRequest,
    StopStringMonitor,
)
from xllm_service_tpu.service.response_handler import (
    ClientStream,
    ResponseHandler,
    accumulate_sequences,
)
from xllm_service_tpu.tokenizer import ChatTemplate, Tokenizer, create_tokenizer

logger = logging.getLogger(__name__)

# Park offline work when every prefill candidate has this many waiters.
OFFLINE_PRESSURE_WAITING = 4

# Control-plane mastership states (docs/FAULT_TOLERANCE.md):
#   STANDBY     — not holding the lease; the front door redirects to the
#                 current master and this replica never dispatches;
#   RECONCILING — lease just won; new work is PARKED (not 500'd) while
#                 the takeover scan rebuilds per-instance load, inflight
#                 charges, and the KV index from instance /reconcile
#                 manifests;
#   ACTIVE      — reconciled; dispatch flows.
MASTER_STANDBY = "standby"
MASTER_RECONCILING = "reconciling"
MASTER_ACTIVE = "active"

# How long a dispatch parks behind an in-flight reconcile before giving
# up (reconciles are one bounded RPC per instance — seconds, not minutes).
RECONCILE_PARK_TIMEOUT_S = 15.0


class NotMasterError(RuntimeError):
    """Raised by the dispatch wrapper when this replica is not the ACTIVE
    master: a demoted master must stop dispatching IMMEDIATELY (epoch
    fencing makes the instance reject it anyway; this stops the attempt
    at the source)."""


@dataclass
class _RequestState:
    request: ServiceRequest
    stream: ClientStream
    lane: int
    # api-tier hook to propagate cancellation to the engine instance
    cancel_callback: Optional[Callable[[], None]] = None
    # api-tier hook to (re-)forward the request to its routed prefill
    # instance; enables automatic re-dispatch after instance death
    dispatch: Optional[Callable[[], None]] = None
    redispatch_count: int = 0
    first_chunk_sent: bool = False
    prefill_finished: bool = False
    # Dispatch-attempt epoch: bumped on every replay; outputs arriving
    # under an older wire id (request.wire_srid) are late pushes from a
    # dead attempt and must never reach the client stream.
    attempt: int = 0
    # Thread id of an in-flight replay (0 = none): two failure signals —
    # e.g. the master's dispatch-exception handler and the removal
    # listener — must not replay the same request concurrently (double
    # dispatch); same-thread re-entry stays allowed for nested recovery.
    replaying: int = 0
    # Monotonic stamp of an in-flight resume (cleared by the first fresh
    # delivery; feeds the resume-latency histogram).
    resume_mono: float = 0.0
    # Observability timestamps (one monotonic clock): registration,
    # first dispatch, first token, and the latest token delivery.
    sched_mono: float = 0.0
    dispatch_mono: float = 0.0
    first_token_mono: float = 0.0
    last_token_mono: float = 0.0
    # Error-finish marker (fail_request): finish_request reports the
    # outcome as "error" instead of "cancelled".
    failed: bool = False
    # Per-sequence stop-string matchers (OpenAI `stop`), lazily created.
    stop_monitors: Dict[int, "StopStringMonitor"] = field(default_factory=dict)
    # Generated tokens dropped by stop truncation (subtracted from usage).
    stop_dropped: int = 0
    # accumulated per-sequence state for non-stream responses
    acc: Dict[int, SequenceOutput] = field(default_factory=dict)
    usage: Optional[Usage] = None
    done: bool = False


class Scheduler:
    def __init__(
        self,
        config: ServiceConfig,
        store: Optional[CoordinationStore] = None,
        tokenizer: Optional[Tokenizer] = None,
        identity: str = "",
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        self._config = config
        # Injectable monotonic clock for the CONTROL-plane components
        # whose expiry/EWMA decisions must be testable and simulatable
        # (instance health, goodput freshness, admission buckets). The
        # request-path latency histograms stay on time.monotonic — they
        # time real work. None = wall monotonic.
        self._ctrl_clock: Callable[[], float] = clock or time.monotonic
        self._store = store if store is not None else connect(config.etcd_addr)
        self._tokenizer = tokenizer or create_tokenizer(config.tokenizer_path)
        self._chat_template = ChatTemplate(self._tokenizer)
        # Always-on flight-recorder ring (obs/flight.py): every lifecycle
        # span the tracer emits is mirrored here regardless of
        # --enable_request_trace, so the master always has a recent-span
        # window to dump on anomalies and to serve GET /trace from.
        self.span_ring = SpanRing(
            "master",
            int(
                os.environ.get("XLLM_TRACE_RING", "")
                or getattr(config, "trace_ring_capacity", 2048)
            ),
        )
        self._tracer = RequestTracer(
            config.trace_dir, config.enable_request_trace,
            keep=getattr(config, "trace_keep", 1), ring=self.span_ring,
        )
        # Which instances participated in each request's trace (prefill /
        # decode / encode names recorded at every dispatch attempt),
        # bounded so finished requests stay collectable for a while.
        self._trace_parts: "OrderedDict[str, List[str]]" = OrderedDict()
        # Installed by the Master: transport for role-flip notifications
        # ((instance_name, new_role) -> POST instance /flip).
        self.on_role_flip = None
        # Installed by the Master: takeover-reconciliation transport
        # ((meta, body) -> instance POST /reconcile response dict).
        self.on_reconcile = None
        # Installed by the Master: this replica's instance-plane address,
        # advertised under the election lease so deposed masters can
        # re-point heartbeating instances at the successor.
        self.advertised_rpc = ""

        # Mastership state machine (docs/FAULT_TOLERANCE.md): dispatch is
        # gated on ACTIVE; RECONCILING parks it, STANDBY rejects it.
        self._master_state = MASTER_STANDBY
        self._dispatch_gate = threading.Event()
        self._reconcile_thread: Optional[threading.Thread] = None
        self._takeover_elected_mono = 0.0
        # Bench/report surfaces (plain attrs; the histograms below carry
        # the same numbers into /metrics).
        self.last_takeover_ms: Optional[float] = None
        self.takeover_first_dispatch_ms: Optional[float] = None
        self.total_reconciled = 0
        self.total_orphaned = 0

        # Service-tier metrics registry (obs.metrics): the master's
        # /metrics renders this alongside the HTTP-plane registries and
        # the scraped per-instance expositions.
        self.metrics = MetricsRegistry()
        # Anomaly flight recorder: dumps the span ring to
        # <trace_dir>/flight on SLO breach / breaker ejection triggers
        # (instances run their own; docs/OBSERVABILITY.md).
        self.flight = FlightRecorder(
            self.span_ring,
            os.path.join(config.trace_dir, "flight"),
            registry=self.metrics,
        )
        self._m_requests = self.metrics.counter(
            "xllm_service_requests_total",
            "Requests accepted by schedule()", labelnames=("kind",),
        )
        self._m_finished = self.metrics.counter(
            "xllm_service_finished_total",
            "Requests finished by outcome", labelnames=("outcome",),
        )
        self.metrics.counter(
            "xllm_service_redispatches_total",
            "Requests transparently replayed after instance death",
        ).set_function(lambda: self.total_redispatches)
        self.metrics.counter(
            "xllm_service_redispatch_attempts_total",
            "Replay attempts (redispatch + resume), successful or not",
        ).set_function(lambda: self.total_redispatch_attempts)
        self.metrics.counter(
            "xllm_service_resumes_total",
            "Mid-stream token-replay resumes completed after instance "
            "death",
        ).set_function(lambda: self.total_resumes)
        self.m_cancel_errors = self.metrics.counter(
            "xllm_service_cancel_errors_total",
            "Instance /cancel calls that failed (previously swallowed "
            "silently)",
        )
        self._m_resume_latency = self.metrics.histogram(
            "xllm_service_resume_latency_ms",
            "Resume initiation -> first post-resume token delivery",
            buckets=LATENCY_BUCKETS_MS,
        )
        self._m_ttft = self.metrics.histogram(
            "xllm_service_ttft_ms",
            "Client-perceived time to first token (schedule -> first "
            "delivery)", buckets=LATENCY_BUCKETS_MS,
        )
        self._m_tpot = self.metrics.histogram(
            "xllm_service_tpot_ms",
            "Inter-delivery gap after the first token",
            buckets=LATENCY_BUCKETS_MS,
        )
        self._m_queue_delay = self.metrics.histogram(
            "xllm_service_queue_delay_ms",
            "Schedule -> first dispatch to an instance (offline parking "
            "included)", buckets=LATENCY_BUCKETS_MS,
        )
        self._m_e2e = self.metrics.histogram(
            "xllm_service_e2e_ms",
            "Schedule -> terminal bookkeeping", buckets=LATENCY_BUCKETS_MS,
        )
        self.metrics.gauge(
            "xllm_service_inflight_requests", "Registered, unfinished "
            "requests",
        ).set_function(lambda: self.num_inflight)
        self.metrics.gauge(
            "xllm_service_is_master", "1 when this replica holds the "
            "master lease",
        ).set_function(lambda: int(self._election.is_master))
        self.metrics.gauge(
            "xllm_service_offline_parked_requests", "Offline requests "
            "parked under cluster pressure",
        ).set_function(lambda: len(self._offline_parked))
        self.metrics.counter(
            "xllm_service_trace_dropped_total", "Trace records lost to "
            "disk-write failures",
        ).set_function(lambda: self._tracer.dropped)
        self.metrics.gauge(
            "xllm_master_epoch", "Fencing epoch of this replica's most "
            "recent won master term (0 = never elected)",
        ).set_function(lambda: self._election.epoch)
        self._m_takeover = self.metrics.histogram(
            "xllm_master_takeover_ms",
            "Master takeover: lease won -> reconciliation complete "
            "(dispatch unparked)", buckets=LATENCY_BUCKETS_MS,
        )
        self.metrics.counter(
            "xllm_service_reconciled_requests_total",
            "In-flight instance manifests reclaimed by a takeover "
            "reconciliation (orphans are reaped instance-side and counted "
            "in xllm_service_orphan_reaped_total there)",
        ).set_function(lambda: self.total_reconciled)
        self.metrics.counter(
            "xllm_coord_watch_reconnects_total",
            "Coordination-store watch streams reconnected after a "
            "failure (jittered exponential backoff)",
        ).set_function(coord_store.watch_reconnects_total)

        self._election = MasterElection(
            self._store,
            identity=identity or f"{config.host}:{config.http_port}",
            lease_ttl_s=config.master_lease_ttl_s,
            on_elected=self._on_elected,
            on_lost=self._on_lost,
        )
        self._instance_mgr = InstanceMgr(
            self._store,
            is_master=lambda: self._election.is_master,
            detect_disconnected_interval_s=(
                config.detect_disconnected_instance_interval_s
            ),
            suspect_failures=getattr(config, "breaker_suspect_failures", 2),
            eject_failures=getattr(config, "breaker_eject_failures", 4),
            clock=self._ctrl_clock,
        )
        self._kvcache_mgr = GlobalKVCacheMgr(
            self._store,
            is_master=lambda: self._election.is_master,
            block_size=config.block_size,
            murmur_hash3_seed=config.murmur_hash3_seed,
        )
        # Fleet-wide prefix KV fabric (cluster/prefix_fabric.py): fetch
        # hints at dispatch, fetch-cost-adjusted CAR scoring, and the
        # coordinated-eviction decisions behind /rpc/fabric/evict_offer.
        self.prefix_fabric = PrefixFabric(
            config, self._instance_mgr, self._kvcache_mgr,
            metrics=self.metrics, span_hook=self.span_ring.emit,
        )
        # Encoder fabric (cluster/encoder_fabric.py, docs/EPD.md): the
        # fleet media-embedding index behind hit-aware encoder routing.
        # Fed by ENCODE-role heartbeat cache deltas; pruned/resynced with
        # the same breaker hardening as the KV index.
        self.encoder_fabric = EncoderFabric(
            config, self._instance_mgr, metrics=self.metrics,
            span_hook=self.span_ring.emit,
        )
        # Goodput controller plane (cluster/goodput.py): per-request
        # colocate-vs-disaggregate placement consulted in schedule(),
        # plus the periodic role-reshaping tick on the master loop.
        self.goodput = GoodputController(
            config, self._instance_mgr, metrics=self.metrics,
            clock=self._ctrl_clock,
        )
        # Front-door admission (service/admission.py): per-tenant rate +
        # inflight caps with fair-share queuing; consulted at the very
        # top of schedule(), released at terminal request bookkeeping.
        self.admission = AdmissionController(
            config, metrics=self.metrics, clock=self._ctrl_clock,
        )
        self._policy: LoadBalancePolicy = make_policy(
            config.load_balance_policy,
            self._instance_mgr,
            self._kvcache_mgr,
            target_ttft_ms=config.target_ttft_ms,
            target_tpot_ms=config.target_tpot_ms,
            fabric=self.prefix_fabric,
        )
        self._response_handler = ResponseHandler()
        self._streams = OrderedStreams(config.num_ordered_output_streams)
        # Re-dispatch interrupted requests when their instance dies (the
        # reference only promises this — README.md:46; its failure surface
        # is an error-finish, SURVEY.md §3.5 note).
        self._instance_mgr.add_removal_listener(self._on_instance_removed)
        self._instance_mgr.add_removal_listener(
            self._kvcache_mgr.remove_instance
        )
        self._instance_mgr.add_removal_listener(
            self.encoder_fabric.remove_instance
        )
        # Stale-location pruning: an EJECTED instance's KV-index locations
        # would otherwise linger until lease expiry, letting cache-aware
        # routing (and the fabric's fetch planner) score phantom hits on
        # an unroutable peer. Deregistration/prune is covered by the
        # removal listener above; this covers the breaker path. Pruned
        # instances are flagged for a full cache resync on their next
        # heartbeat (deltas cannot rebuild dropped locations).
        self._cache_resync_needed: set = set()
        self._instance_mgr.add_health_listener(self._on_instance_health)
        self.max_redispatch = getattr(config, "max_redispatch", 2)
        # Cluster-lifetime fault accounting (aggregated /metrics +
        # bench_serving's fault-injection report).
        self.total_redispatches = 0
        self.total_redispatch_attempts = 0
        self.total_resumes = 0

        self._mu = threading.Lock()
        self._requests: Dict[str, _RequestState] = {}
        # parked offline work: (request, dispatch_callback)
        self._offline_parked: Deque = deque()

        self._stop = threading.Event()
        self._master_thread = threading.Thread(
            target=self._master_loop, name="scheduler-master", daemon=True
        )
        self._master_thread.start()
        # Campaign LAST: a synchronous win fires _on_elected, whose
        # reconcile thread touches every manager constructed above.
        self._election.start()

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #

    @property
    def is_master(self) -> bool:
        return self._election.is_master

    @property
    def master_state(self) -> str:
        return self._master_state

    @property
    def master_epoch(self) -> int:
        """Fencing epoch stamped on every master->instance RPC."""
        return self._election.epoch

    @property
    def election_identity(self) -> str:
        return self._election.identity

    def current_master_identity(self) -> str:
        """The identity (host:http_port) holding the master lease NOW —
        the redirect target for a standby's front door."""
        try:
            return self._election.current_master() or ""
        except Exception:
            return ""

    # ------------------------------------------------------------------ #
    # fenced master failover (docs/FAULT_TOLERANCE.md, control plane)
    # ------------------------------------------------------------------ #

    def _on_elected(self) -> None:
        """Lease won (epoch committed in the same store txn). Enter
        RECONCILING — new work parks, nothing dispatches — and rebuild
        cluster state from instance manifests on a dedicated thread (this
        callback may run on the store's watch-notifier thread, which must
        never block on instance RPCs)."""
        epoch = self._election.epoch
        with self._mu:
            self._master_state = MASTER_RECONCILING
            self._takeover_elected_mono = time.monotonic()
            self.takeover_first_dispatch_ms = None
        logger.info(
            "elected master (epoch %d): reconciling cluster state", epoch
        )
        t = threading.Thread(
            target=self._reconcile_run, args=(epoch,),
            name="master-reconcile", daemon=True,
        )
        self._reconcile_thread = t
        t.start()

    def _on_lost(self) -> None:
        """Demoted (lease lost / store partition): stop dispatching NOW.
        In-flight exchanges are error-finished so their clients retry
        against the current master instead of hanging on a replica whose
        RPCs the fleet now rejects; the front door (api tier) redirects
        from here on."""
        with self._mu:
            self._master_state = MASTER_STANDBY
            self._dispatch_gate.clear()
            inflight = [
                s.request.service_request_id
                for s in self._requests.values()
                if not s.done
            ]
        cur = self.current_master_identity()
        logger.warning(
            "demoted from master (epoch %d was fenced); failing %d "
            "in-flight requests toward current master %s",
            self._election.epoch, len(inflight), cur or "<none>",
        )
        for srid in inflight:
            self.fail_request(
                srid,
                StatusCode.UNAVAILABLE,
                "master demoted mid-request; retry against current "
                f"master {cur or 'unknown'}",
            )

    def _reconcile_run(self, epoch: int) -> None:
        """Takeover reconciliation: for every registered instance, pull
        its in-flight manifest over POST /reconcile and rebuild the
        per-instance request charges, load metrics, and the global KV
        index. Manifest entries this master does not claim (`known`) are
        reaped instance-side after the advertised TTL — no KV leaks from
        a dead master's requests. Any instance failure is skipped: a dead
        instance must not block the takeover (its state re-syncs through
        heartbeats or pruning)."""
        t0 = time.monotonic()
        takeover = epoch > 1  # epoch 1 = cluster birth, nothing to reclaim
        try:
            instances = self._instance_mgr.list_instances()
            if instances:
                # The transport is installed by the api tier right after
                # construction; tolerate that boot-order window.
                deadline = t0 + 2.0
                while (
                    self.on_reconcile is None
                    and time.monotonic() < deadline
                    and not self._stop.is_set()
                ):
                    time.sleep(0.02)
            with self._mu:
                known_by_instance: Dict[str, set] = {}
                for s in self._requests.values():
                    if s.done:
                        continue
                    wire = (
                        s.request.wire_srid
                        or s.request.service_request_id
                    )
                    for name in {
                        s.request.routing.prefill_name,
                        s.request.routing.decode_name,
                    }:
                        if name:
                            known_by_instance.setdefault(name, set()).add(
                                wire
                            )
            if self.on_reconcile is not None:
                for meta in instances:
                    if self._stop.is_set():
                        return
                    # Epoch-keyed abandonment: a demote -> re-elect cycle
                    # starts a NEW reconcile thread for the new term;
                    # this one must stop even though the state reads
                    # RECONCILING again (it belongs to the new epoch).
                    if (
                        self._master_state != MASTER_RECONCILING
                        or self._election.epoch != epoch
                    ):
                        return
                    self._reconcile_instance(
                        meta, epoch,
                        sorted(known_by_instance.get(meta.name, ())),
                    )
        finally:
            # Only the thread whose term is STILL current completes the
            # takeover: an abandoned term must neither unpark dispatch
            # against a half-rebuilt view nor record a takeover sample.
            flipped = False
            with self._mu:
                if (
                    self._master_state == MASTER_RECONCILING
                    and self._election.epoch == epoch
                ):
                    self._master_state = MASTER_ACTIVE
                    self._dispatch_gate.set()
                    flipped = True
            if flipped:
                self.advertise_master_rpc()
                ms = (time.monotonic() - t0) * 1000.0
                if takeover:
                    self._m_takeover.observe(ms)
                    self.last_takeover_ms = ms
                logger.info(
                    "reconciliation complete in %.1f ms (reclaimed=%d "
                    "orphaned=%d)", ms, self.total_reconciled,
                    self.total_orphaned,
                )

    def _reconcile_instance(self, meta, epoch: int, known: List[str]) -> None:
        body = {
            "master_epoch": epoch,
            "master": self._election.identity,
            "known": known,
            "orphan_ttl_s": getattr(
                self._config, "reconcile_orphan_ttl_s", 10.0
            ),
        }
        try:
            # Chaos hook: a dropped/errored reconcile exercises the
            # skip-and-continue path (heartbeats re-sync the instance).
            faults.point("reconcile.send", instance=meta.name, epoch=epoch)
            resp = self.on_reconcile(meta, body)
        except Exception as e:
            logger.warning("reconcile of %s failed: %s", meta.name, e)
            return
        if self._election.epoch != epoch:
            # Term changed while the RPC was in flight: the new term's
            # thread owns absorption (a stale absorb would double-count
            # and schedule a duplicate orphan unwind).
            return
        if not isinstance(resp, dict) or not resp.get("ok"):
            logger.warning("reconcile of %s rejected: %s", meta.name, resp)
            return
        manifest = resp.get("manifest") or []
        load = resp.get("load_metrics")
        self._instance_mgr.absorb_reconcile(
            meta.name,
            LoadMetrics.from_json(load) if load else None,
            manifest,
        )
        try:
            hashes = [
                bytes.fromhex(x) for x in resp.get("cache_hashes") or []
            ]
        except ValueError:
            hashes = []
        if hashes:
            self._kvcache_mgr.absorb_instance_snapshot(meta.name, hashes)
        known_set = set(known)
        reclaimed = sum(
            1 for ent in manifest
            if ent.get("service_request_id") in known_set
        )
        orphans = [
            ent for ent in manifest
            if ent.get("service_request_id") not in known_set
        ]
        with self._mu:
            self.total_reconciled += reclaimed
            self.total_orphaned += len(orphans)
        if orphans:
            # The instance reaps unclaimed manifests at the orphan TTL
            # (engine work cancelled, blocks freed); unwind the charges
            # absorbed above on the same clock so the load accounting
            # doesn't carry dead requests forever.
            t = threading.Timer(
                float(body["orphan_ttl_s"]) + 1.0,
                self._unwind_orphan_charges, args=(meta.name, orphans),
            )
            t.daemon = True
            t.start()

    def _unwind_orphan_charges(self, name: str, entries: List[Dict]) -> None:
        routing = Routing(prefill_name=name, decode_name=name)
        for ent in entries:
            try:
                delivered = int(ent.get("delivered_tokens", 0))
                prompt_toks = int(ent.get("prompt_tokens", 0))
            except (TypeError, ValueError):
                continue
            self._instance_mgr.update_request_metrics(
                routing,
                RequestAction.FINISH_DECODE
                if delivered > 0
                else RequestAction.CANCEL,
                prompt_toks,
            )

    def advertise_master_rpc(self) -> None:
        """Publish this master's instance-plane address under its
        election lease: the key dies with the master, and a deposed
        replica hands its current value to heartbeating instances — the
        re-point path that covers instances a /reconcile never reached."""
        if not self.advertised_rpc or not self._election.is_master:
            return
        try:
            self._store.set(
                MASTER_RPC_KEY, self.advertised_rpc,
                lease_id=self._election._lease_id,
            )
        except Exception:
            logger.debug("master rpc advertisement failed", exc_info=True)

    def current_master_rpc(self) -> str:
        """The ACTIVE master's advertised instance-plane address ('' when
        none) — what a deposed master hints to heartbeating instances."""
        try:
            return self._store.get(MASTER_RPC_KEY) or ""
        except Exception:
            return ""

    def _dispatch_allowed(self) -> bool:
        """Gate every master->instance forward on mastership: ACTIVE
        dispatches, RECONCILING parks (bounded wait — reconciles are one
        RPC per instance), STANDBY refuses."""
        if self._dispatch_gate.is_set():
            return True
        if self._master_state == MASTER_RECONCILING:
            self._dispatch_gate.wait(RECONCILE_PARK_TIMEOUT_S)
        return self._dispatch_gate.is_set()

    @property
    def instance_mgr(self) -> InstanceMgr:
        return self._instance_mgr

    @property
    def kvcache_mgr(self) -> GlobalKVCacheMgr:
        return self._kvcache_mgr

    @property
    def tokenizer(self) -> Tokenizer:
        return self._tokenizer

    @property
    def tracer(self) -> RequestTracer:
        return self._tracer

    def record_trace_participants(self, srid: str, names) -> None:
        """Remember which instances took part in one request's trace
        (every dispatch attempt's prefill/decode/encode trio) so the
        GET /trace collector knows whose rings to pull — bounded LRU, so
        recently finished requests stay collectable."""
        with self._mu:
            cur = self._trace_parts.setdefault(srid, [])
            for n in names:
                if n and n not in cur:
                    cur.append(n)
            self._trace_parts.move_to_end(srid)
            while len(self._trace_parts) > 512:
                self._trace_parts.popitem(last=False)

    def trace_participants(self, srid: str) -> List[str]:
        with self._mu:
            return list(self._trace_parts.get(srid, ()))

    @property
    def num_inflight(self) -> int:
        with self._mu:
            return len(self._requests)

    def stop(self, drain_timeout_s: float = 10.0) -> None:
        """Graceful drain (the reference's SIGINT handler calls exit(1),
        master.cpp:143-147 — its stop path is dead code)."""
        deadline = time.monotonic() + drain_timeout_s
        while self.num_inflight and time.monotonic() < deadline:
            time.sleep(0.05)
        self._stop.set()
        # Unblock any dispatch parked behind an in-flight reconcile.
        self._dispatch_gate.set()
        t = self._reconcile_thread
        if t is not None:
            t.join(timeout=2.0)
        self._master_thread.join(timeout=2.0)
        self._streams.shutdown()
        self._instance_mgr.close()
        self._kvcache_mgr.close()
        self._election.stop()
        self._tracer.close()

    def _master_loop(self) -> None:
        """Heartbeat-period state replication + liveness backstop
        (reference: update_master_service_heartbeat, scheduler.cpp:113-121)."""
        period = self._config.heartbeat_interval_s
        while not self._stop.wait(period):
            self.run_master_upkeep()

    def run_master_upkeep(self) -> None:
        """One master-loop iteration, callable out-of-band: the fleet
        simulator (cluster/fleet_sim) drives this at SIMULATED heartbeat
        cadence while the real loop idles on a huge interval."""
        self._pump_offline()
        self._notify_flips()
        # Master-only upkeep runs only once RECONCILED: pruning with a
        # half-rebuilt heartbeat view would mass-evict live instances
        # on the first post-takeover tick.
        if self._master_state != MASTER_ACTIVE:
            return
        try:
            self._kvcache_mgr.upload_kvcache()
            self._instance_mgr.upload_load_metrics()
            # Goodput reshaping: at most one hysteresis-damped,
            # drain-aware role flip per tick (no-op when the
            # controller is off or the fleet census already fits).
            self.goodput.tick()
            # Autoscaling signals (wanted role counts + encoder
            # headroom gauges) ride the same cadence — reshaping
            # re-slices the fleet we have, the signals say how big
            # it should be.
            self.goodput.autoscale_signals()
            # Health breaker upkeep: silent instances turn suspect
            # before the prune backstop removes them, and ejected ones
            # get an active /health probe toward probation.
            self._instance_mgr.mark_stale_suspects()
            self._instance_mgr.probe_unhealthy()
            # pruning fires the removal listeners (re-dispatch + cache
            # index cleanup)
            self._instance_mgr.prune_disconnected()
        except Exception:
            logger.exception("master loop iteration failed")

    def _notify_flips(self) -> None:
        """Tell flipped instances their new role (round-1 weak item 8:
        the registry mutated but the engine never learned it flipped).
        The transport callback is installed by the Master (HTTP POST to
        the instance's /flip); flips are rare, so one daemon thread per
        event keeps the loop unblocked."""
        if self.on_role_flip is None:
            return
        for name, attempt in self._instance_mgr.take_flip_events():
            threading.Thread(
                target=self.on_role_flip,
                args=(name, attempt),
                name=f"flip-notify-{name}",
                daemon=True,
            ).start()

    # ------------------------------------------------------------------ #
    # request hot path
    # ------------------------------------------------------------------ #

    def route_only(self, token_ids=()):
        """Pick an instance pair without registering a generation request —
        one-shot synchronous calls (/v1/embeddings) that still want the
        policy's load/affinity view. None when no instances exist."""
        routing = self._policy.select_instances_pair(list(token_ids))
        if not routing.prefill_name and not routing.decode_name:
            return None
        if not routing.prefill_name:
            routing.prefill_name = routing.decode_name
        return routing

    def schedule(self, request: ServiceRequest) -> Status:
        """Admission gate -> template -> tokenize -> route. Admission
        runs FIRST (a shed must not pay the tokenizer), and any non-OK
        outcome below returns the admitted slot immediately — only an
        OK schedule holds it until finish_request."""
        shed = self.admission.acquire(request)
        if shed is not None:
            self._tracer.stage(
                request.service_request_id, "shed",
                tenant=request.tenant,
                retry_after_s=request.retry_after_s,
            )
            return shed
        status = self._schedule_admitted(request)
        if not status.ok():
            self.admission.release(request)
        return status

    def _schedule_admitted(self, request: ServiceRequest) -> Status:
        """Template -> tokenize -> route (reference: scheduler.cpp:73-106).
        Fills request.token_ids, request.routing, request.estimated_ttft_ms."""
        self._tracer.stage(
            request.service_request_id, "receive",
            kind="chat" if request.is_chat else "completion",
            stream=request.stream, offline=request.offline,
        )
        if request.is_chat and not request.prompt:
            try:
                request.prompt = self._chat_template.apply(
                    request.messages, request.tools
                )
            except Exception as e:
                return Status(StatusCode.INVALID_ARGUMENT, f"chat template: {e}")
        media_status = self._expand_media(request)
        if media_status is not None:
            return media_status
        if not request.token_ids:
            if not request.prompt:
                return Status(StatusCode.INVALID_ARGUMENT, "empty prompt")
            request.token_ids = self._tokenizer.encode(request.prompt)
        if not request.token_ids:
            return Status(StatusCode.INVALID_ARGUMENT, "prompt tokenized to nothing")
        self._tracer.stage(
            request.service_request_id, "tokenize",
            prompt_tokens=len(request.token_ids),
        )

        # ONE index match per request, shared by the routing policy and
        # the fabric's fetch planner/gauge below — the chained hashing +
        # locked index walk must not run twice on the hot path, and must
        # not run AT ALL when nobody consumes it (RR/SLO routing with the
        # fabric disabled: those fleets never hashed prompts before, and
        # the hit-rate gauge is meaningless with both consumers off).
        # Media prompts bypass the cache (embedding-dependent KV).
        from xllm_service_tpu.cluster.policies import CacheAwareRouting

        want_scores = not request.media_parts and (
            isinstance(self._policy, CacheAwareRouting)
            or self.prefix_fabric.enabled()
        )
        scores = (
            self._kvcache_mgr.match(request.token_ids)
            if want_scores else None
        )
        request.routing = self._policy.select_instances_pair(
            request.token_ids, scores=scores
        )
        if not request.routing.prefill_name and not request.routing.decode_name:
            return Status(StatusCode.UNAVAILABLE, "no instances registered")
        if not request.media_parts:
            # Goodput placement (cluster/goodput.py): colocate the decode
            # onto the routed prefill instance's mixed hot loop when the
            # model says the handoff isn't worth it. Gated decisions
            # (controller off, cold EWMA, non-MIX target, ...) come back
            # "static" and leave the policy's pair untouched.
            try:
                covered = 0
                if scores is not None:
                    covered = int(
                        self.prefix_fabric.effective_matched(
                            request.routing.prefill_name, scores
                        ) * self._config.block_size
                    )
                decision = self.goodput.decide_placement(
                    len(request.token_ids), request.model, request.routing,
                    covered_tokens=covered,
                )
                if decision.mode == "colocate":
                    request.routing.decode_name = request.routing.prefill_name
            except Exception:
                logger.exception("goodput placement decision failed")
        if request.media_parts:
            # Three-stage EPD routing: the encoder runs before prefill.
            # Route by MODALITY — encoders host one tower each — and,
            # with the encoder fabric on, by live queue depth + embedding
            # cache hits instead of blind round-robin (docs/EPD.md). The
            # index match always runs (the fleet hit-rate gauge must not
            # flatline during an A/B hatch flip); only the routing
            # consumer is hatch-gated.
            required = {
                {2: "audio", 4: "video"}.get(len(p["shape"]), "image")
                for p in request.media_parts
            }
            hit_scores = None
            try:
                media_hashes = EncoderFabric.hashes_of(request.media_parts)
                matched = (
                    self.encoder_fabric.match(
                        media_hashes, srid=request.service_request_id
                    )
                    if media_hashes else {}
                )
                if self.encoder_fabric.enabled():
                    hit_scores = matched
            except Exception:
                logger.exception("encoder-fabric match failed")
            request.routing.encode_name = (
                self._instance_mgr.next_encode_instance(
                    required, hit_scores=hit_scores
                )
            )
            if not request.routing.encode_name:
                return Status(
                    StatusCode.UNAVAILABLE,
                    f"media request needs an ENCODE instance serving "
                    f"{sorted(required)}; none registered covers it",
                )
        if scores is not None:
            # Prefix-fabric fetch hint (docs/KV_CACHE.md): when the fleet
            # best match beats the routed instance's, name the holder so
            # the instance can pull the gap instead of recomputing it.
            # On cache-aware fleets plan_fetch also runs fabric-OFF: it
            # feeds the fleet-hit-rate gauge either way (no hint when
            # disabled), so flipping the hatch for an A/B never
            # flatlines the gauge.
            try:
                request.kv_fabric = (
                    self.prefix_fabric.plan_fetch(
                        request.token_ids, request.routing.prefill_name,
                        scores=scores, srid=request.service_request_id,
                    )
                    or {}
                )
            except Exception:
                logger.exception("fabric fetch planning failed")
                request.kv_fabric = {}
        pred = self._instance_mgr.get_time_predictor(request.routing.prefill_name)
        if pred is not None and pred.has_ttft_model:
            request.estimated_ttft_ms = pred.predict_ttft(len(request.token_ids))
        self._instance_mgr.update_request_metrics(
            request.routing, RequestAction.SCHEDULE, len(request.token_ids)
        )
        self._tracer.stage(
            request.service_request_id, "route",
            prefill=request.routing.prefill_name,
            decode=request.routing.decode_name,
        )
        self._m_requests.labels(
            kind="chat" if request.is_chat else "completion"
        ).inc()
        return Status(StatusCode.OK)

    _MM_MARKERS = ("<|image|>", "<|video|>", "<|audio|>")
    _MM_DATA_RE = re.compile(
        r"data:application/x-raw-f32;shape=(\d+)x(\d+)x(\d+);base64,(.*)",
        re.S,
    )
    # Video tensor backdoor: T x H x W x C frames (T even — the qwen2vl
    # temporal_patch_size pairs frames).
    _MM_DATA4_RE = re.compile(
        r"data:application/x-raw-f32;shape=(\d+)x(\d+)x(\d+)x(\d+);"
        r"base64,(.*)",
        re.S,
    )
    # Audio tensor backdoor: num_mel_bins x mel_frames log-mel features.
    _MM_DATA2_RE = re.compile(
        r"data:application/x-raw-f32;shape=(\d+)x(\d+);base64,(.*)",
        re.S,
    )

    def _decode_media_part(self, p):
        """One MMContentPart -> ({type, shape, data}, None) or (None,
        error Status). Real images (data:image/...;base64) decode via PIL
        and preprocess with the configured family's HF pixel math
        (service/image_processor.py); the raw-f32 tensor URI remains as
        the pre-encoded backdoor (tests, non-image media)."""
        import base64 as _b64

        from xllm_service_tpu.service import image_processor as _ip

        url = p.url or ""
        if p.type in ("image", "image_url"):
            try:
                img = _ip.decode_image_url(url)
            except ValueError as e:
                return None, Status(StatusCode.INVALID_ARGUMENT, str(e))
            if img is not None:
                proc = self._config.mm_image_processor
                size = self._config.mm_image_size
                if not proc or not size:
                    return None, Status(
                        StatusCode.INVALID_ARGUMENT,
                        "real-image ingestion is not enabled on this "
                        "deployment (set mm_image_processor and "
                        "mm_image_size to match the ENCODE tower)",
                    )
                if proc == "siglip":
                    arr = _ip.preprocess_siglip(img, size)
                elif proc == "qwen2vl":
                    arr = _ip.preprocess_qwen2vl(img, pinned_size=size)
                else:
                    return None, Status(
                        StatusCode.INVALID_ARGUMENT,
                        f"unknown mm_image_processor {proc!r}",
                    )
                return {
                    "type": p.type,
                    "shape": list(arr.shape),
                    "data": _b64.b64encode(
                        np.ascontiguousarray(arr).tobytes()
                    ).decode(),
                }, None
        if p.type in ("video", "video_url"):
            tps_cfg = max(self._config.mm_temporal_patch_size, 1)
            is_real_video = _ip.is_video_data_url(url)
            proc = self._config.mm_image_processor
            size = self._config.mm_image_size
            if is_real_video and (proc != "qwen2vl" or not size):
                # Config check BEFORE the cv2 decode — a misconfigured
                # deployment must reject for free, not after buffering a
                # whole clip (review finding, r5).
                return None, Status(
                    StatusCode.INVALID_ARGUMENT,
                    "real-video ingestion needs mm_image_processor="
                    "'qwen2vl' and mm_image_size (the video-capable "
                    "tower family)",
                )
            try:
                frames = _ip.decode_video_url(
                    url, max_frames=self._config.mm_video_max_frames,
                    temporal_patch=tps_cfg,
                )
            except ValueError as e:
                return None, Status(StatusCode.INVALID_ARGUMENT, str(e))
            if frames is not None:
                # Real compressed video: per-frame HF pixel math (the
                # qwen2vl family's CLIP normalize, pinned to the tower's
                # square) -> the 4D f32 tensor the encode stage carries.
                arr = np.stack([
                    _ip.preprocess_qwen2vl(f, pinned_size=size)
                    for f in frames
                ])
                return {
                    "type": p.type,
                    "shape": list(arr.shape),
                    "data": _b64.b64encode(
                        np.ascontiguousarray(arr).tobytes()
                    ).decode(),
                }, None
            m4 = self._MM_DATA4_RE.match(url)
            if m4:
                T = int(m4.group(1))
                tps = max(self._config.mm_temporal_patch_size, 1)
                if T < tps or T % tps:
                    return None, Status(
                        StatusCode.INVALID_ARGUMENT,
                        f"video needs a frame count that is a positive "
                        f"multiple of temporal_patch_size {tps}, got {T}",
                    )
                return {
                    "type": p.type,
                    "shape": [T] + [int(m4.group(i)) for i in (2, 3, 4)],
                    "data": m4.group(5),
                }, None
        if p.type in ("audio", "audio_url"):
            from xllm_service_tpu.service import audio_processor as _ap

            frames_cfg = self._config.mm_audio_mel_frames
            if _ap.is_audio_data_url(url):
                if not frames_cfg:
                    return None, Status(
                        StatusCode.INVALID_ARGUMENT,
                        "real-audio ingestion is not enabled (set "
                        "mm_audio_mel_frames/mm_audio_mel_bins to the "
                        "ENCODE audio tower's geometry)",
                    )
                try:
                    wav = _ap.decode_audio_url(url)
                except ValueError as e:
                    return None, Status(
                        StatusCode.INVALID_ARGUMENT, str(e)
                    )
                mel = _ap.log_mel(
                    wav, self._config.mm_audio_mel_bins, frames_cfg
                )
                return {
                    "type": p.type,
                    "shape": list(mel.shape),
                    "data": _b64.b64encode(
                        np.ascontiguousarray(mel).tobytes()
                    ).decode(),
                }, None
            m2 = self._MM_DATA2_RE.match(url)
            if m2:
                return {
                    "type": p.type,
                    "shape": [int(m2.group(1)), int(m2.group(2))],
                    "data": m2.group(3),
                }, None
            # NO fallthrough to the image/video tensor regexes: an
            # audio-typed part with a 3D tensor would otherwise be
            # silently ingested as an image, binding wrong embeddings to
            # the audio marker (review finding, r5).
            return None, Status(
                StatusCode.INVALID_ARGUMENT,
                f"unsupported media URL for {p.type}: expected "
                "data:audio/wav;base64 or a "
                "data:application/x-raw-f32;shape=MxT;base64 log-mel "
                "tensor",
            )
        m = self._MM_DATA_RE.match(url)
        if not m:
            return None, Status(
                StatusCode.INVALID_ARGUMENT,
                f"unsupported media URL for {p.type}: expected a "
                "data:image/...;base64 image, data:audio/wav;base64, a "
                "data:application/x-raw-f32;shape=HxWxC;base64 tensor, "
                "(video) ...shape=TxHxWxC, or (audio) ...shape=MxT",
            )
        return {
            "type": p.type,
            "shape": [int(m.group(1)), int(m.group(2)), int(m.group(3))],
            "data": m.group(4),
        }, None

    def _expand_media(self, request: ServiceRequest) -> Optional[Status]:
        """EPD stage-E preparation (SURVEY.md §7 stage 7): media parts in
        chat messages become runs of placeholder tokens in token_ids; the
        raw payloads + placeholder positions ride the request so the master
        can dispatch the encoder before prefill. Returns a Status only on
        error; None means proceed (with or without media)."""
        parts = [
            p
            for m in request.messages
            if isinstance(m.content, list)
            for p in m.content
            if p.type != "text"
        ]
        if not parts:
            return None
        from xllm_service_tpu.service.image_processor import (
            media_content_hash,
        )

        media_parts = []
        for p in parts:
            part, err = self._decode_media_part(p)
            if err is not None:
                return err
            # Content key for the encoder-fabric embedding cache + the
            # master's fleet index (docs/EPD.md): keyed on what the
            # encode stage will actually see, so a re-sent item in a
            # multi-turn chat hits regardless of which encoder served it.
            part["hash"] = media_content_hash(
                {2: "audio", 4: "video"}.get(len(part["shape"]), "img"),
                part["shape"], part["data"],
            )
            media_parts.append(part)
        k = self._config.mm_tokens_per_media
        marker_re = re.compile(
            "(" + "|".join(re.escape(s) for s in self._MM_MARKERS) + ")"
        )
        segments = marker_re.split(request.prompt)
        n_markers = sum(1 for s in segments if s in self._MM_MARKERS)
        if n_markers != len(media_parts):
            # STRICT equality: a literal marker string typed inside a text
            # part would otherwise steal a real image's placeholder slot
            # and bind its embeddings to an attacker-chosen position.
            return Status(
                StatusCode.INVALID_ARGUMENT,
                f"{len(media_parts)} media parts but {n_markers} media "
                "markers in the templated prompt (literal marker text in a "
                "message is not allowed)",
            )
        # Per-part placeholder counts: an image part takes k tokens (the
        # encoder's tokens-per-slice); a video of T frames spans
        # T // tps temporal slices of k tokens each (tps = the tower's
        # temporal_patch_size, config mm_temporal_patch_size). mm_grids
        # carries each part's merged (t, gh, gw) grid for the engine's
        # M-RoPE streams — only when k is a perfect square (the
        # square-tower geometry); otherwise the engine's span inference
        # applies.
        tps = max(self._config.mm_temporal_patch_size, 1)
        s = math.isqrt(k)
        emit_grids = s * s == k
        counts, grids = [], []
        for part in media_parts:
            shape = part["shape"]
            if len(shape) == 2:
                # Audio: tokens are the Whisper conv+pool geometry of
                # the mel length (models/audio.audio_out_tokens); the
                # M-RoPE grid is sequential (t=1, h=1, w=n).
                from xllm_service_tpu.models.audio import audio_out_tokens

                n = audio_out_tokens(shape[1])
                counts.append(n)
                grids.append([1, 1, n])
                continue
            slices = shape[0] // tps if len(shape) == 4 else 1
            counts.append(k * slices)
            grids.append([slices, s, s])
        token_ids: List[int] = []
        positions: List[int] = []
        pi = 0
        for seg in segments:
            if seg in self._MM_MARKERS:
                n = counts[pi]
                pi += 1
                positions.extend(range(len(token_ids), len(token_ids) + n))
                token_ids.extend([0] * n)  # placeholder (pad) tokens
            elif seg:
                token_ids.extend(self._tokenizer.encode(seg))
        request.token_ids = token_ids
        request.mm_positions = positions
        request.media_parts = media_parts
        request.mm_grids = grids if emit_grids else []
        return None

    def should_defer_offline(self, request: ServiceRequest) -> bool:
        """Hybrid scheduling: park offline work while online traffic keeps
        every prefill candidate busy."""
        if not request.offline:
            return False
        load = self._instance_mgr.get_load_metrics()
        candidates = self._instance_mgr.prefill_instances() or list(load)
        if not candidates:
            return False
        return all(
            load.get(n, LoadMetrics()).waiting_requests_num
            >= OFFLINE_PRESSURE_WAITING
            for n in candidates
        )

    def park_offline(
        self, request: ServiceRequest, dispatch: Callable[[], None]
    ) -> None:
        with self._mu:
            self._offline_parked.append((request, dispatch))

    def _pump_offline(self) -> None:
        while True:
            with self._mu:
                if not self._offline_parked:
                    return
                request, dispatch = self._offline_parked[0]
            if self.should_defer_offline(request):
                return
            with self._mu:
                self._offline_parked.popleft()
            try:
                dispatch()
            except NotMasterError as e:
                # Parked work outlived this replica's mastership: error
                # it toward the current master instead of losing it.
                self.fail_request(
                    request.service_request_id,
                    StatusCode.UNAVAILABLE, str(e),
                )
            except Exception:
                logger.exception("offline dispatch failed")

    def record_new_request(
        self,
        request: ServiceRequest,
        stream: ClientStream,
        cancel_callback: Optional[Callable[[], None]] = None,
        dispatch: Optional[Callable[[], None]] = None,
    ) -> Optional[Callable[[], None]]:
        """Register the response route for a scheduled request
        (reference: scheduler.cpp:171-266). Returns the dispatch callable
        the caller should invoke: it wraps the one passed in with span +
        queue-delay instrumentation, and re-dispatch reuses the same
        wrapper so every forward attempt is timed."""
        if self._tracer.enabled:
            request.trace_callback = self._tracer.bind(request.service_request_id)
            request.trace(
                "in",
                {
                    "model": request.model,
                    "stream": request.stream,
                    "prompt_tokens": len(request.token_ids),
                    "routing": request.routing.to_json(),
                },
            )
        state = _RequestState(
            request=request,
            stream=stream,
            lane=self._streams.assign(),
            cancel_callback=cancel_callback,
            sched_mono=time.monotonic(),
        )
        request.wire_srid = request.service_request_id

        if dispatch is not None:
            def dispatch_instrumented() -> None:
                # Mastership gate (docs/FAULT_TOLERANCE.md): a demoted
                # replica must never forward — the fleet would reject its
                # stale epoch anyway; refusing here keeps the failure on
                # this side of the wire. A reconciling master PARKS the
                # dispatch instead (bounded), so takeover never 500s work
                # that arrived mid-transition.
                if not self._dispatch_allowed():
                    raise NotMasterError(
                        "not the active master (state="
                        f"{self._master_state}); current master is "
                        f"{self.current_master_identity() or 'unknown'}"
                    )
                now = time.monotonic()
                first = state.dispatch_mono == 0.0
                if first:
                    state.dispatch_mono = now
                    self._m_queue_delay.observe(
                        (now - state.sched_mono) * 1000.0
                    )
                    if (
                        self.takeover_first_dispatch_ms is None
                        and self._takeover_elected_mono
                        and self._election.epoch > 1
                    ):
                        # Takeover-to-first-dispatch: the acceptance
                        # number the chaos bench reports.
                        self.takeover_first_dispatch_ms = (
                            (now - self._takeover_elected_mono) * 1000.0
                        )
                self._tracer.stage(
                    request.service_request_id, "dispatch",
                    prefill=request.routing.prefill_name,
                    attempt=state.redispatch_count + 1,
                )
                # Trace-collector participant set: every attempt's routed
                # trio, so GET /trace knows which rings to pull.
                self.record_trace_participants(
                    request.service_request_id,
                    (
                        request.routing.prefill_name,
                        request.routing.decode_name,
                        request.routing.encode_name,
                    ),
                )
                dispatch()

            state.dispatch = dispatch_instrumented
        with self._mu:
            self._requests[request.service_request_id] = state
        return state.dispatch

    # ------------------------------------------------------------------ #
    # token hot path
    # ------------------------------------------------------------------ #

    def handle_generation(self, output: RequestOutput) -> bool:
        """One engine step for one request; serialized per request via its
        lane (reference: scheduler.cpp:293-336). Returns False when the
        request is unknown (finished/cancelled) OR the output carries a
        stale attempt's wire id — both tell the caller to stop the
        upstream stream. Outputs arrive keyed by the attempt-versioned
        wire id (`<srid>` or `<srid>#rN`, service/request.py); a replaced
        attempt's late pushes must not interleave with the live one."""
        wire = output.service_request_id
        base, _, _ = wire.partition("#r")
        with self._mu:
            state = self._requests.get(base)
        if state is None or state.done:
            return False
        if wire != (state.request.wire_srid or base):
            return False  # late push from a replaced dispatch attempt
        self._streams.submit(state.lane, lambda: self._deliver(state, output))
        return True

    def _deliver(self, state: _RequestState, output: RequestOutput) -> None:
        if state.done:
            # finish_request/fail_request won the race while this step sat
            # queued in the lane — never write after the exchange ended.
            return
        request = state.request
        if output.service_request_id != (
            request.wire_srid or request.service_request_id
        ):
            # A resume raced this already-queued delivery: the attempt it
            # belongs to was replaced after handle_generation admitted it.
            return
        if request.resume_base and output.usage is not None:
            # Normalize the resumed attempt's local view back to the
            # client's: the replayed tokens ride as prompt on the wire
            # (prompt + acc), but the client sees them as generated.
            output.usage.num_prompt_tokens = max(
                0, output.usage.num_prompt_tokens - request.resume_base
            )
            output.usage.num_generated_tokens += request.resume_base
        if request.stop:
            self._apply_stop_strings(state, output)
            if output.usage is not None and state.stop_dropped:
                # The engine's cumulative usage counts tokens the stop
                # truncation dropped — report what the client received.
                output.usage.num_generated_tokens = max(
                    0, output.usage.num_generated_tokens - state.stop_dropped
                )
        new_tokens = sum(len(seq.token_ids) for seq in output.outputs)
        if new_tokens:
            now = time.monotonic()
            if state.first_token_mono == 0.0:
                state.first_token_mono = now
                ttft_ms = (now - state.sched_mono) * 1000.0
                self._m_ttft.observe(ttft_ms)
                self._tracer.stage(
                    request.service_request_id, "first_token",
                    ttft_ms=round(ttft_ms, 3),
                )
                # Anomaly trigger: TTFT past the configured SLO dumps the
                # flight ring (hatch XLLM_TRACE_SLO_TTFT_MS; 0 = off).
                # Once per request, never per token.
                slo = float(
                    os.environ.get("XLLM_TRACE_SLO_TTFT_MS", "")
                    or getattr(self._config, "trace_slo_ttft_ms", 0.0)
                    or 0.0
                )
                if slo and ttft_ms > slo:
                    self.flight.trigger(
                        "slo_ttft", request.service_request_id,
                        ttft_ms=round(ttft_ms, 3), slo_ms=slo,
                    )
            else:
                # Per-TOKEN time: a delivery may carry several tokens
                # (speculative decode, RPC-batched chunks) — observing the
                # raw gap would read k x the client-perceived TPOT.
                self._m_tpot.observe(
                    (now - state.last_token_mono) * 1000.0 / new_tokens
                )
                if self._tracer.enabled:
                    self._tracer.stage(
                        request.service_request_id, "decode",
                        n_tokens=new_tokens,
                    )
            state.last_token_mono = now
            if state.resume_mono:
                self._m_resume_latency.observe(
                    (now - state.resume_mono) * 1000.0
                )
                state.resume_mono = 0.0
            request.num_generated_tokens += new_tokens
            if not state.prefill_finished:
                state.prefill_finished = True
                self._instance_mgr.update_request_metrics(
                    request.routing,
                    RequestAction.FINISH_PREFILL,
                    # Must mirror the SCHEDULE charge exactly: a resumed
                    # attempt was charged for prompt + replayed tokens.
                    len(request.resume_token_ids or request.token_ids),
                )
            self._instance_mgr.update_request_metrics(
                request.routing, RequestAction.GENERATE, new_tokens
            )

        if request.stream:
            if not output.status.ok() and not output.status.code == StatusCode.CANCELLED:
                # Engine-side failure mid-stream (or at admission): surface
                # it instead of closing as a clean empty stream.
                state.stream.finish_with_error(
                    output.status.code, output.status.message
                )
                self.finish_request(request.service_request_id, cancelled=True)
                return
            if request.resumable:
                # Streams keep the same delivered-token accumulator the
                # non-stream path fills: it is the replay source a
                # mid-stream resume rebuilds the request from.
                self._accumulate(state, output)
            ok = self._response_handler.send_delta_to_client(
                state.stream, request, output, state.first_chunk_sent
            )
            state.first_chunk_sent = True
            if not ok and not output.finished:
                self._cancel(state)
                return
        else:
            self._accumulate(state, output)
            if output.finished or not output.status.ok():
                final = RequestOutput(
                    request_id=output.request_id,
                    service_request_id=output.service_request_id,
                    status=output.status,
                    outputs=sorted(state.acc.values(), key=lambda s: s.index),
                    usage=state.usage,
                    finished=True,
                )
                self._response_handler.send_result_to_client(
                    state.stream, request, final
                )
        if output.finished or not output.status.ok():
            self.finish_request(
                request.service_request_id,
                cancelled=not output.status.ok()
                and output.status.code == StatusCode.CANCELLED,
            )

    def _apply_stop_strings(
        self, state: _RequestState, output: RequestOutput
    ) -> None:
        """OpenAI `stop` sequences, enforced on the service tier where the
        detokenized text stream lives (stops can span token boundaries —
        each sequence's matcher holds back partial matches). When every
        sequence has stopped, the output is force-finished and the engine
        side is cancelled (it would otherwise keep generating discarded
        tokens)."""
        request = state.request
        for seq in output.outputs:
            mon = state.stop_monitors.get(seq.index)
            if mon is None:
                mon = state.stop_monitors[seq.index] = StopStringMonitor(
                    request.stop
                )
            if mon.stopped:
                # Post-stop tail from the engine: drop entirely, and keep
                # asserting the STOP reason — the engine's later natural
                # finish (length/eos) must not overwrite it in accumulation
                # or emit a contradictory finish_reason delta (n>1: the
                # engine keeps generating this child until all stop).
                state.stop_dropped += len(seq.token_ids)
                seq.text = ""
                seq.token_ids = []
                seq.logprobs = []
                seq.finish_reason = FinishReason.STOP
                continue
            pushed = seq.text or ""
            emit, hit = mon.push(pushed)
            if hit:
                seq.finish_reason = FinishReason.STOP
                # Align token-level fields with the truncated text: exact
                # per-token boundaries aren't visible at this tier (the
                # instance detokenized), so keep a character-proportional
                # share of this chunk's tokens — post-stop tokens must not
                # leak into logprobs/usage/GENERATE metrics.
                if pushed and seq.token_ids:
                    import math as _math

                    keep = min(
                        len(seq.token_ids),
                        _math.ceil(
                            len(emit) / len(pushed) * len(seq.token_ids)
                        ),
                    )
                    state.stop_dropped += len(seq.token_ids) - keep
                    seq.token_ids = seq.token_ids[:keep]
                    seq.logprobs = seq.logprobs[:keep]
            elif output.finished or seq.finish_reason != FinishReason.NONE:
                # THIS sequence ended naturally (n>1: a child can finish
                # before the request-level finished flag) — release any
                # held-back stop-prefix text.
                emit += mon.flush()
            seq.text = emit
        n = max(request.n, 1)
        if (
            not output.finished
            and len(state.stop_monitors) >= n
            and all(m.stopped for m in state.stop_monitors.values())
        ):
            output.finished = True
            # Stop the engine's generation; the finish below is CLEAN
            # (finish_reason stop), not a client cancel.
            if state.cancel_callback is not None:
                try:
                    state.cancel_callback()
                except Exception:
                    pass

    def _accumulate(self, state: _RequestState, output: RequestOutput) -> None:
        accumulate_sequences(state.acc, output)
        if output.usage is not None:
            state.usage = output.usage

    def _cancel(self, state: _RequestState) -> None:
        """Client went away mid-stream: unwind metrics + tell the engine
        (reference cancels via the OutputCallback returning false)."""
        if state.cancel_callback is not None:
            try:
                state.cancel_callback()
            except Exception:
                pass
        self.finish_request(state.request.service_request_id, cancelled=True)

    def finish_request(self, service_request_id: str, cancelled: bool = False) -> None:
        """Terminal bookkeeping (reference: scheduler.cpp:268-291).

        A request cancelled BEFORE its first token unwinds the queued
        prefill work (CANCEL); once FINISH_PREFILL has fired, the prefill
        counters were already decremented and only the decode slot is open,
        so any termination — clean or cancelled — closes it with
        FINISH_DECODE (a CANCEL here would double-decrement prefill and
        corrupt other requests' counts)."""
        with self._mu:
            state = self._requests.pop(service_request_id, None)
        if state is None or state.done:
            return
        state.done = True
        request = state.request
        # Return the admission slot the moment the stream is terminal —
        # a parked fair-queue waiter gets it before this method even
        # finishes its metric bookkeeping. Idempotent (release no-ops on
        # an already-released request).
        self.admission.release(request)
        action = (
            RequestAction.CANCEL
            if cancelled and not state.prefill_finished
            else RequestAction.FINISH_DECODE
        )
        self._instance_mgr.update_request_metrics(
            request.routing, action,
            # Mirror the live attempt's SCHEDULE charge (a resumed
            # attempt was charged for prompt + replayed tokens).
            len(request.resume_token_ids or request.token_ids),
        )
        now = time.monotonic()
        if state.sched_mono:
            self._m_e2e.observe((now - state.sched_mono) * 1000.0)
        outcome = (
            "error" if state.failed
            else "cancelled" if cancelled
            else "ok"
        )
        self._m_finished.labels(outcome=outcome).inc()
        if outcome == "ok":
            # Clean completions feed the goodput controller's per-tenant
            # decode-length EWMA (cancelled/errored lengths would bias
            # the predictor low).
            self.goodput.observe_completion(
                request.model, request.num_generated_tokens
            )
        terminal = {"ok": "finish", "error": "error"}.get(outcome, "cancel")
        self._tracer.stage(
            service_request_id, terminal,
            outcome=outcome,
            generated_tokens=request.num_generated_tokens,
        )

    def fail_request(self, service_request_id: str, code: StatusCode, msg: str) -> None:
        """Error-finish from the API tier (e.g. prefill POST failed —
        reference: handle_first_response cntl->Failed, service.cpp:101-106)."""
        with self._mu:
            state = self._requests.get(service_request_id)
        if state is None:
            return
        self._tracer.stage(
            service_request_id, "error", code=int(code), message=msg
        )
        state.failed = True  # finish_request reports outcome="error"
        self._streams.submit(
            state.lane,
            lambda: (
                state.stream.finish_with_error(code, msg),
                self.finish_request(service_request_id, cancelled=True),
            ),
        )

    # ------------------------------------------------------------------ #
    # fault handling: interrupted-request re-dispatch
    # ------------------------------------------------------------------ #

    def _on_instance_health(self, name: str, state: str) -> None:
        """Breaker transition: ejection retracts the instance's KV-index
        locations so routing/fetch planning stop scoring phantom hits.
        Heartbeats carry DELTAS, so the prune also flags the instance for
        a full cache resync — the next heartbeat response asks it to fold
        its committed-block snapshot into a stored delta, rebuilding the
        index once the instance is reachable again."""
        if state == HealthState.EJECTED:
            # Anomaly trigger: a breaker ejection is exactly the moment
            # the recent-span window explains what went wrong.
            self.flight.trigger("breaker_ejection", instance=name)
            self._kvcache_mgr.remove_instance(name)
            # Encoder fabric parity: an ejected encoder's embedding-index
            # locations are phantom hits for hit-aware routing too; the
            # same armed resync rebuilds them from its LRU snapshot.
            self.encoder_fabric.remove_instance(name)
            with self._mu:
                self._cache_resync_needed.add(name)

    def take_cache_resync(self, name: str) -> bool:
        """Pop the pending cache-resync flag for one instance (called by
        the master's heartbeat handler; the flag rides the response).
        The flag stays armed WHILE the instance remains ejected — a
        partitioned instance whose beats still arrive must not re-index
        blocks nobody can fetch (evict_decisions would count them as live
        replicas and let the real last copy die). Best-effort thereafter:
        a lost response re-flags only on the next ejection, which is also
        the only path that loses index state."""
        with self._mu:
            if name not in self._cache_resync_needed:
                return False
        if self._instance_mgr.health_state(name) == HealthState.EJECTED:
            return False  # keep armed until the breaker re-admits it
        with self._mu:
            self._cache_resync_needed.discard(name)
        return True

    def _on_instance_removed(self, name: str) -> None:
        """An instance left the registry (lease expiry / prune). Requests
        routed to it that have produced NO tokens yet are re-routed and
        re-forwarded transparently; requests already mid-stream resume by
        token replay (prompt + every delivered token re-dispatched to a
        survivor); only when neither works does the request error-finish."""
        with self._mu:
            affected = [
                s
                for s in self._requests.values()
                if not s.done
                and name
                in (s.request.routing.prefill_name, s.request.routing.decode_name)
            ]
        for state in affected:
            srid = state.request.service_request_id
            if not (
                self.redispatch_request(srid, exclude=name)
                or self.resume_request(srid, exclude=name)
            ):
                self.fail_request(
                    srid,
                    StatusCode.UNAVAILABLE,
                    f"instance {name} died mid-generation",
                )

    def _route_excluding(self, token_ids: List[int], exclude: str):
        """Policy pair choice that never lands on `exclude` (the registry
        may still list the failed instance — fast-fail beats lease
        expiry). Returns None when no viable pair exists."""
        routing = self._policy.select_instances_pair(token_ids)
        if exclude and routing.prefill_name == exclude:
            candidates = [
                n
                for n in (
                    self._instance_mgr.routable_prefill_instances()
                    + self._instance_mgr.routable_decode_instances()
                )
                if n != exclude
            ]
            if not candidates:
                return None
            routing.prefill_name = self._instance_mgr.least_loaded(candidates)
        if exclude and routing.decode_name == exclude:
            routing.decode_name = routing.prefill_name
        if not routing.prefill_name and not routing.decode_name:
            return None
        return routing

    def _bump_attempt(self, state: _RequestState) -> None:
        """Advance the dispatch-attempt epoch: outputs pushed under the
        previous wire id are rejected from here on (handle_generation and
        the queued-delivery check in _deliver)."""
        with self._mu:
            state.attempt += 1
            state.request.wire_srid = (
                f"{state.request.service_request_id}#r{state.attempt}"
            )

    def _drain_lane(self, state: _RequestState) -> None:
        """Barrier on the request's lane: any delivery admitted BEFORE the
        attempt bump finishes writing (client + acc) before we snapshot
        the delivered tokens. Never called from a lane thread."""
        fence = threading.Event()
        self._streams.submit(state.lane, fence.set)
        fence.wait(timeout=5.0)

    def redispatch_request(
        self, service_request_id: str, exclude: str = ""
    ) -> bool:
        """Re-route + re-forward a request whose instance failed. Only safe
        before any token reached the client (mid-stream requests go through
        resume_request's token replay); bounded by max_redispatch.
        Returns False when the request cannot be replayed (caller decides
        how to fail it)."""
        me = threading.get_ident()
        with self._mu:
            state = self._requests.get(service_request_id)
            if state is None or state.done:
                return False
            request = state.request
            if (
                request.num_generated_tokens > 0
                or state.dispatch is None
                or state.redispatch_count >= self.max_redispatch
                # another thread is already replaying this request (the
                # dispatch-failure handler racing the removal listener):
                # a second concurrent replay would double-dispatch it
                or state.replaying not in (0, me)
            ):
                return False
            outermost = state.replaying == 0
            state.replaying = me
            state.redispatch_count += 1
            self.total_redispatch_attempts += 1
        try:
            return self._redispatch_locked_out(
                service_request_id, state, request, exclude
            )
        finally:
            if outermost:
                state.replaying = 0

    def _redispatch_locked_out(
        self, service_request_id, state, request, exclude
    ) -> bool:
        routing = self._route_excluding(request.token_ids, exclude)
        if routing is None:
            return False
        # Unwind the failed attempt's queued-prefill bookkeeping (a no-op
        # when the instance already left the registry) before charging the
        # new target.
        self._instance_mgr.update_request_metrics(
            request.routing, RequestAction.CANCEL, len(request.token_ids)
        )
        request.routing = routing
        self._bump_attempt(state)
        self._instance_mgr.update_request_metrics(
            routing, RequestAction.SCHEDULE, len(request.token_ids)
        )
        logger.info(
            "re-dispatching %s (excluding %s) -> %s",
            service_request_id, exclude or "-", routing.to_json(),
        )
        try:
            state.dispatch()
        except Exception:
            # The SCHEDULE above must not leak when the forward itself
            # failed — load accounting would drift on every failed replay
            # (mirror of the "prefill instance vanished" unwind in
            # api/master.py). Clearing the routing keeps the later
            # finish_request/fail_request from unwinding a second time.
            self._instance_mgr.update_request_metrics(
                routing, RequestAction.CANCEL, len(request.token_ids)
            )
            request.routing = Routing()
            return False
        # Count only SUCCESSFUL replays (the /metrics counter claims
        # "transparently replayed", not "attempted"); under self._mu —
        # the removal watch and the prune loop race here.
        with self._mu:
            self.total_redispatches += 1
        self._tracer.stage(
            service_request_id, "redispatch",
            excluded=exclude, prefill=routing.prefill_name,
        )
        return True

    def resume_request(
        self, service_request_id: str, exclude: str = ""
    ) -> bool:
        """Mid-stream token-replay resume (docs/FAULT_TOLERANCE.md): the
        request's instance died AFTER tokens reached the client. The
        forwarded request is rebuilt as prompt + every delivered token
        (state.acc), re-dispatched to a survivor with a `resume_from`
        marker, and the continuation splices onto the client stream with
        no duplicated or missing tokens (the attempt-versioned wire id
        fences off the dead attempt's late pushes). Eligibility:
        n=1/best_of=1, non-guided, no media (request.resumable); bounded
        by max_redispatch together with pre-token redispatches."""
        me = threading.get_ident()
        with self._mu:
            state = self._requests.get(service_request_id)
            if state is None or state.done:
                return False
            request = state.request
            if (
                request.num_generated_tokens <= 0
                or not request.resumable
                or state.dispatch is None
                or state.redispatch_count >= self.max_redispatch
                # see redispatch_request: one replay at a time
                or state.replaying not in (0, me)
            ):
                return False
            outermost = state.replaying == 0
            state.replaying = me
            state.redispatch_count += 1
            self.total_redispatch_attempts += 1
        try:
            return self._resume_locked_out(
                service_request_id, state, request, exclude
            )
        finally:
            if outermost:
                state.replaying = 0

    def _resume_locked_out(
        self, service_request_id, state, request, exclude
    ) -> bool:
        # Fence the dead attempt FIRST, then drain the lane: deliveries
        # already queued finish writing into acc, later ones are rejected
        # — the snapshot below is exactly what the client has.
        self._bump_attempt(state)
        self._drain_lane(state)
        with self._mu:
            seq = state.acc.get(0)
            emitted = list(seq.token_ids) if seq is not None else []
        resume_ids = list(request.token_ids) + emitted
        routing = self._route_excluding(resume_ids, exclude)
        if routing is None:
            return False
        # Resumed requests serve colocated on the instance (no second PD
        # handoff on a recovery path) — keep the load accounting aligned
        # with where the work actually runs.
        routing.decode_name = routing.prefill_name
        # Close out the dead attempt's load accounting: its decode slot
        # (or queued prefill, if the kill beat the first FINISH_PREFILL
        # bookkeeping) — no-ops when the instance already left the
        # registry. The unwind mirrors that attempt's own SCHEDULE charge
        # (a second resume's predecessor was charged prompt + replay).
        self._instance_mgr.update_request_metrics(
            request.routing,
            RequestAction.FINISH_DECODE
            if state.prefill_finished
            else RequestAction.CANCEL,
            len(request.resume_token_ids or request.token_ids),
        )
        state.prefill_finished = False
        request.routing = routing
        request.resume_token_ids = resume_ids
        request.resume_base = len(emitted)
        # Stop bookkeeping restarts per attempt: drops already applied to
        # acc are excluded from the replay, so carrying the old counter
        # would double-subtract from the resumed attempt's usage.
        state.stop_dropped = 0
        state.resume_mono = time.monotonic()
        self._instance_mgr.update_request_metrics(
            routing, RequestAction.SCHEDULE, len(resume_ids)
        )
        logger.info(
            "resuming %s mid-stream at token %d (excluding %s) -> %s",
            service_request_id, len(emitted), exclude or "-",
            routing.to_json(),
        )
        try:
            state.dispatch()
        except Exception:
            # Same unwind rule as redispatch: a failed forward must not
            # leave the SCHEDULE charge on the new target, and the cleared
            # routing keeps the terminal bookkeeping from re-unwinding it.
            self._instance_mgr.update_request_metrics(
                routing, RequestAction.CANCEL, len(resume_ids)
            )
            request.routing = Routing()
            return False
        with self._mu:
            self.total_resumes += 1
        self._tracer.stage(
            service_request_id, "resume",
            excluded=exclude, prefill=routing.prefill_name,
            replayed_tokens=len(emitted),
        )
        return True

    # ------------------------------------------------------------------ #
    # instance-facing plane
    # ------------------------------------------------------------------ #

    def handle_instance_heartbeat(
        self,
        name: str,
        load_metrics: Optional[LoadMetrics] = None,
        latency_metrics: Optional[LatencyMetrics] = None,
        cache_event: Optional[KvCacheEvent] = None,
    ) -> None:
        """(reference: scheduler.cpp:123-130)"""
        if cache_event is not None and not cache_event.empty():
            # Breaker gate: an EJECTED instance's beats may still arrive
            # (asymmetric partition), but its cache deltas must not
            # re-index blocks nobody can fetch — evict_decisions would
            # count them as live replicas and let the real last copy die.
            # Its locations were pruned at ejection; the armed cache
            # resync rebuilds them (all tiers) once the breaker re-admits
            # it, so dropping deltas here loses nothing.
            if (
                self._instance_mgr.health_state(name)
                != HealthState.EJECTED
            ):
                meta = self._instance_mgr.get_instance(name)
                if meta is not None and meta.current_type.name == "ENCODE":
                    # ENCODE-role deltas are embedding-LRU transitions
                    # keyed by media content hashes, not KV block hashes:
                    # they feed the fleet embedding index, never the KV
                    # index (a media hash colliding into prefix scoring
                    # would score phantom KV hits).
                    self.encoder_fabric.record_event(name, cache_event)
                else:
                    self._kvcache_mgr.record_updated_kvcaches(
                        name, cache_event
                    )
        if load_metrics is not None:
            self._instance_mgr.record_load_metrics_update(name, load_metrics)
        if latency_metrics is not None:
            self._instance_mgr.update_latency_metrics(name, latency_metrics)
