"""Audio front door: WAV decode + Whisper-parity log-mel features.

The reference's message model carries `audio_url` parts verbatim
(jinja_chat_template.h:30-47); this turns them into the fixed-geometry
[num_mel_bins, mel_frames] float32 features the Qwen2-Audio tower
(models/audio.py) compiles for.

The mel pipeline replicates HF's WhisperFeatureExtractor numpy path
exactly (parity-tested): periodic Hann window 400, hop 160, centered
STFT with reflect padding, power spectrum, slaney-scale/slaney-norm mel
filterbank over 0..8 kHz at 16 kHz, log10 clamped at 1e-10, dynamic
floor at (max - 8), then (x + 4) / 4. Everything is stdlib + numpy —
`wave` for PCM decode, `np.fft.rfft` for the STFT.
"""

from __future__ import annotations

import base64
import io
import re
import wave
from typing import Optional, Tuple

import numpy as np

SAMPLE_RATE = 16000
N_FFT = 400
HOP = 160

_AUDIO_DATA_RE = re.compile(
    r"data:audio/(wav|x-wav|wave);base64,(.*)", re.S
)


def is_audio_data_url(url: str) -> bool:
    return bool(_AUDIO_DATA_RE.match(url or ""))


def decode_audio_url(url: str) -> Optional[np.ndarray]:
    """`data:audio/wav;base64` -> mono float32 waveform at 16 kHz, or
    None when the URL is not an audio data URL."""
    m = _AUDIO_DATA_RE.match(url or "")
    if not m:
        return None
    try:
        raw = base64.b64decode(m.group(2))
    except Exception as e:
        raise ValueError(f"bad base64 audio payload: {e}") from e
    wav, rate = decode_wav_bytes(raw)
    return resample_linear(wav, rate, SAMPLE_RATE)


def decode_wav_bytes(raw: bytes) -> Tuple[np.ndarray, int]:
    """PCM WAV bytes -> (mono float32 in [-1, 1], sample_rate)."""
    try:
        with wave.open(io.BytesIO(raw)) as w:
            rate = w.getframerate()
            n_ch = w.getnchannels()
            width = w.getsampwidth()
            data = w.readframes(w.getnframes())
    except Exception as e:
        raise ValueError(f"undecodable WAV payload: {e}") from e
    if width == 2:
        x = np.frombuffer(data, np.int16).astype(np.float32) / 32768.0
    elif width == 4:
        x = np.frombuffer(data, np.int32).astype(np.float32) / 2147483648.0
    elif width == 1:  # unsigned 8-bit PCM
        x = (np.frombuffer(data, np.uint8).astype(np.float32) - 128.0) / 128.0
    else:
        raise ValueError(f"unsupported WAV sample width {width}")
    if n_ch > 1:
        x = x.reshape(-1, n_ch).mean(axis=1)
    return x, rate


def resample_linear(x: np.ndarray, src: int, dst: int) -> np.ndarray:
    """Linear-interpolation resample (front-door tolerance for non-16k
    uploads; 16 kHz input passes through untouched)."""
    if src == dst:
        return np.asarray(x, np.float32)
    n_out = int(round(len(x) * dst / src))
    pos = np.linspace(0.0, len(x) - 1.0, n_out)
    return np.interp(pos, np.arange(len(x)), x).astype(np.float32)


def _hz_to_mel_slaney(hz):
    hz = np.asarray(hz, np.float64)
    mel = 3.0 * hz / 200.0
    log_region = hz >= 1000.0
    logstep = np.log(6.4) / 27.0
    mel = np.where(
        log_region, 15.0 + np.log(np.maximum(hz, 1e-10) / 1000.0) / logstep,
        mel,
    )
    return mel


def _mel_to_hz_slaney(mel):
    mel = np.asarray(mel, np.float64)
    hz = 200.0 * mel / 3.0
    logstep = np.log(6.4) / 27.0
    return np.where(
        mel >= 15.0, 1000.0 * np.exp(logstep * (mel - 15.0)), hz
    )


def mel_filter_bank(
    num_mel: int, n_fft: int = N_FFT, rate: int = SAMPLE_RATE,
    fmin: float = 0.0, fmax: float = 8000.0,
) -> np.ndarray:
    """[n_fft//2 + 1, num_mel] slaney-scale, slaney-normalized
    triangular filters (HF audio_utils.mel_filter_bank semantics)."""
    n_bins = n_fft // 2 + 1
    fft_freqs = np.linspace(0.0, rate / 2.0, n_bins)
    mel_pts = np.linspace(
        _hz_to_mel_slaney(fmin), _hz_to_mel_slaney(fmax), num_mel + 2
    )
    hz_pts = _mel_to_hz_slaney(mel_pts)
    fdiff = np.diff(hz_pts)
    slopes = hz_pts[None, :] - fft_freqs[:, None]  # [bins, mel+2]
    down = -slopes[:, :-2] / fdiff[:-1]
    up = slopes[:, 2:] / fdiff[1:]
    fb = np.maximum(0.0, np.minimum(down, up))
    # slaney norm: constant energy per filter
    fb *= (2.0 / (hz_pts[2:] - hz_pts[:-2]))[None, :]
    return fb.astype(np.float64)


def log_mel(
    waveform: np.ndarray, num_mel_bins: int, mel_frames: int
) -> np.ndarray:
    """Mono 16 kHz float32 -> [num_mel_bins, mel_frames] float32 —
    HF WhisperFeatureExtractor numpy semantics: the waveform pads with
    zeros (or truncates) to mel_frames * hop samples, centered STFT with
    reflect padding, and the final frame is dropped (the extractor's
    `log_spec[:, :-1]`)."""
    n_samples = mel_frames * HOP
    x = np.zeros(n_samples, np.float64)
    x[: min(len(waveform), n_samples)] = waveform[:n_samples]
    pad = N_FFT // 2
    x = np.pad(x, (pad, pad), mode="reflect")
    # periodic Hann (HF window_function(400, "hann"))
    window = 0.5 * (
        1.0 - np.cos(2.0 * np.pi * np.arange(N_FFT) / N_FFT)
    )
    n_frames = 1 + (len(x) - N_FFT) // HOP
    idx = (
        np.arange(N_FFT)[None, :]
        + HOP * np.arange(n_frames)[:, None]
    )
    frames = x[idx] * window[None, :]
    power = np.abs(np.fft.rfft(frames, N_FFT, axis=1)) ** 2  # [F, bins]
    mel = power @ mel_filter_bank(num_mel_bins)  # [F, M]
    log_spec = np.log10(np.maximum(mel, 1e-10)).T  # [M, F]
    log_spec = log_spec[:, :-1][:, :mel_frames]
    log_spec = np.maximum(log_spec, log_spec.max() - 8.0)
    return ((log_spec + 4.0) / 4.0).astype(np.float32)
