"""Real-image front door: decode + HF-processor-parity preprocessing.

The reference's message model carries `image_url` parts verbatim to the
engine (jinja_chat_template.h:30-47); any OpenAI-compatible server must
therefore accept `data:image/png;base64,...` payloads, not just
pre-encoded tensors. This module turns those payloads into the
fixed-geometry float32 tensors the EPD encode stage already transports
(service/scheduler._expand_media -> api/instance_mm._handle_encode ->
models/vision towers):

  * decode: PNG/JPEG/WebP/GIF via PIL -> uint8 RGB;
  * SigLIP family: bicubic resize to (S, S), rescale 1/255, normalize
    with mean/std 0.5 — exactly HF SiglipImageProcessor;
  * Qwen2-VL family: `smart_resize` to patch*merge multiples bounded by
    min/max pixels (the exact HF function), bicubic resize, rescale,
    normalize with the OPENAI CLIP mean/std — exactly HF
    Qwen2VLImageProcessor (shared by Qwen2.5-VL);
  * hf_qwen2vl_patches replicates the HF processor's patch flattening
    (temporal tiling + (h//m, m, w//m, m) interleave) so tests can
    assert OUR tensor equals HF `pixel_values` bit-for-bit; the serving
    tower does its own patchify from the [H, W, 3] image.

Resizes go through PIL on uint8 data — the same path transformers takes
(image_transforms.resize converts to PIL) — so parity is exact, not
approximate. Everything here is host-side numpy; nothing is jitted.
"""

from __future__ import annotations

import base64
import io
import math
import re
from typing import Optional, Tuple

import numpy as np

# HF constants (transformers.image_utils).
OPENAI_CLIP_MEAN = (0.48145466, 0.4578275, 0.40821073)
OPENAI_CLIP_STD = (0.26862954, 0.26130258, 0.27577711)
IMAGENET_STANDARD_MEAN = (0.5, 0.5, 0.5)  # SigLIP
IMAGENET_STANDARD_STD = (0.5, 0.5, 0.5)

_IMAGE_DATA_RE = re.compile(
    r"data:image/(png|jpeg|jpg|webp|gif|bmp);base64,(.*)", re.S
)


def media_content_hash(kind: str, shape, data_b64: str) -> str:
    """Content key for one media item as the encode stage will see it:
    16-byte blake2b over (kind, shape, payload), hex-encoded. Hashed at
    the front door AFTER preprocessing, so two byte-different uploads of
    the same pixels (PNG vs JPEG re-encode) key differently while a
    re-sent identical payload in a multi-turn chat keys identically —
    the property the encoder-fabric embedding cache needs (docs/EPD.md).
    The digest width matches the KV block hashes so the same
    KvCacheEvent/heartbeat plumbing can carry embedding-index deltas."""
    import hashlib

    h = hashlib.blake2b(digest_size=16)
    h.update(kind.encode())
    h.update(("x".join(str(int(s)) for s in shape)).encode())
    h.update(data_b64.encode())
    return h.hexdigest()


def decode_image_url(url: str) -> Optional[np.ndarray]:
    """`data:image/...;base64` URL -> uint8 RGB [H, W, 3], or None if the
    URL is not an image data URL (the raw-f32 tensor backdoor and error
    reporting stay with the caller). Raises ValueError on a payload that
    claims to be an image but does not decode."""
    m = _IMAGE_DATA_RE.match(url or "")
    if not m:
        return None
    try:
        raw = base64.b64decode(m.group(2))
    except Exception as e:
        raise ValueError(f"bad base64 image payload: {e}") from e
    return decode_image_bytes(raw)


def decode_image_bytes(raw: bytes) -> np.ndarray:
    """Compressed image bytes -> uint8 RGB [H, W, 3] via PIL."""
    try:
        from PIL import Image
    except Exception as e:  # pragma: no cover - PIL is in the image
        raise RuntimeError("PIL is required for image decoding") from e
    try:
        with Image.open(io.BytesIO(raw)) as im:
            return np.asarray(im.convert("RGB"))
    except Exception as e:
        raise ValueError(f"undecodable image payload: {e}") from e


_VIDEO_DATA_RE = re.compile(
    r"data:video/(mp4|webm|avi|x-msvideo|quicktime|mpeg);base64,(.*)",
    re.S,
)


def is_video_data_url(url: str) -> bool:
    """Cheap predicate so callers can gate config BEFORE paying for a
    decode."""
    return bool(_VIDEO_DATA_RE.match(url or ""))


def decode_video_url(
    url: str, max_frames: int = 16, temporal_patch: int = 2
) -> Optional[np.ndarray]:
    """`data:video/...;base64` URL -> uint8 RGB frames [T, H, W, 3]
    (T a positive multiple of `temporal_patch`), or None when the URL is
    not a video data URL. Frames are sampled UNIFORMLY across the clip
    down to `max_frames` (the standard serving policy — vLLM and the HF
    video processors sample rather than encode every frame), then
    truncated to a temporal_patch multiple (padding by repeating the
    last frame when the clip is shorter than one patch). Decoding uses
    OpenCV via a temp file (cv2.VideoCapture has no in-memory API)."""
    m = _VIDEO_DATA_RE.match(url or "")
    if not m:
        return None
    try:
        raw = base64.b64decode(m.group(2))
    except Exception as e:
        raise ValueError(f"bad base64 video payload: {e}") from e
    return decode_video_bytes(
        raw, suffix="." + {"x-msvideo": "avi", "quicktime": "mov"}.get(
            m.group(1), m.group(1)
        ),
        max_frames=max_frames, temporal_patch=temporal_patch,
    )


def decode_video_bytes(
    raw: bytes, suffix: str = ".mp4", max_frames: int = 16,
    temporal_patch: int = 2,
) -> np.ndarray:
    import os
    import tempfile

    try:
        import cv2
    except Exception as e:  # pragma: no cover - cv2 is in the image
        raise RuntimeError("OpenCV is required for video decoding") from e
    fd, path = tempfile.mkstemp(suffix=suffix)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(raw)
        cap = cv2.VideoCapture(path)
        # Memory is bounded to O(max_frames) decoded frames, NEVER the
        # clip length — a few-MB H.264 payload can expand ~1000x
        # uncompressed, and buffering a whole clip on the admission path
        # is a one-request OOM (review finding, r5). When the container
        # reports its frame count, grab()-skip straight to the sampled
        # indices; otherwise keep a stride-doubling reservoir of at most
        # 2*max_frames frames (near-uniform coverage of unknown length).
        total = int(cap.get(cv2.CAP_PROP_FRAME_COUNT) or 0)

        def read_rgb():
            ok, frame = cap.read()
            if not ok:
                return None
            return np.asarray(cv2.cvtColor(frame, cv2.COLOR_BGR2RGB))

        frames = []
        if total > 0:
            want = sorted({
                int(i)
                for i in np.linspace(
                    0, total - 1, min(max_frames, total)
                ).round()
            })
            pos = 0
            for target in want:
                while pos < target:
                    if not cap.grab():
                        break
                    pos += 1
                fr = read_rgb()
                if fr is None:
                    break
                pos += 1
                frames.append(fr)
        else:
            stride, pos = 1, 0
            while True:
                if pos % stride == 0:
                    fr = read_rgb()
                    if fr is None:
                        break
                    frames.append(fr)
                    if len(frames) >= 2 * max_frames:
                        frames = frames[::2]
                        stride *= 2
                else:
                    if not cap.grab():
                        break
                pos += 1
        cap.release()
    finally:
        os.unlink(path)
    if not frames:
        raise ValueError("undecodable video payload (no frames)")
    if len(frames) > max_frames:
        idx = np.linspace(0, len(frames) - 1, max_frames).round()
        frames = [frames[int(i)] for i in sorted({int(i) for i in idx})]
    while len(frames) % temporal_patch:
        frames.append(frames[-1])  # repeat-last pad (HF convention)
    return np.stack(frames)


def _resize_bicubic(img: np.ndarray, height: int, width: int) -> np.ndarray:
    """uint8 [H, W, 3] -> uint8 [height, width, 3], PIL bicubic — the
    exact resample path transformers uses for both families."""
    from PIL import Image

    if img.shape[0] == height and img.shape[1] == width:
        return img
    pil = Image.fromarray(img).resize(
        (width, height), resample=Image.Resampling.BICUBIC
    )
    return np.asarray(pil)


def _normalize(img_u8: np.ndarray, mean, std) -> np.ndarray:
    x = img_u8.astype(np.float32) * (1.0 / 255.0)
    return (
        (x - np.asarray(mean, np.float32)) / np.asarray(std, np.float32)
    ).astype(np.float32)


def preprocess_siglip(img: np.ndarray, image_size: int) -> np.ndarray:
    """uint8 RGB -> normalized float32 [S, S, 3] (HF SiglipImageProcessor:
    bicubic resize, rescale 1/255, mean/std 0.5)."""
    return _normalize(
        _resize_bicubic(img, image_size, image_size),
        IMAGENET_STANDARD_MEAN, IMAGENET_STANDARD_STD,
    )


def smart_resize(
    height: int, width: int, factor: int = 28,
    min_pixels: int = 56 * 56, max_pixels: int = 14 * 14 * 4 * 1280,
) -> Tuple[int, int]:
    """HF Qwen2-VL smart_resize, verbatim semantics
    (transformers qwen2_vl/image_processing_qwen2_vl.py): round both
    sides to `factor` multiples, keeping the pixel count within
    [min_pixels, max_pixels] and the aspect ratio (within 200:1)."""
    if max(height, width) / min(height, width) > 200:
        raise ValueError(
            "absolute aspect ratio must be smaller than 200, got "
            f"{max(height, width) / min(height, width)}"
        )
    h_bar = round(height / factor) * factor
    w_bar = round(width / factor) * factor
    if h_bar * w_bar > max_pixels:
        beta = math.sqrt((height * width) / max_pixels)
        h_bar = math.floor(height / beta / factor) * factor
        w_bar = math.floor(width / beta / factor) * factor
    elif h_bar * w_bar < min_pixels:
        beta = math.sqrt(min_pixels / (height * width))
        h_bar = math.ceil(height * beta / factor) * factor
        w_bar = math.ceil(width * beta / factor) * factor
    return max(h_bar, factor), max(w_bar, factor)


def preprocess_qwen2vl(
    img: np.ndarray,
    patch_size: int = 14,
    merge_size: int = 2,
    min_pixels: int = 56 * 56,
    max_pixels: int = 14 * 14 * 4 * 1280,
    pinned_size: int = 0,
) -> np.ndarray:
    """uint8 RGB -> normalized float32 [H', W', 3] with H', W' multiples
    of patch_size*merge_size (HF Qwen2VLImageProcessor: smart_resize,
    bicubic, rescale 1/255, CLIP mean/std). `pinned_size` overrides
    smart_resize with a fixed square — the serving towers compile for
    one static grid (models/vision.VisionConfig.image_size), so the
    service pins the geometry while keeping the exact HF pixel math."""
    if pinned_size:
        h_bar = w_bar = pinned_size
    else:
        h_bar, w_bar = smart_resize(
            img.shape[0], img.shape[1],
            factor=patch_size * merge_size,
            min_pixels=min_pixels, max_pixels=max_pixels,
        )
    return _normalize(
        _resize_bicubic(img, h_bar, w_bar),
        OPENAI_CLIP_MEAN, OPENAI_CLIP_STD,
    )


def hf_qwen2vl_patches(
    norm_img: np.ndarray,
    patch_size: int = 14,
    merge_size: int = 2,
    temporal_patch_size: int = 2,
) -> Tuple[np.ndarray, Tuple[int, int, int]]:
    """Replicate the HF processor's flattened patch layout from a
    normalized [H, W, 3] image: tile temporally (a single image repeats
    t_patch times), then emit rows in (h//m, w//m, m, m) merge order —
    [grid_t*grid_h*grid_w, 3*tps*p*p] `pixel_values` plus grid_thw.
    Used by parity tests to compare against transformers bit-for-bit
    (the serving tower patchifies on device instead)."""
    H, W, C = norm_img.shape
    p, m, tps = patch_size, merge_size, temporal_patch_size
    gh, gw = H // p, W // p
    x = np.repeat(norm_img.transpose(2, 0, 1)[None], tps, axis=0)  # [t,C,H,W]
    x = x.reshape(1, tps, C, gh // m, m, p, gw // m, m, p)
    x = x.transpose(0, 3, 6, 4, 7, 2, 1, 5, 8)
    return (
        np.ascontiguousarray(x).reshape(gh * gw, C * tps * p * p),
        (1, gh, gw),
    )
