"""Cluster-wide observability: metrics registry + request-lifecycle spans.

The reference service has no aggregated metrics of its own — its /metrics
is a per-instance passthrough (http_service/service.cpp:452-457) and its
only tracing is a mutex-guarded JSONL appender. This package supplies the
layer P/D-Serve (arXiv:2408.08147) and the xLLM technical report
(arXiv:2510.14686) tune disaggregated fleets with: a lock-cheap
Counter/Gauge/Histogram registry with one Prometheus text renderer
(`metrics`), and structured per-request stage spans exportable as Chrome
trace_event JSON (`spans`).
"""

from xllm_service_tpu.obs.metrics import (
    BATCH_BUCKETS,
    LATENCY_BUCKETS_MS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    absorb_exposition,
    parse_exposition,
    render_families,
)
from xllm_service_tpu.obs.flight import FlightRecorder, SpanRing
from xllm_service_tpu.obs.spans import (
    ALL_SPAN_STAGES,
    INSTANCE_SPAN_STAGES,
    SPAN_STAGES,
    ClockSync,
    assemble_trace,
    blame_stages,
    build_timeline,
    load_spans,
    to_chrome_trace,
    trace_to_chrome,
)

__all__ = [
    "BATCH_BUCKETS",
    "LATENCY_BUCKETS_MS",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "absorb_exposition",
    "parse_exposition",
    "render_families",
    "ALL_SPAN_STAGES",
    "INSTANCE_SPAN_STAGES",
    "SPAN_STAGES",
    "ClockSync",
    "FlightRecorder",
    "SpanRing",
    "assemble_trace",
    "blame_stages",
    "build_timeline",
    "load_spans",
    "to_chrome_trace",
    "trace_to_chrome",
]
