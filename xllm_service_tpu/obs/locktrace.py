"""Runtime lock-order sanitizer (`XLLM_LOCK_TRACE=1`).

The static passes (docs/STATIC_ANALYSIS.md) catch what's visible in one
class; deadlocks live in the composition — instance A's heartbeat
holding its registry lock while the master's dispatch path holds the
scheduler lock and each posts to the other. This module is the runtime
half, modeled on the kernel's lockdep and TSan's deadlock detector, as
the reference stack's C++ service tier would get from TSan:

* `install()` patches `threading.Lock`/`threading.RLock` so every lock
  subsequently CREATED BY REPO CODE (creation-site frame inside this
  repository — stdlib/third-party locks are left untouched and untraced)
  is wrapped with an acquisition recorder;
* locks are grouped into CLASSES by creation site (`file:line`, the
  lockdep trick — every `InstanceMgr._mu` across a fleet of test
  instances is one class, so an order inversion between two *objects*
  of the same two classes is still caught);
* each acquire records a `held-class -> new-class` edge with one
  example (thread name + both creation sites). A cycle in the class
  graph is a potential deadlock: some interleaving of those call paths
  can stall both threads forever, even if this run got lucky;
* `faults.point(...)` hits are observed via `faults.set_point_observer`:
  an acquisition HELD ACROSS a fault point means chaos can inject a
  delay/hang while the lock is held — the lock-convoy half of every
  chaos-found stall — and is reported with the holding sites;
* the chaos/differential suites (test_faults, test_master_failover,
  test_prefix_fabric, test_encoder_fabric) assert a clean report via
  the autouse fixture in tests/conftest.py when `XLLM_LOCK_TRACE=1`.

Counters (scraped via `registry()`, documented in OBSERVABILITY.md):
`xllm_locktrace_locks_total`, `xllm_locktrace_acquires_total`,
`xllm_locktrace_edges_total`, `xllm_locktrace_point_holds_total`, and
the `xllm_locktrace_lock_classes` gauge.

Caveats, by design: module-level locks created before `install()` are
untraced (install runs at conftest import, before any component is
constructed, so in practice that's a handful of stdlib-shaped globals);
`Condition.wait`'s release/re-acquire is tracked through
`_release_save`/`_acquire_restore`, so a wait doesn't count as holding
the lock across the wait.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

from xllm_service_tpu.obs.metrics import MetricsRegistry

__all__ = [
    "enabled",
    "active",
    "install",
    "uninstall",
    "reset",
    "report",
    "note_point",
    "registry",
    "isolated",
]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))
_SELF = os.path.abspath(__file__).rstrip("co")  # .pyc → .py


def enabled() -> bool:
    return os.environ.get("XLLM_LOCK_TRACE", "") not in ("", "0")


# ---------------------------------------------------------------------------
# trace state
# ---------------------------------------------------------------------------


class _State:
    def __init__(self):
        # The sanitizer's OWN bookkeeping locks (this mu, the metric
        # registry's per-metric locks) must never be traced: a recorded
        # acquire increments a counter, and if that counter's lock were
        # itself traced the inc inside record_acquire would re-enter the
        # wrapper while the lock is already held — instant self-deadlock.
        # A fresh _State built while install() is active (isolated())
        # would hit exactly that, so construction runs on the original
        # factories.
        restore = None
        if _installed:
            restore = (threading.Lock, threading.RLock)
            threading.Lock, threading.RLock = _orig_lock, _orig_rlock
        try:
            self.mu = threading.Lock()  # guards edges/point_holds
            self.tls = threading.local()
            self.classes: Set[str] = set()
            self.edges: Dict[Tuple[str, str], dict] = {}
            self.point_holds: Dict[Tuple[str, str], int] = {}
            self.reg = MetricsRegistry()
            self.c_locks = self.reg.counter(
                "xllm_locktrace_locks_total", "Traced locks created")
            self.c_acquires = self.reg.counter(
                "xllm_locktrace_acquires_total", "Traced lock acquisitions")
            self.c_edges = self.reg.counter(
                "xllm_locktrace_edges_total",
                "Distinct held->acquired lock-class edges observed")
            self.c_point_holds = self.reg.counter(
                "xllm_locktrace_point_holds_total",
                "Fault-point hits with at least one traced lock held")
            self.g_classes = self.reg.gauge(
                "xllm_locktrace_lock_classes",
                "Distinct lock creation sites traced")
            self.g_classes.set_function(lambda: len(self.classes))
        finally:
            if restore is not None:
                threading.Lock, threading.RLock = restore

    # ------------------------------------------------------------ stack

    def stack(self) -> list:
        st = getattr(self.tls, "stack", None)
        if st is None:
            st = self.tls.stack = []
        return st

    # The recorders themselves take locks (the metrics registry's, this
    # state's `mu`) which may be traced too — a thread already inside a
    # recorder must pass straight through or every recorded acquire
    # recurses into recording its own bookkeeping locks.
    def _busy(self) -> bool:
        return getattr(self.tls, "busy", False)

    def record_acquire(self, lock: "_TracedLockBase") -> None:
        if self._busy():
            return
        self.tls.busy = True
        try:
            st = self.stack()
            self.c_acquires.inc()
            if st:
                new_edges = [
                    (h.site, lock.site) for h in st
                    if h is not lock
                    and (h.site, lock.site) not in self.edges
                ]
                if new_edges:
                    with self.mu:
                        for a, b in new_edges:
                            if (a, b) not in self.edges:
                                self.edges[(a, b)] = {
                                    "thread":
                                        threading.current_thread().name,
                                }
                                self.c_edges.inc()
            st.append(lock)
        finally:
            self.tls.busy = False

    def record_release(self, lock: "_TracedLockBase") -> None:
        if self._busy():
            return
        st = self.stack()
        # remove LAST occurrence — manual acquire/release may interleave
        for i in range(len(st) - 1, -1, -1):
            if st[i] is lock:
                del st[i]
                return

    def note_point(self, name: str) -> None:
        if self._busy():
            return
        st = self.stack()
        if not st:
            return
        self.tls.busy = True
        try:
            self.c_point_holds.inc()
            with self.mu:
                for h in st:
                    key = (name, h.site)
                    self.point_holds[key] = self.point_holds.get(key, 0) + 1
        finally:
            self.tls.busy = False

    # ------------------------------------------------------------ report

    def cycles(self) -> List[List[str]]:
        """Every elementary cycle's node list (deduped by node set) in
        the lock-class graph — small graphs, plain DFS is fine."""
        with self.mu:
            adj: Dict[str, List[str]] = {}
            for a, b in self.edges:
                adj.setdefault(a, []).append(b)
        out: List[List[str]] = []
        seen_sets: Set[frozenset] = set()

        def dfs(start: str, node: str, path: List[str],
                on_path: Set[str]) -> None:
            for nxt in adj.get(node, ()):
                if nxt == start and len(path) >= 1:
                    key = frozenset(path)
                    if key not in seen_sets:
                        seen_sets.add(key)
                        out.append(path + [start])
                elif nxt not in on_path and nxt > start:
                    # only explore nodes ordered after `start`: each
                    # cycle is found once, from its smallest node
                    dfs(start, nxt, path + [nxt], on_path | {nxt})

        # self-edges (two instances of one class nested) fall out of the
        # same DFS: start's successor list contains start itself.
        for n in sorted(adj):
            dfs(n, n, [n], {n})
        return out


_installed = False
_orig_lock = None
_orig_rlock = None
_state = _State()


# ---------------------------------------------------------------------------
# traced lock wrappers
# ---------------------------------------------------------------------------


def _creation_site() -> Optional[str]:
    """repo-relative file:line of the frame that created the lock, or
    None when the creator is outside the repo (don't trace)."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        base = os.path.basename(fn)
        if base != "threading.py" and os.path.abspath(fn) != _SELF:
            absfn = os.path.abspath(fn)
            if absfn.startswith(_REPO_ROOT + os.sep):
                return f"{os.path.relpath(absfn, _REPO_ROOT)}:{f.f_lineno}"
            return None
        f = f.f_back
    return None


class _TracedLockBase:
    site: str

    def __repr__(self):
        return f"<traced {type(self).__name__} {self.site}>"


class _TracedLock(_TracedLockBase):
    def __init__(self, inner, site: str):
        self._inner = inner
        self.site = site

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _state.record_acquire(self)
        return ok

    def release(self):
        _state.record_release(self)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class _TracedRLock(_TracedLockBase):
    def __init__(self, inner, site: str):
        self._inner = inner
        self.site = site
        self._depth = 0  # mutated only by the owning thread

    def acquire(self, blocking: bool = True, timeout: float = -1):
        reentrant = self._inner._is_owned()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            if not reentrant:
                _state.record_acquire(self)
            self._depth += 1
        return ok

    def release(self):
        self._depth -= 1
        if self._depth == 0:
            _state.record_release(self)
        self._inner.release()

    # Condition integration: wait() fully releases (recursion included)
    # and re-acquires — the held-stack must mirror that or every
    # cv.wait() looks like a lock held across whatever woke it.
    def _release_save(self):
        st = self._inner._release_save()
        depth, self._depth = self._depth, 0
        _state.record_release(self)
        return (st, depth)

    def _acquire_restore(self, saved):
        st, depth = saved
        self._inner._acquire_restore(st)
        _state.record_acquire(self)
        self._depth = depth

    def _is_owned(self):
        return self._inner._is_owned()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


def _make_lock():
    site = _creation_site()
    inner = _orig_lock()
    if site is None:
        return inner
    _state.classes.add(site)
    _state.c_locks.inc()
    return _TracedLock(inner, site)


def _make_rlock():
    site = _creation_site()
    inner = _orig_rlock()
    if site is None:
        return inner
    _state.classes.add(site)
    _state.c_locks.inc()
    return _TracedRLock(inner, site)


# ---------------------------------------------------------------------------
# public surface
# ---------------------------------------------------------------------------


def active() -> bool:
    return _installed


def install() -> None:
    """Patch threading.Lock/RLock and observe fault points. Idempotent."""
    global _installed, _orig_lock, _orig_rlock
    if _installed:
        return
    _orig_lock = threading.Lock
    _orig_rlock = threading.RLock
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    from xllm_service_tpu.common import faults

    faults.set_point_observer(note_point)
    _installed = True


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    threading.Lock = _orig_lock
    threading.RLock = _orig_rlock
    from xllm_service_tpu.common import faults

    faults.set_point_observer(None)
    _installed = False


def reset() -> None:
    """Drop recorded graph/holds (lock classes persist — creation sites
    don't un-happen). Used between fixture scopes."""
    with _state.mu:
        _state.edges.clear()
        _state.point_holds.clear()


def note_point(name: str) -> None:
    _state.note_point(name)


def registry() -> MetricsRegistry:
    return _state.reg


def report() -> dict:
    """{'cycles': [[site,...],...], 'point_holds': {(point, site): n},
    'edges': n, 'classes': n} — the fixture asserts cycles == [] and
    point_holds == {}."""
    cycles = _state.cycles()
    with _state.mu:
        return {
            "cycles": cycles,
            "point_holds": dict(_state.point_holds),
            "edges": len(_state.edges),
            "classes": len(_state.classes),
        }


class isolated:
    """Context manager swapping in a fresh _State — the synthetic
    cycle/point-hold unit tests must not pollute (or read) the suite-wide
    graph."""

    def __enter__(self):
        global _state
        self._saved = _state
        _state = _State()
        return _state

    def __exit__(self, *exc):
        global _state
        _state = self._saved
        return False
