"""Request-lifecycle spans: structured stage records -> timelines/traces.

The tracer (service/request.py RequestTracer.stage) appends one JSONL
record per stage transition:

    {"type": "stage", "service_request_id": ..., "stage": ...,
     "t_mono_ms": <monotonic ms>, "timestamp_ms": <wall ms>, ...fields}

Stage vocabulary (SPAN_STAGES) follows the request path end to end:
receive -> tokenize -> route -> dispatch -> first_token -> decode ticks ->
finish (or cancel/error), with redispatch interleaved on fault replay.
This module reconstructs per-request timelines from the JSONL and exports
Chrome `trace_event` JSON (chrome://tracing / Perfetto "load trace"),
giving the per-stage latency breakdown P/D-Serve (arXiv:2408.08147) argues
disaggregated serving is tuned by.
"""

from __future__ import annotations

import json
from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Tuple

SPAN_STAGES = (
    "receive",
    # Admission verdict (service/admission.py): the request was turned
    # away at the front door — 429 + Retry-After, before tokenize ever
    # ran. Terminal: a shed request has no further timeline.
    "shed",
    "tokenize",
    "route",
    "dispatch",
    "redispatch",
    "resume",
    "first_token",
    "decode",
    "finish",
    "cancel",
    "error",
)

# Instance/engine-side stage vocabulary (distributed tracing,
# docs/OBSERVABILITY.md): spans emitted into per-process ring buffers by
# the serving/KV/fabric/mm mixins and the engine loop, merged with the
# master's SPAN_STAGES timeline by assemble_trace().
INSTANCE_SPAN_STAGES = (
    "admit",
    "prefill_chunk",
    "step_batch",
    "handoff_send",
    "handoff_commit",
    "kv_chunk_sent",
    "kv_chunk_landed",
    "decode_admit",
    "fabric_fetch",
    "fabric_landed",
    "encoder_batch",
    "flight_dump",
    # Master-side fabric routing decisions (cluster/prefix_fabric.py,
    # cluster/encoder_fabric.py): dispatch-time plan spans on the same
    # merged timeline.
    "fabric_plan",
    "encoder_route",
)

# The canonical vocabulary the span-stages lint pass enforces: every
# stage literal emitted anywhere in the tree must be one of these.
ALL_SPAN_STAGES = SPAN_STAGES + INSTANCE_SPAN_STAGES

# Terminal stages close a request's timeline.
TERMINAL_STAGES = frozenset(("finish", "cancel", "error", "shed"))


def load_spans(path: str) -> List[Dict[str, Any]]:
    """Stage records from a tracer JSONL file (non-stage records — the
    raw in/out payload traces — are skipped)."""
    records: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("type") == "stage":
                records.append(rec)
    return records


def build_timeline(
    records: Iterable[Dict[str, Any]],
) -> "OrderedDict[str, List[Dict[str, Any]]]":
    """service_request_id -> stage records in RECORDED order.

    Raises ValueError if any request's records go backwards in time — the
    tracer stamps a single process monotonic clock and appends under one
    lock, so a regression means a corrupted or hand-interleaved trace
    file. The records are deliberately NOT re-sorted: sorting would mask
    exactly the corruption this check exists to surface."""
    by_req: "OrderedDict[str, List[Dict[str, Any]]]" = OrderedDict()
    for rec in records:
        srid = rec.get("service_request_id", "")
        by_req.setdefault(srid, []).append(rec)
    for srid, recs in by_req.items():
        prev = None
        for r in recs:
            t = float(r.get("t_mono_ms", 0.0))
            if prev is not None and t < prev:
                raise ValueError(
                    f"{srid}: non-monotonic stage timestamps "
                    f"({t} after {prev})"
                )
            prev = t
    return by_req


def stage_durations_ms(
    timeline: List[Dict[str, Any]],
) -> List[Tuple[str, float]]:
    """[(stage, ms-until-next-stage)] for one request's ordered records;
    the terminal record gets duration 0."""
    out: List[Tuple[str, float]] = []
    for i, rec in enumerate(timeline):
        t = float(rec.get("t_mono_ms", 0.0))
        if i + 1 < len(timeline):
            dur = float(timeline[i + 1].get("t_mono_ms", 0.0)) - t
        else:
            dur = 0.0
        out.append((str(rec.get("stage", "")), dur))
    return out


_META_KEYS = ("type", "service_request_id", "stage", "t_mono_ms",
              "timestamp_ms")


def to_chrome_trace(records: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome trace_event JSON: per request, each stage becomes a complete
    ("X") slice lasting until the next stage; the terminal stage is an
    instant ("i"). Requests map to tids so the trace viewer stacks them as
    parallel tracks. Extra record fields ride in args."""
    by_req = build_timeline(records)
    events: List[Dict[str, Any]] = []
    for tid, (srid, recs) in enumerate(by_req.items(), start=1):
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": tid,
                "args": {"name": srid},
            }
        )
        for i, rec in enumerate(recs):
            ts_us = float(rec.get("t_mono_ms", 0.0)) * 1000.0
            args = {k: v for k, v in rec.items() if k not in _META_KEYS}
            stage = str(rec.get("stage", ""))
            if i + 1 < len(recs):
                dur_us = (
                    float(recs[i + 1].get("t_mono_ms", 0.0)) * 1000.0 - ts_us
                )
                events.append(
                    {
                        "name": stage,
                        "cat": "request",
                        "ph": "X",
                        "ts": ts_us,
                        "dur": max(dur_us, 0.0),
                        "pid": 1,
                        "tid": tid,
                        "args": args,
                    }
                )
            else:
                events.append(
                    {
                        "name": stage,
                        "cat": "request",
                        "ph": "i",
                        "s": "t",
                        "ts": ts_us,
                        "pid": 1,
                        "tid": tid,
                        "args": args,
                    }
                )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(records: Iterable[Dict[str, Any]], path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(to_chrome_trace(records), f)


# --------------------------------------------------------------------- #
# cross-process clock alignment + trace assembly (distributed tracing)
# --------------------------------------------------------------------- #


class ClockSync:
    """Monotonic-offset estimator for one instance clock against the
    master's, fed by samples piggybacked on heartbeats.

    Define o = master_mono - instance_mono (both in ms). Each heartbeat
    REQUEST carries the instance's send stamp: the master's receive stamp
    gives  recv - send = o + d  with one-way delay d >= 0, an UPPER bound
    of o. Each heartbeat RESPONSE carries the master's reply stamp, which
    the instance echoes on its NEXT beat together with its own receive
    stamp: reply <= recv_i + o, so  reply - recv_i  is a LOWER bound.
    The estimate is the midpoint of the intersection [max lower, min
    upper] over a bounded window; with only upper bounds (first beat) it
    degrades to min-upper, which overestimates o by the minimum one-way
    delay — mapped instance events then land slightly late, never before
    the master RPC that caused them."""

    WINDOW = 64

    def __init__(self) -> None:
        self._uppers: List[float] = []
        self._lowers: List[float] = []

    def sample_upper(self, bound_ms: float) -> None:
        self._uppers.append(float(bound_ms))
        del self._uppers[: -self.WINDOW]

    def sample_lower(self, bound_ms: float) -> None:
        self._lowers.append(float(bound_ms))
        del self._lowers[: -self.WINDOW]

    @property
    def samples(self) -> int:
        return len(self._uppers) + len(self._lowers)

    def offset_ms(self) -> float:
        """Best current estimate of o = master_mono - instance_mono."""
        upper = min(self._uppers) if self._uppers else None
        lower = max(self._lowers) if self._lowers else None
        if upper is not None and lower is not None and lower <= upper:
            return (upper + lower) / 2.0
        if upper is not None:
            return upper
        if lower is not None:
            return lower
        return 0.0

    def to_json(self) -> Dict[str, Any]:
        return {
            "offset_ms": round(self.offset_ms(), 3),
            "samples": self.samples,
            "upper_ms": round(min(self._uppers), 3) if self._uppers else None,
            "lower_ms": round(max(self._lowers), 3) if self._lowers else None,
        }


def assemble_trace(
    master_process: str,
    master_spans: Iterable[Dict[str, Any]],
    participants: Iterable[Tuple[str, Iterable[Dict[str, Any]], float]],
) -> List[Dict[str, Any]]:
    """ONE merged per-request timeline from every participant's spans.

    `participants` is (process_name, spans, offset_ms) per instance, with
    offset_ms = master_mono - instance_mono (ClockSync.offset_ms): each
    instance record's t_mono_ms is shifted into the MASTER clock domain
    so inter-process durations subtract exactly. Records are returned
    sorted on the aligned clock with a `process` field stamped on each;
    ties keep master-before-instance order (the RPC that caused an
    instance span sorts ahead of it)."""
    merged: List[Dict[str, Any]] = []
    for rec in master_spans:
        r = dict(rec)
        r.setdefault("process", master_process)
        merged.append(r)
    for name, spans, off in participants:
        for rec in spans:
            r = dict(rec)
            r["process"] = name
            r["t_mono_ms"] = float(r.get("t_mono_ms", 0.0)) + float(off)
            merged.append(r)
    merged.sort(
        key=lambda r: (
            float(r.get("t_mono_ms", 0.0)),
            0 if r.get("process") == master_process else 1,
        )
    )
    return merged


_TRACE_META_KEYS = _META_KEYS + ("process",)


def trace_to_chrome(merged: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome trace_event JSON for one ASSEMBLED multi-process trace
    (assemble_trace output): one pid track per process so Perfetto stacks
    master/prefill/decode/encoder timelines in parallel, each span a
    complete ("X") slice lasting until that process's next span (the
    process's last span is an instant)."""
    procs: "OrderedDict[str, List[Dict[str, Any]]]" = OrderedDict()
    for rec in merged:
        procs.setdefault(str(rec.get("process", "")), []).append(rec)
    events: List[Dict[str, Any]] = []
    for pid, (proc, recs) in enumerate(procs.items(), start=1):
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": proc},
        })
        for i, rec in enumerate(recs):
            ts_us = float(rec.get("t_mono_ms", 0.0)) * 1000.0
            args = {
                k: v for k, v in rec.items() if k not in _TRACE_META_KEYS
            }
            ev: Dict[str, Any] = {
                "name": str(rec.get("stage", "")),
                "cat": "trace",
                "pid": pid,
                "tid": 1,
                "ts": ts_us,
                "args": args,
            }
            if i + 1 < len(recs):
                nxt = float(recs[i + 1].get("t_mono_ms", 0.0)) * 1000.0
                ev["ph"] = "X"
                ev["dur"] = max(nxt - ts_us, 0.0)
            else:
                ev["ph"] = "i"
                ev["s"] = "t"
            events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# p99 blame attribution: stage -> (start anchor, end anchor). Each anchor
# names the FIRST record with that stage in the aligned timeline; missing
# anchors void the stage (blamed 0) rather than guessing.
_BLAME_EDGES = (
    ("queue", "receive", "dispatch"),
    ("prefill", "admit", "handoff_send"),
    ("handoff", "handoff_send", "decode_admit"),
    ("decode", "decode_admit", "finish"),
)


def blame_stages(merged: List[Dict[str, Any]]) -> Dict[str, float]:
    """Per-stage latency blame for one assembled trace: queue vs prefill
    vs handoff vs decode vs host_gap (ms). host_gap is everything the
    named edges don't cover — RPC transit, serving-thread scheduling,
    push batching — so the five always sum to the end-to-end span.
    Colocated (non-PD) traces have no handoff/decode_admit anchors:
    prefill falls back to dispatch->first_token, decode to
    first_token->finish, and handoff blames 0. (A PD trace must NOT use
    the first_token anchor for decode: the prefill side pushes the first
    token BEFORE the handoff, so that edge would double-count the whole
    handoff window and the blame table could never point at it.)"""
    first: Dict[str, float] = {}
    for rec in merged:
        stage = str(rec.get("stage", ""))
        if stage and stage not in first:
            first[stage] = float(rec.get("t_mono_ms", 0.0))
    t_start = min(first.values()) if first else 0.0
    terminal = [first[s] for s in TERMINAL_STAGES if s in first]
    t_end = max(terminal) if terminal else (
        max(first.values()) if first else 0.0
    )
    blame: Dict[str, float] = {}
    covered = 0.0
    for name, a, b in _BLAME_EDGES:
        if a in first and b in first and first[b] >= first[a]:
            dur = first[b] - first[a]
        elif name == "prefill" and "dispatch" in first and "first_token" in first:
            dur = max(first["first_token"] - first["dispatch"], 0.0)
        elif name == "decode" and "first_token" in first and "finish" in first:
            dur = max(first["finish"] - first["first_token"], 0.0)
        else:
            dur = 0.0
        blame[name] = round(dur, 3)
        covered += dur
    blame["host_gap"] = round(max((t_end - t_start) - covered, 0.0), 3)
    blame["total"] = round(max(t_end - t_start, 0.0), 3)
    return blame
