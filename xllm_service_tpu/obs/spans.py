"""Request-lifecycle spans: structured stage records -> timelines/traces.

The tracer (service/request.py RequestTracer.stage) appends one JSONL
record per stage transition:

    {"type": "stage", "service_request_id": ..., "stage": ...,
     "t_mono_ms": <monotonic ms>, "timestamp_ms": <wall ms>, ...fields}

Stage vocabulary (SPAN_STAGES) follows the request path end to end:
receive -> tokenize -> route -> dispatch -> first_token -> decode ticks ->
finish (or cancel/error), with redispatch interleaved on fault replay.
This module reconstructs per-request timelines from the JSONL and exports
Chrome `trace_event` JSON (chrome://tracing / Perfetto "load trace"),
giving the per-stage latency breakdown P/D-Serve (arXiv:2408.08147) argues
disaggregated serving is tuned by.
"""

from __future__ import annotations

import json
from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Tuple

SPAN_STAGES = (
    "receive",
    "tokenize",
    "route",
    "dispatch",
    "redispatch",
    "first_token",
    "decode",
    "finish",
    "cancel",
    "error",
)

# Terminal stages close a request's timeline.
TERMINAL_STAGES = frozenset(("finish", "cancel", "error"))


def load_spans(path: str) -> List[Dict[str, Any]]:
    """Stage records from a tracer JSONL file (non-stage records — the
    raw in/out payload traces — are skipped)."""
    records: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("type") == "stage":
                records.append(rec)
    return records


def build_timeline(
    records: Iterable[Dict[str, Any]],
) -> "OrderedDict[str, List[Dict[str, Any]]]":
    """service_request_id -> stage records in RECORDED order.

    Raises ValueError if any request's records go backwards in time — the
    tracer stamps a single process monotonic clock and appends under one
    lock, so a regression means a corrupted or hand-interleaved trace
    file. The records are deliberately NOT re-sorted: sorting would mask
    exactly the corruption this check exists to surface."""
    by_req: "OrderedDict[str, List[Dict[str, Any]]]" = OrderedDict()
    for rec in records:
        srid = rec.get("service_request_id", "")
        by_req.setdefault(srid, []).append(rec)
    for srid, recs in by_req.items():
        prev = None
        for r in recs:
            t = float(r.get("t_mono_ms", 0.0))
            if prev is not None and t < prev:
                raise ValueError(
                    f"{srid}: non-monotonic stage timestamps "
                    f"({t} after {prev})"
                )
            prev = t
    return by_req


def stage_durations_ms(
    timeline: List[Dict[str, Any]],
) -> List[Tuple[str, float]]:
    """[(stage, ms-until-next-stage)] for one request's ordered records;
    the terminal record gets duration 0."""
    out: List[Tuple[str, float]] = []
    for i, rec in enumerate(timeline):
        t = float(rec.get("t_mono_ms", 0.0))
        if i + 1 < len(timeline):
            dur = float(timeline[i + 1].get("t_mono_ms", 0.0)) - t
        else:
            dur = 0.0
        out.append((str(rec.get("stage", "")), dur))
    return out


_META_KEYS = ("type", "service_request_id", "stage", "t_mono_ms",
              "timestamp_ms")


def to_chrome_trace(records: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome trace_event JSON: per request, each stage becomes a complete
    ("X") slice lasting until the next stage; the terminal stage is an
    instant ("i"). Requests map to tids so the trace viewer stacks them as
    parallel tracks. Extra record fields ride in args."""
    by_req = build_timeline(records)
    events: List[Dict[str, Any]] = []
    for tid, (srid, recs) in enumerate(by_req.items(), start=1):
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": tid,
                "args": {"name": srid},
            }
        )
        for i, rec in enumerate(recs):
            ts_us = float(rec.get("t_mono_ms", 0.0)) * 1000.0
            args = {k: v for k, v in rec.items() if k not in _META_KEYS}
            stage = str(rec.get("stage", ""))
            if i + 1 < len(recs):
                dur_us = (
                    float(recs[i + 1].get("t_mono_ms", 0.0)) * 1000.0 - ts_us
                )
                events.append(
                    {
                        "name": stage,
                        "cat": "request",
                        "ph": "X",
                        "ts": ts_us,
                        "dur": max(dur_us, 0.0),
                        "pid": 1,
                        "tid": tid,
                        "args": args,
                    }
                )
            else:
                events.append(
                    {
                        "name": stage,
                        "cat": "request",
                        "ph": "i",
                        "s": "t",
                        "ts": ts_us,
                        "pid": 1,
                        "tid": tid,
                        "args": args,
                    }
                )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(records: Iterable[Dict[str, Any]], path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(to_chrome_trace(records), f)
