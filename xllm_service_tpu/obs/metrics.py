"""Lock-cheap metrics registry + Prometheus text-exposition rendering.

One registry per component (scheduler, engine, HTTP plane) rather than one
process-global singleton: tests and benches run a whole cluster — master +
N instances — inside a single process, and per-component registries keep
their series from bleeding into each other. A component exposes itself by
rendering its registry; the master aggregates by parsing scraped instance
expositions and re-emitting every sample under an `instance` label with ONE
`# TYPE` line per family (the text parser rejects duplicate TYPE lines and
ungrouped series, which would fail the whole scrape).

Conventions (enforced at registration, linted by
scripts/check_metric_names.py):
  * every name matches ^xllm_[a-z0-9_]+$;
  * counters end in `_total`;
  * histograms render `_bucket` (cumulative, `le` labels, `+Inf`),
    `_sum`, `_count`.

Hot-path cost: a labeled child is resolved once and cached by the caller;
inc/observe take one short per-child lock (allocation-free).
"""

from __future__ import annotations

import bisect
import re
import threading
from collections import OrderedDict
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^xllm_[a-z0-9_]+$")
_LABEL_RE = re.compile(r"^[a-z_][a-z0-9_]*$")

# Fixed log-spaced latency buckets (ms), shared by every latency histogram
# in the system so fleet-wide quantiles aggregate exactly: a 1-2-5 ladder
# from 1 ms to 60 s covers TTFT, TPOT, queue delay, and E2E alike.
LATENCY_BUCKETS_MS: Tuple[float, ...] = (
    1, 2, 5, 10, 20, 50, 100, 200, 500,
    1000, 2000, 5000, 10000, 30000, 60000,
)

# Power-of-two occupancy buckets (batch sizes, queue depths).
BATCH_BUCKETS: Tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256)


def _fmt_num(v: float) -> str:
    """Prometheus sample value: integral floats render without the dot."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label_value(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class _Child:
    """One (metric, label-set) time series."""

    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def set_function(self, fn: Callable[[], float]) -> None:
        """Pull the value from `fn` at render time instead of storing it —
        exposes an existing counter attribute or queue length without
        instrumenting its hot path. The source must stay monotonic when
        the parent metric is a Counter."""
        self._fn = fn

    def get(self) -> float:
        fn = self._fn
        if fn is not None:
            try:
                return float(fn())
            except Exception:
                return 0.0
        with self._lock:
            return self._value


class _Metric:
    kind = "untyped"

    def __init__(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
    ) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} must match {_NAME_RE.pattern}"
            )
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"bad label name {ln!r} on {name}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: "OrderedDict[Tuple[str, ...], _Child]" = OrderedDict()
        self._children_mu = threading.Lock()
        if not self.labelnames:
            self._default = self._make_child()
            self._children[()] = self._default
        else:
            self._default = None

    def _make_child(self) -> _Child:
        return _Child()

    def labels(self, **kv: str) -> _Child:
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.labelnames)}"
            )
        key = tuple(str(kv[ln]) for ln in self.labelnames)
        with self._children_mu:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child()
        return child

    def _iter_children(self) -> List[Tuple[Dict[str, str], _Child]]:
        with self._children_mu:
            items = list(self._children.items())
        return [
            (dict(zip(self.labelnames, key)), child) for key, child in items
        ]

    # -- unlabeled conveniences ---------------------------------------- #
    def _only(self) -> _Child:
        if self._default is None:
            raise ValueError(f"{self.name} is labeled; use .labels(...)")
        return self._default

    def inc(self, n: float = 1.0) -> None:
        self._only().inc(n)

    def set(self, v: float) -> None:
        self._only().set(v)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._only().set_function(fn)

    def get(self) -> float:
        return self._only().get()

    # -- rendering ------------------------------------------------------ #
    def collect(self) -> List[Tuple[str, str]]:
        """[(labels_str, value_str)] sample lines (name prepended later)."""
        return [
            (_label_str(labels), _fmt_num(child.get()))
            for labels, child in self._iter_children()
        ]


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help="", labelnames=()):
        if not name.endswith("_total"):
            raise ValueError(f"counter {name!r} must end in _total")
        super().__init__(name, help, labelnames)

    def dec(self, n: float = 1.0) -> None:  # pragma: no cover — guard
        raise TypeError("counters only go up")

    def set(self, v: float) -> None:  # pragma: no cover — guard
        raise TypeError("counters only go up; use inc() or set_function()")


class Gauge(_Metric):
    kind = "gauge"

    def dec(self, n: float = 1.0) -> None:
        self._only().dec(n)


class _HistChild:
    __slots__ = ("_lock", "_bounds", "counts", "sum", "count")

    def __init__(self, bounds: Tuple[float, ...]):
        self._lock = threading.Lock()
        self._bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # last = +Inf overflow
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        idx = bisect.bisect_left(self._bounds, v)
        with self._lock:
            self.counts[idx] += 1
            self.sum += v
            self.count += 1

    def snapshot(self) -> Tuple[List[int], float, int]:
        with self._lock:
            return list(self.counts), self.sum, self.count

    def percentile(self, q: float) -> Optional[float]:
        """Bucket-interpolated quantile estimate (q in [0, 100]). None when
        empty; the +Inf bucket clamps to the largest finite bound."""
        counts, _, total = self.snapshot()
        if total == 0:
            return None
        target = max(1.0, (q / 100.0) * total)
        cum = 0
        for i, c in enumerate(counts):
            prev_cum = cum
            cum += c
            if cum >= target:
                if i >= len(self._bounds):
                    return float(self._bounds[-1])
                lo = self._bounds[i - 1] if i > 0 else 0.0
                hi = self._bounds[i]
                frac = (target - prev_cum) / max(c, 1)
                return float(lo + (hi - lo) * frac)
        return float(self._bounds[-1])


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name,
        help="",
        labelnames=(),
        buckets: Sequence[float] = LATENCY_BUCKETS_MS,
    ):
        for suffix in ("_total", "_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                raise ValueError(
                    f"histogram {name!r} must not end in {suffix} "
                    "(those suffixes are render-reserved)"
                )
        b = tuple(float(x) for x in buckets)
        if not b or list(b) != sorted(set(b)):
            raise ValueError("buckets must be sorted and distinct")
        self.buckets = b
        super().__init__(name, help, labelnames)

    def _make_child(self) -> _HistChild:
        return _HistChild(self.buckets)

    def observe(self, v: float) -> None:
        self._only().observe(v)

    def percentile(self, q: float) -> Optional[float]:
        return self._only().percentile(q)

    def collect(self) -> List[Tuple[str, str]]:
        """Histogram expands to _bucket/_sum/_count sample lines; the
        returned labels_str here carries the FULL sample name because the
        suffixes differ per line (render() special-cases kind)."""
        out: List[Tuple[str, str]] = []
        for labels, child in self._iter_children():
            counts, total_sum, n = child.snapshot()
            cum = 0
            for bound, c in zip(self.buckets, counts):
                cum += c
                ls = _label_str({**labels, "le": _fmt_num(bound)})
                out.append((f"{self.name}_bucket{ls}", _fmt_num(cum)))
            ls = _label_str({**labels, "le": "+Inf"})
            out.append((f"{self.name}_bucket{ls}", _fmt_num(n)))
            out.append(
                (f"{self.name}_sum{_label_str(labels)}", _fmt_num(total_sum))
            )
            out.append(
                (f"{self.name}_count{_label_str(labels)}", _fmt_num(n))
            )
        return out


class MetricsRegistry:
    """Create-or-get metric factory + renderer for one component."""

    def __init__(self) -> None:
        self._metrics: "OrderedDict[str, _Metric]" = OrderedDict()
        self._mu = threading.Lock()

    def _register(self, cls, name, help, labelnames, **kw) -> _Metric:
        with self._mu:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls:
                    raise ValueError(
                        f"{name} already registered as {m.kind}"
                    )
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(
        self, name, help="", labelnames=(), buckets=LATENCY_BUCKETS_MS
    ) -> Histogram:
        return self._register(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def get(self, name: str) -> Optional[_Metric]:
        with self._mu:
            return self._metrics.get(name)

    def names(self) -> List[Tuple[str, str]]:
        """[(name, kind)] of everything registered (lint surface)."""
        with self._mu:
            return [(m.name, m.kind) for m in self._metrics.values()]

    def families(self) -> "OrderedDict[str, Tuple[str, str, List[Tuple[str, str]]]]":
        """name -> (kind, help, [(sample_suffix_or_labels, value)]).

        For counter/gauge the first tuple element is the label string to
        append to the family name; for histograms it is the FULL sample
        name (suffix + labels) and the family name must not be prepended.
        render_families() handles both via the histogram kind.
        """
        with self._mu:
            metrics = list(self._metrics.values())
        fams: "OrderedDict[str, Tuple[str, str, List[Tuple[str, str]]]]" = (
            OrderedDict()
        )
        for m in metrics:
            fams[m.name] = (m.kind, m.help, m.collect())
        return fams

    def render(self) -> str:
        return render_families(self.families())


# --------------------------------------------------------------------- #
# exposition text: render / parse / merge (master-side aggregation)
# --------------------------------------------------------------------- #

def render_families(fams) -> str:
    """One text exposition from a families dict — exactly one HELP/TYPE
    pair per family, every sample grouped contiguously under it.

    HISTOGRAM families with NO samples are omitted entirely: a labelled
    histogram nobody has observed yet (e.g. the scrape-latency histogram
    on the very first exposure, whose observations land DURING the
    scrape the exposition is being built for) would otherwise render a
    TYPE-only header, which a strict scraper rejects as a histogram
    without `_bucket` samples. Empty counter/gauge families keep their
    TYPE-only header — that IS valid exposition, and tests and dashboards
    discover series names from it."""
    lines: List[str] = []
    for name, (kind, help_text, samples) in fams.items():
        if not samples and kind == "histogram":
            continue
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        for head, value in samples:
            if kind == "histogram":
                lines.append(f"{head} {value}")
            else:
                lines.append(f"{name}{head} {value}")
    return "\n".join(lines) + "\n" if lines else ""


_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)\s*$"
)
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _family_of(sample_name: str, known: Dict[str, str]) -> str:
    """Map a histogram sample name back to its family."""
    for suffix in ("_bucket", "_sum", "_count"):
        base = sample_name[: -len(suffix)]
        if sample_name.endswith(suffix) and known.get(base) == "histogram":
            return base
    return sample_name


def parse_exposition(text: str):
    """Parse Prometheus text into an OrderedDict:
    name -> (kind, help, [(sample_name, labels_dict, value_str)]).

    Tolerant: unknown families default to `untyped`; values stay strings
    so re-rendering never drifts a float. Used by the master to re-label
    scraped instance expositions before merging."""
    fams: "OrderedDict[str, List]" = OrderedDict()
    kinds: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) >= 4:
                kinds[parts[2]] = parts[3]
                fams.setdefault(parts[2], [])
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) >= 3:
                helps[parts[2]] = parts[3] if len(parts) > 3 else ""
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        sample_name, labels_raw, value = m.groups()
        labels = dict(_LABEL_PAIR_RE.findall(labels_raw or ""))
        fam = _family_of(sample_name, kinds)
        fams.setdefault(fam, []).append((sample_name, labels, value))
    return OrderedDict(
        (
            name,
            (kinds.get(name, "untyped"), helps.get(name, ""), samples),
        )
        for name, samples in fams.items()
    )


def absorb_exposition(
    fams,
    text: str,
    extra_labels: Optional[Dict[str, str]] = None,
) -> List[str]:
    """Merge one exposition into a render_families()-shaped dict, adding
    `extra_labels` to every sample. Families that already exist keep their
    first-seen kind/help and the new samples append under the SAME single
    TYPE line — the whole point of aggregation (a second TYPE line would
    fail strict scrapers). Kind conflicts (a family whose incoming # TYPE
    disagrees with the first-seen one) deterministically SKIP the incoming
    samples — first-seen kind wins regardless of merge order within a
    family — and the skipped family names are returned so callers can
    count them instead of losing series silently."""
    # Parsed label values are kept in their ESCAPED wire form; only the
    # extra labels need escaping here — re-escaping parsed values would
    # drift a backslash/quote-bearing value on every aggregation hop.
    extra = {
        k: _escape_label_value(v) for k, v in (extra_labels or {}).items()
    }

    def label_str_raw(escaped: Dict[str, str]) -> str:
        if not escaped:
            return ""
        inner = ",".join(
            f'{k}="{v}"' for k, v in sorted(escaped.items())
        )
        return "{" + inner + "}"

    conflicts: List[str] = []
    for name, (kind, help_text, samples) in parse_exposition(text).items():
        rendered: List[Tuple[str, str]] = []
        for sample_name, labels, value in samples:
            merged = {**labels, **extra}
            if kind == "histogram":
                rendered.append(
                    (f"{sample_name}{label_str_raw(merged)}", value)
                )
            else:
                rendered.append((label_str_raw(merged), value))
        if name in fams:
            prev_kind, prev_help, prev_samples = fams[name]
            if prev_kind != kind:
                conflicts.append(name)
                continue
            fams[name] = (prev_kind, prev_help, prev_samples + rendered)
        else:
            fams[name] = (kind, help_text, rendered)
    return conflicts
