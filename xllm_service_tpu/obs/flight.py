"""Anomaly flight recorder: always-on span rings + triggered dumps.

Every process (master, each instance) keeps a bounded in-memory ring of
recent span records (SpanRing) — cheap enough to stay on in production,
and the source both for the master's `GET /trace/<srid>` collector and
for the FlightRecorder, which dumps the whole ring to disk the moment an
anomaly trigger fires (SLO breach, breaker ejection, fenced RPC, KV
handoff stall over threshold) so the "black box" around an incident
survives the incident. Dumps are rate-limited and rotation-bounded; the
recorder never throws into the serving path.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

__all__ = ["SpanRing", "FlightRecorder"]


class SpanRing:
    """Bounded, thread-safe ring of span records for one process.

    Records mirror the tracer's stage schema ({"type": "stage",
    "service_request_id", "stage", "t_mono_ms", "timestamp_ms", ...}) so
    obs.spans timeline/assembly code consumes them unchanged. Emission is
    per-event (admission, chunk, step batch — never per-token) and lock
    hold time is O(1) append, so the ring is safe to leave always-on.
    """

    def __init__(self, process: str, capacity: int = 2048) -> None:
        self.process = process
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring: Deque[Dict[str, Any]] = deque(maxlen=self.capacity)
        self._emitted = 0

    def emit(self, service_request_id: str, stage: str, **fields: Any) -> None:
        rec: Dict[str, Any] = {
            "type": "stage",
            "service_request_id": service_request_id,
            "stage": stage,
            "t_mono_ms": round(time.monotonic() * 1000.0, 3),
            "timestamp_ms": int(time.time() * 1000),
        }
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        with self._lock:
            self._ring.append(rec)
            self._emitted += 1

    def append(self, rec: Dict[str, Any]) -> None:
        """Mirror an externally-stamped record (e.g. the master tracer's
        stage hook) into the ring without re-stamping clocks."""
        with self._lock:
            self._ring.append(rec)
            self._emitted += 1

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def for_request(self, service_request_id: str) -> List[Dict[str, Any]]:
        """Spans whose wire id matches `service_request_id` by BASE id:
        attempt-versioned ids (`srid#rN`) collapse onto the service id so
        one collector query sees every attempt."""
        base = str(service_request_id).split("#", 1)[0]
        with self._lock:
            return [
                r
                for r in self._ring
                if str(r.get("service_request_id", "")).split("#", 1)[0] == base
            ]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "process": self.process,
                "capacity": self.capacity,
                "size": len(self._ring),
                "emitted": self._emitted,
            }


class FlightRecorder:
    """Dumps a SpanRing to disk when an anomaly trigger fires.

    Dump files are `flight-<seq>.json` under `directory`, rotation keeps
    the newest `keep`, and triggers inside `min_interval_s` of the last
    dump only count (xllm_flight_dumps_total{reason=...} still ticks) so
    a breaker flapping at line rate can't turn the recorder into its own
    disk DoS. All failures are swallowed: the recorder must never add a
    failure mode to the path it is recording.
    """

    def __init__(
        self,
        ring: SpanRing,
        directory: str,
        keep: int = 8,
        min_interval_s: float = 5.0,
        registry: Optional[Any] = None,
    ) -> None:
        self.ring = ring
        self.directory = directory
        self.keep = max(int(keep), 1)
        self.min_interval_s = float(min_interval_s)
        self._lock = threading.Lock()
        self._last_dump_mono = float("-inf")
        self._seq = 0
        self._m_dumps = (
            registry.counter(
                "xllm_flight_dumps_total",
                "Flight-recorder anomaly triggers by reason",
                labelnames=("reason",),
            )
            if registry is not None
            else None
        )

    def trigger(self, reason: str, service_request_id: str = "", **ctx: Any) -> Optional[str]:
        """Record an anomaly; dump the ring unless rate-limited.

        Returns the dump path when a file was written, else None. Never
        raises."""
        try:
            if self._m_dumps is not None:
                self._m_dumps.labels(reason=reason).inc()
            self.ring.emit(
                service_request_id, "flight_dump", reason=reason, **ctx
            )
            now = time.monotonic()
            with self._lock:
                if now - self._last_dump_mono < self.min_interval_s:
                    return None
                self._last_dump_mono = now
                self._seq += 1
                seq = self._seq
            return self._dump(reason, service_request_id, ctx, seq)
        except Exception:
            return None

    def _dump(
        self, reason: str, srid: str, ctx: Dict[str, Any], seq: int
    ) -> Optional[str]:
        os.makedirs(self.directory, exist_ok=True)
        path = os.path.join(self.directory, "flight-%06d.json" % seq)
        body = {
            "reason": reason,
            "service_request_id": srid,
            "context": ctx,
            "timestamp_ms": int(time.time() * 1000),
            "ring": self.ring.stats(),
            "spans": self.ring.snapshot(),
        }
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(body, f)
        os.replace(tmp, path)
        self._prune()
        return path

    def _prune(self) -> None:
        try:
            dumps = sorted(
                n
                for n in os.listdir(self.directory)
                if n.startswith("flight-") and n.endswith(".json")
            )
            for stale in dumps[: -self.keep]:
                try:
                    os.remove(os.path.join(self.directory, stale))
                except OSError:
                    pass
        except OSError:
            pass
