// MurmurHash3 x64_128 + chained KV-block hashing, exposed with a C ABI for
// ctypes. Implemented fresh from Austin Appleby's public-domain algorithm
// description; behaviorally equivalent to the reference's smhasher dependency
// (reference: xllm_service/common/hash_util.cpp:18-44 for the chaining
// contract: hash_i = murmur3_x64_128(prev_hash_16B || int32_le_tokens, seed)).
//
// Build: g++ -O2 -shared -fPIC -o libxllm_native.so murmur3.cpp

#include <cstdint>
#include <cstring>

namespace {

inline uint64_t rotl64(uint64_t x, int8_t r) {
  return (x << r) | (x >> (64 - r));
}

inline uint64_t fmix64(uint64_t k) {
  k ^= k >> 33;
  k *= 0xff51afd7ed558ccdULL;
  k ^= k >> 33;
  k *= 0xc4ceb9fe1a85ec53ULL;
  k ^= k >> 33;
  return k;
}

inline uint64_t load64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, sizeof(v));  // little-endian hosts only (x86/arm)
  return v;
}

void murmur3_x64_128(const void* key, int len, uint32_t seed, void* out) {
  const uint8_t* data = static_cast<const uint8_t*>(key);
  const int nblocks = len / 16;

  uint64_t h1 = seed;
  uint64_t h2 = seed;

  const uint64_t c1 = 0x87c37b91114253d5ULL;
  const uint64_t c2 = 0x4cf5ad432745937fULL;

  for (int i = 0; i < nblocks; i++) {
    uint64_t k1 = load64(data + i * 16);
    uint64_t k2 = load64(data + i * 16 + 8);

    k1 *= c1; k1 = rotl64(k1, 31); k1 *= c2; h1 ^= k1;
    h1 = rotl64(h1, 27); h1 += h2; h1 = h1 * 5 + 0x52dce729;
    k2 *= c2; k2 = rotl64(k2, 33); k2 *= c1; h2 ^= k2;
    h2 = rotl64(h2, 31); h2 += h1; h2 = h2 * 5 + 0x38495ab5;
  }

  const uint8_t* tail = data + nblocks * 16;
  uint64_t k1 = 0;
  uint64_t k2 = 0;

  switch (len & 15) {
    case 15: k2 ^= static_cast<uint64_t>(tail[14]) << 48; [[fallthrough]];
    case 14: k2 ^= static_cast<uint64_t>(tail[13]) << 40; [[fallthrough]];
    case 13: k2 ^= static_cast<uint64_t>(tail[12]) << 32; [[fallthrough]];
    case 12: k2 ^= static_cast<uint64_t>(tail[11]) << 24; [[fallthrough]];
    case 11: k2 ^= static_cast<uint64_t>(tail[10]) << 16; [[fallthrough]];
    case 10: k2 ^= static_cast<uint64_t>(tail[9]) << 8; [[fallthrough]];
    case 9:
      k2 ^= static_cast<uint64_t>(tail[8]);
      k2 *= c2; k2 = rotl64(k2, 33); k2 *= c1; h2 ^= k2;
      [[fallthrough]];
    case 8: k1 ^= static_cast<uint64_t>(tail[7]) << 56; [[fallthrough]];
    case 7: k1 ^= static_cast<uint64_t>(tail[6]) << 48; [[fallthrough]];
    case 6: k1 ^= static_cast<uint64_t>(tail[5]) << 40; [[fallthrough]];
    case 5: k1 ^= static_cast<uint64_t>(tail[4]) << 32; [[fallthrough]];
    case 4: k1 ^= static_cast<uint64_t>(tail[3]) << 24; [[fallthrough]];
    case 3: k1 ^= static_cast<uint64_t>(tail[2]) << 16; [[fallthrough]];
    case 2: k1 ^= static_cast<uint64_t>(tail[1]) << 8; [[fallthrough]];
    case 1:
      k1 ^= static_cast<uint64_t>(tail[0]);
      k1 *= c1; k1 = rotl64(k1, 31); k1 *= c2; h1 ^= k1;
  }

  h1 ^= static_cast<uint64_t>(len);
  h2 ^= static_cast<uint64_t>(len);
  h1 += h2;
  h2 += h1;
  h1 = fmix64(h1);
  h2 = fmix64(h2);
  h1 += h2;
  h2 += h1;

  std::memcpy(static_cast<uint8_t*>(out), &h1, 8);
  std::memcpy(static_cast<uint8_t*>(out) + 8, &h2, 8);
}

}  // namespace

extern "C" {

// Hash one buffer.
void xllm_murmur3_x64_128(const void* key, int len, uint32_t seed, void* out) {
  murmur3_x64_128(key, len, seed, out);
}

// Chained block hash: out = murmur3(prev_hash(16B, may be null) ||
// int32_le(token_ids), seed). Mirrors hash_util.cpp:18-44.
void xllm_block_hash(const uint8_t* prev_hash,
                     const int32_t* token_ids,
                     int num_tokens,
                     uint32_t seed,
                     uint8_t* out) {
  if (prev_hash == nullptr) {
    murmur3_x64_128(token_ids, num_tokens * 4, seed, out);
    return;
  }
  // 16-byte prev hash + tokens; stack for typical block sizes, heap beyond.
  uint8_t stack_buf[16 + 8192 * 4];
  const size_t need = 16 + static_cast<size_t>(num_tokens) * 4;
  uint8_t* buf = need <= sizeof(stack_buf) ? stack_buf : new uint8_t[need];
  std::memcpy(buf, prev_hash, 16);
  std::memcpy(buf + 16, token_ids, static_cast<size_t>(num_tokens) * 4);
  murmur3_x64_128(buf, static_cast<int>(need), seed, out);
  if (buf != stack_buf) delete[] buf;
}

// Full prefix walk: hash every complete block of `block_size` tokens,
// chaining. Writes num_blocks*16 bytes into out; returns num_blocks.
int xllm_prefix_block_hashes(const int32_t* token_ids,
                             int num_tokens,
                             int block_size,
                             uint32_t seed,
                             uint8_t* out) {
  int num_blocks = num_tokens / block_size;
  const uint8_t* prev = nullptr;
  for (int b = 0; b < num_blocks; ++b) {
    xllm_block_hash(prev, token_ids + b * block_size, block_size, seed,
                    out + b * 16);
    prev = out + b * 16;
  }
  return num_blocks;
}

}  // extern "C"
