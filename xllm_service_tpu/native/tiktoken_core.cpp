// Native tiktoken-style BPE merge core.
//
// The reference ships a native tiktoken tokenizer
// (xllm_service/tokenizer/tiktoken_tokenizer.{h,cpp}: base64 vocab file,
// re2 pre-tokenization, rank-ordered byte-pair merging). This is the
// rebuild's equivalent core: the merge loop over one pre-tokenized word,
// where a pair is mergeable iff the concatenated byte string exists in
// the vocab and pairs merge in ascending RANK order (tiktoken semantics —
// no merges list; the vocab ranks ARE the merge priorities). The Python
// wrapper (tokenizer/native_tiktoken.py) parses the base64 vocab file,
// runs the unicode regex split (the `regex` module speaks \p{L}; the
// same division of labor as native_bpe), and handles special tokens.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 tiktoken_core.cpp -o libxllm_tk.so

#include <cstdint>
#include <cstring>
#include <limits>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Vocab {
  std::unordered_map<std::string, int32_t> rank;  // bytes -> id (== rank)
  std::vector<std::string> pieces;                // id -> bytes
  size_t max_piece_len = 1;
};

}  // namespace

extern "C" {

void* tk_create() { return new Vocab(); }

void tk_destroy(void* h) { delete static_cast<Vocab*>(h); }

namespace {

int64_t lookup_rank(const Vocab& v, const uint8_t* bytes, size_t a, size_t b) {
  if (b - a > v.max_piece_len) return std::numeric_limits<int64_t>::max();
  std::string s(reinterpret_cast<const char*>(bytes) + a, b - a);
  auto it = v.rank.find(s);
  return it == v.rank.end() ? std::numeric_limits<int64_t>::max()
                            : int64_t(it->second);
}

}  // namespace

// Register one vocab entry (raw bytes + its id/rank). Entries may arrive
// in any order; ids need not be dense.
void tk_add(void* h, const uint8_t* bytes, int64_t len, int32_t id) {
  auto& v = *static_cast<Vocab*>(h);
  std::string s(reinterpret_cast<const char*>(bytes), size_t(len));
  if (size_t(id) >= v.pieces.size()) v.pieces.resize(size_t(id) + 1);
  v.pieces[size_t(id)] = s;
  v.rank.emplace(std::move(s), id);
  v.max_piece_len = std::max(v.max_piece_len, size_t(len));
}

// Encode ONE pre-tokenized word (raw bytes). Returns token count, or
// -needed if out too small, or INT32_MIN when a single byte is missing
// from the vocab (malformed vocab — tiktoken vocabs carry all 256).
int tk_encode_word(void* h, const uint8_t* bytes, int64_t len, int32_t* out,
                   int max_out) {
  auto& v = *static_cast<Vocab*>(h);
  if (len <= 0) return 0;
  // Whole-word fast path (common for frequent words and special-cased
  // single-byte words).
  {
    std::string whole(reinterpret_cast<const char*>(bytes), size_t(len));
    auto it = v.rank.find(whole);
    if (it != v.rank.end()) {
      if (max_out < 1) return -1;
      out[0] = it->second;
      return 1;
    }
  }
  constexpr int64_t kMax = std::numeric_limits<int64_t>::max();
  // parts[i] = (start offset of symbol i, rank of merging symbols i and
  // i+1). Only the pairs ADJACENT to a merge change rank, so each merge
  // recomputes two entries instead of rescanning the word (tiktoken's
  // byte_pair_merge shape — a 10k-char punctuation run stays O(n^2)
  // worst in the erase, not O(n^2) hash lookups).
  struct Part { int32_t start; int64_t rank; };
  std::vector<Part> parts(size_t(len) + 1);
  for (int64_t i = 0; i <= len; i++) parts[size_t(i)] = {int32_t(i), kMax};
  for (size_t i = 0; i + 2 < parts.size(); i++)
    parts[i].rank = lookup_rank(v, bytes, size_t(parts[i].start),
                                size_t(parts[i + 2].start));

  while (parts.size() > 2) {
    int64_t best = kMax;
    size_t best_i = 0;
    for (size_t i = 0; i + 1 < parts.size(); i++) {
      if (parts[i].rank < best) {
        best = parts[i].rank;
        best_i = i;
      }
    }
    if (best == kMax) break;
    parts.erase(parts.begin() + long(best_i) + 1);
    parts[best_i].rank =
        best_i + 2 < parts.size()
            ? lookup_rank(v, bytes, size_t(parts[best_i].start),
                          size_t(parts[best_i + 2].start))
            : kMax;
    if (best_i > 0)
      parts[best_i - 1].rank =
          best_i + 1 < parts.size()
              ? lookup_rank(v, bytes, size_t(parts[best_i - 1].start),
                            size_t(parts[best_i + 1].start))
              : kMax;
  }

  int count = int(parts.size()) - 1;
  if (count > max_out) return -count;
  for (int i = 0; i < count; i++) {
    size_t a = size_t(parts[size_t(i)].start);
    size_t b = size_t(parts[size_t(i) + 1].start);
    std::string s(reinterpret_cast<const char*>(bytes) + a, b - a);
    auto it = v.rank.find(s);
    if (it == v.rank.end()) return std::numeric_limits<int32_t>::min();
    out[i] = it->second;
  }
  return count;
}

// Decode ids into the out buffer; returns byte length or -needed.
int tk_decode(void* h, const int32_t* ids, int n, uint8_t* out, int max_out) {
  auto& v = *static_cast<Vocab*>(h);
  size_t total = 0;
  for (int i = 0; i < n; i++) {
    int32_t id = ids[i];
    if (id < 0 || size_t(id) >= v.pieces.size()) continue;
    total += v.pieces[size_t(id)].size();
  }
  if (total > size_t(max_out)) return -int(total);
  size_t off = 0;
  for (int i = 0; i < n; i++) {
    int32_t id = ids[i];
    if (id < 0 || size_t(id) >= v.pieces.size()) continue;
    const std::string& p = v.pieces[size_t(id)];
    std::memcpy(out + off, p.data(), p.size());
    off += p.size();
  }
  return int(off);
}

// id of an exact byte string, or -1.
int tk_token_to_id(void* h, const uint8_t* bytes, int64_t len) {
  auto& v = *static_cast<Vocab*>(h);
  std::string s(reinterpret_cast<const char*>(bytes), size_t(len));
  auto it = v.rank.find(s);
  return it == v.rank.end() ? -1 : it->second;
}

// bytes of an id; returns length or -needed or -1 for unknown id.
int tk_id_to_token(void* h, int32_t id, uint8_t* out, int max_out) {
  auto& v = *static_cast<Vocab*>(h);
  if (id < 0 || size_t(id) >= v.pieces.size()) return -1;
  const std::string& p = v.pieces[size_t(id)];
  if (int(p.size()) > max_out) return -int(p.size());
  std::memcpy(out, p.data(), p.size());
  return int(p.size());
}

}  // extern "C"
