// Native byte-level BPE tokenizer core (C ABI, ctypes-bound).
//
// The reference ships three NATIVE tokenizer implementations (a Rust
// HF-tokenizers FFI crate, sentencepiece_tokenizer.cpp, and
// tiktoken_tokenizer.cpp — reference xllm_service/tokenizer/); this is the
// TPU rebuild's native equivalent: the BPE merge loop and vocab tables —
// the per-request hot path the service tier runs on every schedule() —
// live here, while JSON model parsing and unicode regex pre-tokenization
// stay in the Python wrapper (tokenizer/native_bpe.py), mirroring how the
// reference's Rust crate delegates model parsing to the hf-tokenizers
// library.
//
// Algorithm: classic lowest-rank-first pair merging over byte-level
// initial symbols, with an unordered word cache (HF tokenizers does the
// same) guarded by a mutex for concurrent service threads.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 bpe_tokenizer.cpp -o libxllm_bpe.so
// (tokenizer/native_bpe.py rebuilds on demand when the .cpp is newer).

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct PairHash {
  size_t operator()(const std::pair<int32_t, int32_t>& p) const {
    return std::hash<uint64_t>()(
        (static_cast<uint64_t>(static_cast<uint32_t>(p.first)) << 32) |
        static_cast<uint32_t>(p.second));
  }
};

struct Bpe {
  // id -> token bytes (decode table).
  std::vector<std::string> id_to_bytes;
  // raw byte value -> initial symbol id.
  int32_t byte_to_id[256];
  // (left_id, right_id) -> {rank, merged_id}; lower rank merges first.
  std::unordered_map<std::pair<int32_t, int32_t>,
                     std::pair<int32_t, int32_t>, PairHash>
      merges;

  std::mutex cache_mu;
  std::unordered_map<std::string, std::vector<int32_t>> word_cache;
  size_t cache_cap = 1 << 16;

  void encode_word(const char* data, int n, std::vector<int32_t>* out) {
    out->clear();
    if (n <= 0) return;
    std::string key(data, n);
    {
      std::lock_guard<std::mutex> g(cache_mu);
      auto it = word_cache.find(key);
      if (it != word_cache.end()) {
        *out = it->second;
        return;
      }
    }
    std::vector<int32_t>& ids = *out;
    ids.reserve(n);
    for (int i = 0; i < n; ++i) {
      int32_t id = byte_to_id[static_cast<uint8_t>(data[i])];
      if (id < 0) continue;  // byte with no token (malformed vocab): drop
      ids.push_back(id);
    }
    // Lowest-rank-first merge loop. Each pass scans for the best pair and
    // merges ALL its occurrences; word lengths are pre-tokenized short
    // (a handful of symbols), so the quadratic bound is irrelevant.
    while (ids.size() >= 2) {
      int32_t best_rank = INT32_MAX, best_pos = -1, best_new = -1;
      for (size_t i = 0; i + 1 < ids.size(); ++i) {
        auto it = merges.find({ids[i], ids[i + 1]});
        if (it != merges.end() && it->second.first < best_rank) {
          best_rank = it->second.first;
          best_pos = static_cast<int32_t>(i);
          best_new = it->second.second;
        }
      }
      if (best_pos < 0) break;
      // Merge every non-overlapping occurrence of this exact pair (same
      // semantics as HF: the chosen merge applies across the word).
      int32_t l = ids[best_pos], r = ids[best_pos + 1];
      std::vector<int32_t> next;
      next.reserve(ids.size());
      for (size_t i = 0; i < ids.size();) {
        if (i + 1 < ids.size() && ids[i] == l && ids[i + 1] == r) {
          next.push_back(best_new);
          i += 2;
        } else {
          next.push_back(ids[i]);
          i += 1;
        }
      }
      ids.swap(next);
    }
    std::lock_guard<std::mutex> g(cache_mu);
    if (word_cache.size() >= cache_cap) word_cache.clear();
    word_cache.emplace(std::move(key), ids);
  }
};

}  // namespace

extern "C" {

void* xbpe_new(int32_t vocab_size) {
  auto* b = new Bpe();
  b->id_to_bytes.resize(vocab_size);
  std::memset(b->byte_to_id, 0xff, sizeof(b->byte_to_id));
  return b;
}

void xbpe_free(void* p) { delete static_cast<Bpe*>(p); }

// Register a token's raw bytes under its id (decode table).
int xbpe_set_token(void* p, int32_t id, const char* bytes, int32_t n) {
  auto* b = static_cast<Bpe*>(p);
  if (id < 0 || id >= static_cast<int32_t>(b->id_to_bytes.size())) return -1;
  b->id_to_bytes[id].assign(bytes, n);
  return 0;
}

void xbpe_set_byte_token(void* p, int32_t byte, int32_t id) {
  auto* b = static_cast<Bpe*>(p);
  if (byte >= 0 && byte < 256) b->byte_to_id[byte] = id;
}

void xbpe_add_merge(void* p, int32_t left, int32_t right, int32_t merged,
                    int32_t rank) {
  auto* b = static_cast<Bpe*>(p);
  b->merges[{left, right}] = {rank, merged};
}

// Encode one pre-tokenized word's raw bytes. Returns the id count (may
// exceed max_out — caller retries with a bigger buffer).
int32_t xbpe_encode_word(void* p, const char* data, int32_t n,
                         int32_t* out_ids, int32_t max_out) {
  auto* b = static_cast<Bpe*>(p);
  std::vector<int32_t> ids;
  b->encode_word(data, n, &ids);
  int32_t count = static_cast<int32_t>(ids.size());
  for (int32_t i = 0; i < count && i < max_out; ++i) out_ids[i] = ids[i];
  return count;
}

// Concatenate token bytes. Returns byte count (may exceed cap — caller
// retries with a bigger buffer).
int32_t xbpe_decode(void* p, const int32_t* ids, int32_t n, char* out,
                    int32_t cap) {
  auto* b = static_cast<Bpe*>(p);
  int32_t total = 0;
  for (int32_t i = 0; i < n; ++i) {
    if (ids[i] < 0 || ids[i] >= static_cast<int32_t>(b->id_to_bytes.size()))
      continue;
    const std::string& s = b->id_to_bytes[ids[i]];
    if (total + static_cast<int32_t>(s.size()) <= cap)
      std::memcpy(out + total, s.data(), s.size());
    total += static_cast<int32_t>(s.size());
  }
  return total;
}

}  // extern "C"
